"""photon_tpu.pilot — the always-on train→validate→promote→rollback loop.

Photon-ML's photon-client layer is a human-driven batch driver; this is
that surface rebuilt as a production control loop (ROADMAP item 4). The
``Pilot`` watches a shard directory and, per cycle: freezes the shard
snapshot, streams it in through ``data/stream.py`` (bounded memory,
integrity manifest, resumable cursor), warm-start retrains from the
live generation under the PR-7 training checkpointer, gates promotion
on the evaluation suite versus the CURRENTLY-SERVING model, hot-reloads
the live scorer through ``MicroBatchQueue.reload_model`` (values-only:
zero recompiles; structure change: off-path ladder rebuild under
quiesce), then OBSERVES post-promotion SLO burn and auto-rolls back to
the previous ring generation when it crosses the declared threshold.

Robustness is the headline, not the garnish:

- **Atomic state machine** — every IDLE→INGEST→TRAIN→VALIDATE→PROMOTE→
  OBSERVE transition commits ``pilot-state.json`` through
  ``atomic_write_bytes``; a killed pilot resumes exactly at the
  committed stage (``pilot/state.py``).
- **Stage retry + deadlines** — each stage runs under
  ``resilience.retry`` behind its own seeded fault point
  (``pilot.ingest`` / ``pilot.train`` / ``pilot.validate`` /
  ``pilot.promote`` / ``pilot.rollback``); a stage exceeding its
  declared deadline is recorded as an overrun and counts toward
  degradation.
- **Degrade, never die** — consecutive failed (or overrun) cycles back
  off exponentially and, past ``max_consecutive_failures``, drop the
  pilot to SERVE-ONLY mode: the live scorer keeps serving the last
  good generation while the trainer is wedged; ``reset_serve_only()``
  re-arms after the operator intervenes.
- **Bounded rollback inventory** — ``pilot/ring.py`` keeps the newest N
  generations on disk; promotion is a two-step staged→live commit so a
  kill between the generation write and the ``reload()`` leaves the
  server on the old generation and the promotion resumable.
- **Every bad outcome leaves evidence** — refusals record their
  per-metric reasons in the state file, and refusals AND rollbacks dump
  a flight-recorder post-mortem (``obs/flight.py``).

Vocabulary pinning: by default the first cycle's scanned vocabulary is
committed (``pilot-vocab.json``) and reused by every later cycle, so
day-over-day retrains keep table shapes — and therefore the compiled
score ladder — fixed (the zero-recompile promotion the tier-2 ``pilot``
contract audits). Unpinned runs still work: a grown vocabulary is a
structure change and promotes through the quiesced rebuild instead.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time

from photon_tpu.pilot.ring import GenerationRing
from photon_tpu.pilot.state import (
    MODE_ACTIVE,
    MODE_SERVE_ONLY,
    STAGES,
    PilotState,
    commit_state,
    load_state,
)

logger = logging.getLogger(__name__)

# Program contract (audited by `python -m photon_tpu.analysis
# --semantic`; builder build_pilot in analysis/program.py): one full
# promotion cycle against a live score ladder — values-only reload via
# the same ``reload_model`` path the pilot's PROMOTE stage drives — must
# add ZERO serving programs: the census stays at the ladder's rung count
# and every post-promotion trace is byte-identical to its rung's base
# program (stable_under=promotion_cycle). The control loop is host
# machinery; promoting a model may never perturb what XLA compiles.
PROGRAM_AUDIT = dict(
    name="pilot",
    entry="pilot.loop promotion cycle -> serve score ladder "
    "(reload_model values-only swap)",
    builder="build_pilot",
    max_programs=2,
    stable_under=("promotion_cycle",),
    hot_loop=True,
)

_VOCAB_FILE = "pilot-vocab.json"


@dataclasses.dataclass(frozen=True)
class PromotionGate:
    """Candidate-vs-serving promotion policy.

    ``min_delta`` maps metric name -> required improvement IN THE
    METRIC'S BETTER DIRECTION (so +0.01 on RMSE means "at least 0.01
    LOWER"); negative values grant a regression allowance. Metrics not
    named require ``>= 0`` improvement only if ``require_primary`` and
    they are the primary metric; others are recorded but not gating.
    The very first generation (no incumbent) auto-passes.
    """

    min_delta: dict = dataclasses.field(default_factory=dict)
    require_primary: bool = True

    def decide(self, specs, candidate: dict, incumbent: dict) -> list[str]:
        """Refusal reasons (empty = promote)."""
        reasons = []
        by_name = {s.name: s for s in specs}
        gated = dict(self.min_delta)
        if self.require_primary and specs:
            gated.setdefault(specs[0].name, 0.0)
        for metric, need in gated.items():
            spec = by_name.get(metric)
            if spec is None or metric not in candidate \
                    or metric not in incumbent:
                reasons.append(
                    f"{metric}: gated metric not evaluated "
                    f"(have {sorted(candidate)})")
                continue
            sign = 1.0 if spec.bigger_is_better else -1.0
            improvement = sign * (candidate[metric] - incumbent[metric])
            if improvement < need:
                reasons.append(
                    f"{metric}: improvement {improvement:+.6g} < "
                    f"required {need:+.6g} (candidate "
                    f"{candidate[metric]:.6g} vs serving "
                    f"{incumbent[metric]:.6g})")
        return reasons


@dataclasses.dataclass(frozen=True)
class ObservePolicy:
    """Post-promotion observation window + rollback triggers."""

    window_s: float = 2.0
    poll_s: float = 0.25
    # Any of these crossing rolls the promotion back:
    max_dispatch_errors: int = 0  # dispatch-error DELTA over the window
    max_error_burn: float = 0.0  # SLO error-budget short-window burn
    rollback_on_breaker: bool = True


@dataclasses.dataclass(frozen=True)
class PilotConfig:
    """Everything the control loop needs, declared once."""

    stream_dir: str
    work_dir: str
    estimator_factory: object  # () -> GameEstimator
    # Optional HELD-OUT validation shard directory: when set, the
    # promotion gate scores candidate and incumbent on THIS data
    # (streamed each cycle under the pinned vocabulary) instead of the
    # candidate's own training data. Without it the gate compares
    # in-sample — operationally useful (a broken retrain still refuses)
    # but biased toward promotion for overfit candidates; production
    # pilots should point this at a holdout stream.
    validation_dir: str | None = None
    window_shards: int = 1
    keep_generations: int = 3
    # Per-cycle work dirs (ingest spills, training checkpoints, the
    # candidate npz) kept on disk after a cycle completes — the bounded
    # companion to the generation ring's retention.
    keep_cycle_dirs: int = 2
    gate: PromotionGate = dataclasses.field(default_factory=PromotionGate)
    observe: ObservePolicy = dataclasses.field(
        default_factory=ObservePolicy)
    # Per-stage soft deadlines, seconds (stage name lower-cased ->
    # budget; a finished stage past its budget is an OVERRUN: recorded,
    # counted toward degradation, but its work is kept — discarding a
    # completed retrain because it was slow would burn the cycle twice).
    stage_deadline_s: dict = dataclasses.field(default_factory=dict)
    max_consecutive_failures: int = 3
    backoff_base_s: float = 1.0
    backoff_cap_s: float = 60.0
    retry: object = None  # resilience.RetryPolicy | None (default policy)
    pin_vocabulary: bool = True
    ingest_kwargs: dict = dataclasses.field(default_factory=dict)
    # Model/data-health promotion gates (obs/health.py
    # ``HealthGatePolicy`` | None = off). When set, the pilot ARMS the
    # health layer: every cycle's ingest is sketched, the VALIDATE
    # stage scores drift (this cycle vs the last PROMOTED cycle's
    # committed sketch), train/serve skew (vs the queue's request tap),
    # candidate calibration, coefficient movement vs the serving
    # generation, and the fit's numerics sentinels — and any violation
    # REFUSES the promotion with recorded ``health:*`` reasons through
    # the same refusal machinery as the metric gate (state file +
    # flight post-mortem). PILOT.md documents the knobs.
    health: object = None  # obs.health.HealthGatePolicy | None


class Pilot:
    """The supervisor. Single-threaded by design: the one control
    thread runs stages in order and commits each transition; all
    serving concurrency stays inside the queue it supervises."""

    def __init__(self, config: PilotConfig, *, server=None,
                 server_factory=None):
        self.config = config
        self.server = server
        self.server_factory = server_factory
        os.makedirs(config.work_dir, exist_ok=True)
        self.ring = GenerationRing(
            os.path.join(config.work_dir, "generations"),
            keep=config.keep_generations,
        )
        self.state = load_state(config.work_dir) or PilotState()
        if config.health is not None:
            # Health gates need the layer armed: ingest sketching, the
            # serve tap, and the fused fit's numerics sentinels all key
            # off the one obs.health flag (host-only; the audited
            # `health` contract pins zero traced-program impact).
            from photon_tpu.obs import health

            health.enable()
        self._commit()

    # -- plumbing ----------------------------------------------------------

    def _commit(self) -> None:
        commit_state(self.config.work_dir, self.state)
        self._export_gauges()

    def _cycle_dir(self, cycle: int | None = None) -> str:
        c = self.state.cycle if cycle is None else cycle
        return os.path.join(self.config.work_dir, f"cycle-{c:05d}")

    def _retry_policy(self):
        from photon_tpu.resilience.retry import DEFAULT_POLICY

        return self.config.retry or DEFAULT_POLICY

    def _stage_run(self, stage: str, point: str, fn):
        """One stage body: fault point + transient retry inside,
        deadline bookkeeping outside. Returns ``fn()``'s result."""
        from photon_tpu.resilience import retry

        t0 = time.monotonic()
        out = retry.retrying_check(
            point, fn, site=point, policy=self._retry_policy()
        )
        took = time.monotonic() - t0
        budget = self.config.stage_deadline_s.get(stage.lower())
        if budget is not None and took > budget:  # photon: ignore[spmd-host-divergence] -- host-side deadline/degrade control; selects retry posture, not which program is traced
            self.state.deadline_overruns += 1
            self.state.consecutive_failures += 1
            self._maybe_degrade(
                f"stage {stage} overran its {budget:g}s deadline "
                f"({took:.3f}s)")
            self._commit()
            logger.warning(
                "pilot: stage %s finished but overran its deadline "
                "(%.3fs > %gs) — counted toward degradation",
                stage, took, budget)
        return out

    def _maybe_degrade(self, why: str) -> None:
        if (
            self.state.mode == MODE_ACTIVE
            and self.state.consecutive_failures
            >= self.config.max_consecutive_failures
        ):
            self.state.mode = MODE_SERVE_ONLY
            self.state.last_error = why
            logger.error(
                "pilot: %d consecutive failure(s) — degrading to "
                "SERVE-ONLY mode (the live scorer keeps serving; "
                "reset_serve_only() re-arms the trainer): %s",
                self.state.consecutive_failures, why)

    def reset_serve_only(self) -> None:
        """Operator action: re-arm a pilot that degraded to serve-only."""
        self.state.mode = MODE_ACTIVE
        self.state.consecutive_failures = 0
        self._commit()

    def backoff_s(self) -> float:
        """Suggested sleep before the next cycle attempt (exponential in
        the consecutive-failure count, capped)."""
        n = self.state.consecutive_failures
        if n <= 0:
            return 0.0
        return min(
            self.config.backoff_base_s * (2.0 ** (n - 1)),
            self.config.backoff_cap_s,
        )

    # -- shard watching ----------------------------------------------------

    def _all_shards(self) -> list[str]:
        from photon_tpu.io.avro_data import data_shard_files

        return [
            os.path.basename(p)
            for p in data_shard_files(self.config.stream_dir)
        ]

    def pending_shards(self) -> tuple[list[str], list[str]]:
        """(all shards, shards not yet trained into a generation)."""
        all_shards = self._all_shards()
        seen = set(self.state.processed_shards)
        return all_shards, [s for s in all_shards if s not in seen]

    def _landed_at(self, names: list[str]) -> float:
        stamps = []
        for name in names:
            try:
                stamps.append(os.path.getmtime(
                    os.path.join(self.config.stream_dir, name)))
            except OSError:
                pass
        return max(stamps) if stamps else time.time()

    # -- vocabulary pin ----------------------------------------------------

    def _vocab_path(self) -> str:
        return os.path.join(self.config.work_dir, _VOCAB_FILE)

    def _pinned_vocab(self) -> dict | None:
        path = self._vocab_path()
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def _save_vocab(self, ingest) -> None:
        from photon_tpu.io.model_io import atomic_write_bytes

        payload = {
            "maps": {
                s: dict(m.items())
                for s, m in ingest.resolved_maps.items()
            },
            "id_tag_names": list(
                ingest.id_tag_names if ingest.id_tag_names != "auto"
                else ()
            ),
            "response_field": ingest.response_field,
        }
        atomic_write_bytes(
            self._vocab_path(),
            json.dumps(payload, indent=2, sort_keys=True).encode(),
        )

    # -- stages ------------------------------------------------------------

    def _vocab_kwargs(self) -> dict:
        """Ingest kwargs carrying the pinned vocabulary (or the current
        cycle's resolved one for unpinned runs — set by ``_ingest``)."""
        from photon_tpu.data.index_map import IndexMap

        kwargs = dict(self.config.ingest_kwargs)
        vocab = self._pinned_vocab() if self.config.pin_vocabulary else None
        if vocab is None:
            vocab = getattr(self, "_cycle_vocab", None)
        if vocab is not None:
            kwargs.setdefault("index_maps", {
                s: IndexMap({k: int(v) for k, v in fwd.items()})
                for s, fwd in vocab["maps"].items()
            })
            kwargs.setdefault("id_tag_names", vocab["id_tag_names"])
            kwargs.setdefault("response_field", vocab["response_field"])
        return kwargs

    def _run_ingest(self, stream_dir: str, work_name: str,
                    shard_names: list | None):
        from photon_tpu.data.stream import MANIFEST_FILE, StreamingIngest
        from photon_tpu.resilience.errors import ResumeMismatchError

        ingest_dir = os.path.join(self._cycle_dir(), work_name)
        kwargs = self._vocab_kwargs()

        def build(resume: bool):
            return StreamingIngest(
                stream_dir,
                work_dir=ingest_dir,
                shard_names=shard_names,
                window_shards=self.config.window_shards,
                resume=resume,
                **kwargs,
            )

        resume = os.path.exists(os.path.join(ingest_dir, MANIFEST_FILE))
        try:
            ingest = build(resume)
            data, stats = ingest.run()
        except ResumeMismatchError as exc:
            if not resume:
                raise
            # The interrupted attempt ran under a different ingest
            # identity (the common case: the FIRST cycle's vocabulary
            # scan committed the pin between its ingest and its crash,
            # so the resume now carries pinned maps the cursor never
            # saw). A fresh ingest under the current identity is always
            # correct — resume is an optimization, never a requirement.
            logger.warning(
                "pilot: ingest resume refused (%s); re-ingesting "
                "cycle %d %s fresh", exc, self.state.cycle, work_name)
            import shutil

            shutil.rmtree(ingest_dir, ignore_errors=True)
            ingest = build(False)
            data, stats = ingest.run()
        return data, stats, ingest

    def _ingest(self):
        had_pin = (
            self.config.pin_vocabulary
            and self._pinned_vocab() is not None
        )
        data, stats, ingest = self._run_ingest(
            self.config.stream_dir, "ingest",
            list(self.state.cycle_shards),
        )
        if self.config.pin_vocabulary and not had_pin:
            self._save_vocab(ingest)
        # This cycle's health sketch (None unless the layer is armed):
        # the VALIDATE stage's drift/skew evidence, and — on promotion
        # — the next cycle's reference (committed by _promote).
        self._cycle_sketch = getattr(ingest, "health_sketch", None)
        # The resolved vocabulary (pinned or this cycle's scan) also
        # keys the validation ingest, so a held-out set always indexes
        # features exactly as training did.
        self._cycle_vocab = {
            "maps": {
                s: dict(m.items())
                for s, m in ingest.resolved_maps.items()
            },
            "id_tag_names": list(
                ingest.id_tag_names
                if ingest.id_tag_names != "auto" else ()
            ),
            "response_field": ingest.response_field,
        }
        return data, stats

    def _validation_data(self):
        """The held-out validation dataset for this cycle, or None when
        ``validation_dir`` is unset (the gate then compares in-sample —
        see PilotConfig.validation_dir)."""
        if self.config.validation_dir is None:
            return None
        data, _, _ = self._run_ingest(
            self.config.validation_dir, "validate-ingest", None
        )
        return data

    def _candidate_path(self) -> str:
        return os.path.join(self._cycle_dir(), "candidate.npz")

    def _train(self, data):
        """Warm-start retrain under the training checkpointer; commits
        the candidate npz so VALIDATE/PROMOTE resumes never retrain."""
        from photon_tpu.io.model_io import load_checkpoint, save_checkpoint
        from photon_tpu.resilience.checkpoint import (
            TrainingCheckpointer,
            load_training_checkpoint,
            training_static_key,
        )

        cand_path = self._candidate_path()
        if os.path.exists(cand_path):
            # A prior attempt finished TRAIN and committed the
            # candidate before dying mid-transition: keep its work.
            return load_checkpoint(cand_path), self._init_model()
        est = self.config.estimator_factory()
        init = self._init_model()
        ckpt_dir = os.path.join(self._cycle_dir(), "train")
        key = training_static_key(est, None)
        resume = None
        if os.path.exists(os.path.join(ckpt_dir, "manifest.json")):
            resume = load_training_checkpoint(ckpt_dir)
        checkpointer = TrainingCheckpointer(ckpt_dir, key)
        try:
            results = est.fit(
                data,
                initial_model=init,
                checkpointer=checkpointer,
                resume=resume,
            )
            model = results[0].model
        except ValueError as exc:
            # The crash window between the final iteration's checkpoint
            # + config-final retention and the candidate commit: the
            # chain says "already completed" — finalize from it.
            if resume is None or "already completed" not in str(exc):
                raise
            from photon_tpu.resilience.checkpoint import load_config_final

            model = load_config_final(ckpt_dir, 0, key)
        save_checkpoint(model, cand_path, fault_point=None)
        return model, init

    def _init_model(self):
        return (
            self.ring.load(self.ring.live)
            if self.ring.live is not None else None
        )

    def _validate(self, data, candidate, init):
        """Candidate vs serving through ONE evaluation ruler — on the
        held-out set when ``validation_dir`` is configured, else
        in-sample — plus the statistical health gates when
        ``config.health`` is set; returns (candidate_metrics,
        incumbent_metrics|None, refusal_reasons, health_block|None)."""
        from photon_tpu.evaluation.evaluators import EvaluatorSpec

        val = self._validation_data()
        if val is None:
            val = data
        est = self.config.estimator_factory()
        policy = self.config.health
        cal = sink = None
        if policy is not None and policy.max_ece is not None:
            from photon_tpu.obs import health

            pair = health.calibration_sink(est.task)
            if pair is not None:
                cal, sink = pair
        cand = est.evaluate_model(
            candidate, data, val, initial_model=init, score_sink=sink
        )
        reasons: list[str] = []
        inc_m = None
        if init is not None:
            inc = est.evaluate_model(
                init, data, val, initial_model=init
            )
            inc_m = dict(inc.evaluations)
            specs = [
                s if isinstance(s, EvaluatorSpec)
                else EvaluatorSpec.parse(s)
                for s in (est.evaluators or ())
            ] or [cand.primary_evaluator]
            reasons = self.config.gate.decide(
                specs, dict(cand.evaluations), inc_m
            )
        health_block = None
        if policy is not None:
            h_reasons, health_block = self._health_gate(
                policy, candidate, init, cal
            )
            reasons.extend(h_reasons)
        return dict(cand.evaluations), inc_m, reasons, health_block

    def _health_sketch_path(self) -> str:
        """The last PROMOTED cycle's ingest sketch — the temporal-drift
        reference the next cycle's gate compares against."""
        return os.path.join(
            self.config.work_dir, "pilot-health-sketch.json"
        )

    def _health_gate(self, policy, candidate, init, cal):
        """Score every armed health surface and apply the policy.

        Returns ``(health: reasons, block)`` where ``block`` is the
        recorded evidence (cycle report + ``state.last_health`` + the
        ``health_*`` gauges) — a refusal without its numbers would be
        an alert nobody can act on."""
        from photon_tpu.obs import health

        block: dict = {}
        drift = None
        cycle_sketch = getattr(self, "_cycle_sketch", None)
        ref_path = self._health_sketch_path()
        if cycle_sketch is not None and os.path.exists(ref_path):
            try:
                ref = health.DataSketch.load(ref_path)
                drift = health.compare(ref, cycle_sketch)
            except (OSError, ValueError, KeyError) as exc:
                # A rotted reference must not wedge the control loop:
                # the drift gate degrades (visibly) to "no reference".
                block["drift_error"] = repr(exc)
                logger.warning(
                    "pilot: health reference sketch unreadable (%s); "
                    "drift gate skipped this cycle", exc)
        skew = None
        skew_requests = 0
        if policy.max_skew_psi is not None and cycle_sketch is not None:
            serve_sk = health.serve_sketch(
                since=getattr(self, "_serve_mark", None)
            )
            skew_requests = serve_sk.rows
            if serve_sk.shards:
                skew = health.compare(cycle_sketch, serve_sk)
        ece = cal.ece() if cal is not None else None
        movement = (
            health.coefficient_movement(init, candidate)
            if init is not None else None
        )
        nonfinite = health.numerics_report(
            since_seq=getattr(self, "_sentinel_mark", 0)
        )
        scan = health.scan_model(candidate)
        reasons = policy.evaluate(
            drift=drift,
            skew=skew,
            skew_requests=skew_requests,
            ece=ece,
            movement=movement,
            nonfinite=nonfinite,
            model_scan=scan,
        )
        block.update({
            "reasons": list(reasons),
            "drift": None if drift is None else {
                "max_psi": drift["max_psi"],
                "max_ks": drift["max_ks"],
                "max_psi_surface": drift["max_psi_surface"],
            },
            "skew": None if skew is None else {
                "max_psi": skew["max_psi"],
                "max_psi_surface": skew["max_psi_surface"],
                "requests_sampled": skew_requests,
            },
            "ece": ece,
            "coefficient_movement": movement,
            "nonfinite_total": nonfinite["nonfinite_total"],
            "model_scan": list(scan),
        })
        health.record_gate(block)
        self.state.last_health = dict(block)
        return reasons, block

    def _promote(self, candidate, metrics) -> dict:
        """Two-step staged→live promotion. The ``pilot.promote`` fault
        point fires twice per clean cycle: once inside the generation
        npz's atomic-write window (ring commit can be killed mid-write)
        and once between the ring commit and the serving reload — the
        window the kill-during-promotion test aims SIGTERM at."""
        from photon_tpu.resilience import faults, retry

        gen = self.ring.staged
        if gen is None:
            gen = self.ring.stage_candidate(
                candidate, cycle=self.state.cycle, metrics=metrics
            )
        faults.check("pilot.promote")
        reload_out = {"values_only": None, "programs_compiled": 0}
        if self.server is None and self.server_factory is not None:
            self.server = self.server_factory(candidate)
            reload_out = {
                "values_only": None,
                "programs_compiled":
                    self.server.programs.stats["programs_compiled"],
            }
        elif self.server is not None:
            reload_out = retry.call_with_retry(
                lambda: self.server.reload(candidate),
                site="pilot.promote.reload",
                policy=self._retry_policy(),
            )
        self.ring.commit_live(gen)
        # Promote the cycle's ingest sketch to THE drift reference:
        # the next cycle's gate compares against the data the serving
        # model actually trained on. Committed only after the ring
        # commit (a refused or crashed promotion leaves the old
        # reference in place); a PROMOTE resumed in a fresh process
        # has no in-memory sketch and keeps the previous reference.
        sketch = getattr(self, "_cycle_sketch", None)
        if self.config.health is not None and sketch is not None:
            sketch.save(self._health_sketch_path())
        return {
            "generation": gen,
            "values_only": reload_out.get("values_only"),
            "programs_compiled": reload_out.get("programs_compiled", 0),
            "compile_events": reload_out.get("compile_events"),
            "table_generation": reload_out.get("generation"),
        }

    def _observe_baseline(self) -> dict:
        if self.server is None:
            return {}
        h = self.server.health()
        return {
            "dispatch_errors": h.get("dispatch_errors", 0),
            "requests": h.get("requests", 0),
        }

    def _burn_verdict(self, baseline: dict) -> str | None:
        """A non-None string names the rollback trigger."""
        if self.server is None:
            return None
        policy = self.config.observe
        h = self.server.health()
        # A pilot restart resets the queue's counters; rebase so stale
        # baselines from before the crash never mask (or invent) burn.
        base_err = min(
            baseline.get("dispatch_errors", 0),
            h.get("dispatch_errors", 0),
        )
        err_delta = h.get("dispatch_errors", 0) - base_err
        if policy.rollback_on_breaker and h.get("breaker_open"):
            return (
                "dispatch circuit breaker OPEN post-promotion "
                f"(after {h.get('consecutive_failures')} consecutive "
                "failures)")
        if err_delta > policy.max_dispatch_errors:
            return (
                f"{err_delta} dispatch error(s) inside the observation "
                f"window (budget {policy.max_dispatch_errors})")
        slo = h.get("slo") or {}
        err = slo.get("error_rate") or {}
        burn = err.get("burn_short") or 0.0
        if burn > policy.max_error_burn:
            return (
                f"error-rate SLO short-window burn {burn:g} > budget "
                f"{policy.max_error_burn:g}")
        return None

    def _observe(self, started_at: float, baseline: dict) -> str | None:
        """Watch the window out; returns the rollback trigger or None."""
        policy = self.config.observe
        while True:
            verdict = self._burn_verdict(baseline)
            if verdict is not None:
                return verdict
            remaining = policy.window_s - (time.time() - started_at)
            if remaining <= 0 or self.server is None:
                return None
            time.sleep(min(policy.poll_s, max(remaining, 0.01)))

    def _rollback(self, reason: str) -> dict:
        """Auto-rollback to the previous ring generation; the flight
        recorder gets a post-mortem either way."""
        from photon_tpu.obs import flight
        from photon_tpu.resilience import faults, retry

        bad = self.ring.live
        target = self.ring.previous(bad)
        if target is None:
            # Nothing older to serve: keep the current generation (a
            # degraded scorer beats no scorer) and surface loudly.
            logger.error(
                "pilot: rollback wanted (%s) but generation %s has no "
                "predecessor in the ring; keeping it live", reason, bad)
            flight.dump(f"pilot.rollback-impossible:gen-{bad}")
            return {"rolled_back": False, "reason": reason}
        faults.check("pilot.rollback")
        model = self.ring.load(target)
        if self.server is not None:
            retry.call_with_retry(
                lambda: self.server.reload(model),
                site="pilot.rollback.reload",
                policy=self._retry_policy(),
            )
            self.server.reset_breaker()
        self.ring.mark_rolled_back(bad, to=target, reason=reason)
        self.state.rollbacks += 1
        self.state.last_rollback = {
            "cycle": self.state.cycle,
            "from_generation": bad,
            "to_generation": target,
            "reason": reason,
            "at": time.time(),
        }
        flight.dump(f"pilot.rollback:gen-{bad}")
        logger.warning(
            "pilot: ROLLED BACK generation %s -> %s (%s)",
            bad, target, reason)
        return {
            "rolled_back": True, "from": bad, "to": target,
            "reason": reason,
        }

    # -- the cycle ---------------------------------------------------------

    def run_cycle(self) -> dict:
        """One supervision pass: trigger (or resume) and drive a cycle
        to IDLE. Returns a report dict; never raises for stage
        failures (they are recorded, committed, and retried with
        backoff on the next pass) — only ``InjectedCrash`` and
        BaseExceptions (signals) propagate, since they model process
        death."""
        from photon_tpu.resilience.errors import InjectedCrash

        if self.state.mode == MODE_SERVE_ONLY:
            return {
                "mode": MODE_SERVE_ONLY,
                "stage": self.state.stage,
                "last_error": self.state.last_error,
            }
        if self.state.stage == "IDLE":
            all_shards, new = self.pending_shards()
            if not new:
                self._export_gauges()
                return {"stage": "IDLE", "new_shards": 0}
            self.state.cycle += 1
            self.state.stage = "INGEST"
            self.state.cycle_shards = list(all_shards)
            self.state.new_shards = list(new)
            self.state.landed_at = self._landed_at(new)
            # Cost-ledger window for this cycle (obs/ledger.py): the
            # cycle report carries the per-(coordinate, phase, program)
            # attribution delta — None when the ledger is unarmed. The
            # mark is process-local scratch, not committed state: a
            # resumed cycle simply reports no attribution window.
            from photon_tpu.obs import ledger

            self._ledger_mark = ledger.mark()
            # Numerics-sentinel window for this cycle (obs/health.py):
            # only fits parked AFTER this mark can refuse THIS cycle's
            # promotion — a previous cycle's non-finite fit already had
            # its refusal. Process-local like the ledger mark: a
            # resumed cycle re-marks at 0 and scans everything parked
            # since the restart (conservative, never stale).
            from photon_tpu.obs import health as _health_mod

            self._sentinel_mark = (
                _health_mod.sentinel_seq()
                if self.config.health is not None else 0
            )
            # Serve-tap window for the skew gate: the comparison wants
            # THIS cycle's sampled traffic, not the process-cumulative
            # tap (a month of history dilutes a fresh shift to
            # invisibility). Process-local like the marks above; a
            # resumed cycle has no mark and conservatively compares
            # the full tap.
            self._serve_mark = (
                _health_mod.serve_mark()
                if self.config.health is not None else None
            )
            self._commit()
            logger.info(
                "pilot: cycle %d triggered by %d new shard(s)",
                self.state.cycle, len(new))
        try:
            return self._drive_cycle()
        except InjectedCrash:
            raise  # chaos 'crash' faults model process death
        except Exception as exc:  # noqa: BLE001 — the supervisor
            # outlives every failure it supervises: record, commit,
            # back off, resume at the committed stage next pass.
            self.state.failures += 1
            self.state.consecutive_failures += 1
            self.state.last_error = f"{type(exc).__name__}: {exc}"
            self._maybe_degrade(self.state.last_error)
            self._commit()
            logger.exception(
                "pilot: cycle %d failed at stage %s (failure streak "
                "%d); will resume there after backoff",
                self.state.cycle, self.state.stage,
                self.state.consecutive_failures)
            return {
                "stage": self.state.stage,
                "cycle": self.state.cycle,
                "error": self.state.last_error,
                "mode": self.state.mode,
                "backoff_s": self.backoff_s(),
            }

    def _drive_cycle(self) -> dict:
        report: dict = {"cycle": self.state.cycle}
        self._cycle_overruns_baseline = self.state.deadline_overruns
        data = None
        candidate = init = None
        stage = self.state.stage
        self.state.require_stage(*STAGES[1:])

        if stage in ("INGEST", "TRAIN", "VALIDATE"):
            data, stats = self._stage_run(
                "INGEST", "pilot.ingest", self._ingest
            )
            report["ingest"] = {
                "rows": stats["rows_ingested"],
                "quarantined": stats["shards_quarantined"],
            }
            if stage == "INGEST":
                self.state.stage = stage = "TRAIN"
                self._commit()

        if stage in ("TRAIN", "VALIDATE"):
            if stage == "TRAIN":
                candidate, init = self._stage_run(
                    "TRAIN", "pilot.train", lambda: self._train(data)
                )
                self.state.stage = stage = "VALIDATE"
                self._commit()
            else:
                # Resumed directly at VALIDATE: TRAIN committed the
                # candidate before the transition, by construction.
                from photon_tpu.io.model_io import load_checkpoint

                candidate = load_checkpoint(self._candidate_path())
                init = self._init_model()

        if stage == "VALIDATE":
            cand_m, inc_m, reasons, health_block = self._stage_run(
                "VALIDATE", "pilot.validate",
                lambda: self._validate(data, candidate, init),
            )
            report["candidate_metrics"] = cand_m
            report["serving_metrics"] = inc_m
            if health_block is not None:
                report["health"] = health_block
            if reasons:
                return self._refuse(report, reasons)
            self.state.stage = stage = "PROMOTE"
            self._commit()

        if stage == "PROMOTE":
            if candidate is None:
                from photon_tpu.io.model_io import load_checkpoint

                candidate = load_checkpoint(self._candidate_path())
            promoted = self._promote_with_deadline(candidate, report)
            report["promotion"] = promoted
            staleness = (
                time.time() - self.state.landed_at
                if self.state.landed_at else None
            )
            self.state.staleness_seconds = staleness
            self.state.promotions += 1
            self.state.last_promotion = {
                "cycle": self.state.cycle,
                "generation": promoted["generation"],
                "values_only": promoted.get("values_only"),
                "staleness_seconds": staleness,
                "at": time.time(),
            }
            report["staleness_seconds"] = staleness
            self.state.stage = stage = "OBSERVE"
            self.state.last_error = None
            self._commit()

        if stage == "OBSERVE":
            started = (self.state.last_promotion or {}).get(
                "at", time.time()
            )
            baseline = self._observe_baseline()
            verdict = self._observe(started, baseline)
            if verdict is not None:
                report["rollback"] = self._rollback(verdict)
            return self._finish_cycle(report)
        raise AssertionError(
            f"unreachable pilot stage {stage!r}")  # pragma: no cover

    def _promote_with_deadline(self, candidate, report) -> dict:
        """PROMOTE runs its fault point inline (the ring write and the
        post-stage window both fire ``pilot.promote`` themselves), so
        the stage wrapper here only adds deadline bookkeeping and
        transient retry around the reload sub-step (already wrapped)."""
        t0 = time.monotonic()
        out = self._promote(candidate, report.get("candidate_metrics"))
        took = time.monotonic() - t0
        budget = self.config.stage_deadline_s.get("promote")
        if budget is not None and took > budget:
            self.state.deadline_overruns += 1
            self.state.consecutive_failures += 1
            self._maybe_degrade(
                f"stage PROMOTE overran its {budget:g}s deadline")
            self._commit()
        return out

    def _refuse(self, report: dict, reasons: list[str]) -> dict:
        from photon_tpu.obs import flight

        self.state.refusals += 1
        self.state.last_refusal = {
            "cycle": self.state.cycle,
            "reasons": list(reasons),
            "candidate_metrics": report.get("candidate_metrics"),
            "serving_metrics": report.get("serving_metrics"),
            "at": time.time(),
        }
        report["refused"] = list(reasons)
        flight.dump(f"pilot.refusal:cycle-{self.state.cycle}")
        logger.warning(
            "pilot: cycle %d promotion REFUSED: %s",
            self.state.cycle, "; ".join(reasons))
        return self._finish_cycle(report)

    def _finish_cycle(self, report: dict) -> dict:
        """Back to IDLE: the cycle's shards are processed either way
        (a refused/rolled-back candidate still consumed the data — the
        next cycle waits for NEW shards, it does not spin on the old)."""
        clean = (
            "error" not in report
            and self.state.deadline_overruns
            == getattr(self, "_cycle_overruns_baseline", 0)
        )
        self.state.processed_shards = list(self.state.cycle_shards)
        self.state.cycle_shards = []
        self.state.new_shards = []
        self.state.stage = "IDLE"
        self.state.cycles_completed += 1
        if clean:
            self.state.consecutive_failures = 0
        self._commit()
        self._prune_cycle_dirs()
        report["stage"] = "IDLE"
        report["mode"] = self.state.mode
        from photon_tpu.obs import ledger

        mark = getattr(self, "_ledger_mark", None)
        self._ledger_mark = None
        if ledger.enabled() and mark is not None:
            # Where THIS cycle's seconds went, by (coordinate, phase,
            # program) — the same rows a flight post-mortem carries
            # cumulatively (obs/flight.py books the full ledger in
            # every dump), windowed to the cycle here. A RESUMED cycle
            # (killed after the trigger, restarted in a new process)
            # has no mark and reports no window — never the cumulative
            # process ledger masquerading as one cycle's delta.
            report["attribution"] = ledger.attribution_since(mark)
        return report

    def _prune_cycle_dirs(self) -> None:
        """Bounded disk for an always-on daemon: per-cycle work dirs
        (ingest spills, training checkpoints, the candidate npz) are
        deleted past ``keep_cycle_dirs``, AFTER the IDLE commit — the
        ring holds the durable generations; cycle dirs are debugging
        context, not recovery state, once their cycle completed."""
        import re
        import shutil

        keep = max(int(self.config.keep_cycle_dirs), 0)
        pat = re.compile(r"^cycle-(\d+)$")
        found = []
        for name in os.listdir(self.config.work_dir):
            m = pat.match(name)
            if m is not None:
                found.append((int(m.group(1)), name))
        for _, name in sorted(found)[:-keep] if keep else sorted(found):
            shutil.rmtree(
                os.path.join(self.config.work_dir, name),
                ignore_errors=True,
            )

    # -- daemon loop -------------------------------------------------------

    def run_forever(self, *, poll_interval_s: float = 5.0,
                    max_cycles: int | None = None,
                    idle_timeout_s: float | None = None,
                    should_stop=None) -> dict:
        """Poll -> cycle -> sleep, forever (or until ``max_cycles``
        promotions+refusals for CI, ``idle_timeout_s`` of no new
        shards, or ``should_stop()``). Failure backoff stretches the
        sleep; the loop itself never raises for supervised failures."""
        last_work = time.time()
        cycles = 0
        while True:
            if should_stop is not None and should_stop():
                return {"stopped": "requested", "cycles": cycles}
            report = self.run_cycle()
            if report.get("stage") == "IDLE" and "cycle" in report:
                cycles += 1
                last_work = time.time()
                if max_cycles is not None and cycles >= max_cycles:
                    return {"stopped": "max_cycles", "cycles": cycles}
            elif "error" in report:
                last_work = time.time()
            elif (
                idle_timeout_s is not None
                and time.time() - last_work > idle_timeout_s
            ):
                return {"stopped": "idle", "cycles": cycles}
            time.sleep(max(poll_interval_s, self.backoff_s())
                       if "error" in report else poll_interval_s)

    # -- observability -----------------------------------------------------

    def _export_gauges(self) -> None:
        """pilot_* registry gauges (ride /metrics via the registry
        collector; not gated on the telemetry flag — same policy as the
        stream gauges)."""
        try:
            from photon_tpu import obs

            s = self.state
            g = obs.REGISTRY.gauge
            g("pilot_promotions_total").set(s.promotions)
            g("pilot_rollbacks_total").set(s.rollbacks)
            g("pilot_refusals_total").set(s.refusals)
            g("pilot_cycles_completed_total").set(s.cycles_completed)
            g("pilot_cycle_stage").set(STAGES.index(s.stage))
            g("pilot_serve_only").set(
                1.0 if s.mode == MODE_SERVE_ONLY else 0.0)
            g("pilot_consecutive_failures").set(s.consecutive_failures)
            g("pilot_deadline_overruns_total").set(s.deadline_overruns)
            if s.staleness_seconds is not None:
                g("pilot_staleness_seconds").set(s.staleness_seconds)
            if self.ring.live is not None:
                g("pilot_generation_live").set(self.ring.live)
        except Exception:  # pragma: no cover — telemetry must never
            # alter control-loop semantics.
            logger.debug("pilot gauges unavailable", exc_info=True)

    def metrics_families(self) -> list[dict]:
        """/metrics collector (register with ``MonitorServer``): the
        labeled control-loop outcome counters and the one-hot stage
        state-set — the families the flat registry gauges CANNOT
        express. The plain gauges (``pilot_staleness_seconds``,
        ``pilot_serve_only``, ``pilot_consecutive_failures``,
        ``pilot_generation_live``, the ``*_total`` counters) already
        reach every /metrics render through ``_export_gauges`` →
        the registry collector; re-emitting them here made the two
        sources collide on the family name and the whole scrape 500
        ("duplicate metric family" — caught by the health drive's
        live-scrape check). OBSERVABILITY.md pilot rows."""
        from photon_tpu.obs import monitor

        s = self.state
        return [
            monitor.family(
                "pilot_cycle_events_total", "counter",
                "control-loop outcomes by kind",
                [
                    ("", {"kind": "promotion"}, float(s.promotions)),
                    ("", {"kind": "rollback"}, float(s.rollbacks)),
                    ("", {"kind": "refusal"}, float(s.refusals)),
                    ("", {"kind": "failure"}, float(s.failures)),
                    ("", {"kind": "deadline_overrun"},
                     float(s.deadline_overruns)),
                ],
            ),
            monitor.state_family(
                "pilot_cycle_stage_state", STAGES, s.stage,
                "one-hot pilot state-machine stage",
            ),
        ]
