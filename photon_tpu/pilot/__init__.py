"""photon_tpu.pilot — an always-on train→validate→promote→rollback
control loop that survives every failure it supervises.

The photon-client driver surface (PAPER.md layer map) rebuilt as a
supervisor daemon: watch a shard directory, stream-ingest new data,
warm-start retrain, gate promotion on the evaluation suite versus the
serving model, hot-reload the live scorer with zero recompiles, observe
post-promotion SLO burn, and auto-roll back from a bounded on-disk ring
of previous generations. State machine, stage semantics, gate and
rollback policy, metrics: PILOT.md.

Run it: ``python -m photon_tpu.cli.pilot --config pilot.yaml``.
"""

from __future__ import annotations

from photon_tpu.obs.health import HealthGatePolicy
from photon_tpu.pilot.loop import (
    PROGRAM_AUDIT,
    ObservePolicy,
    Pilot,
    PilotConfig,
    PromotionGate,
)
from photon_tpu.pilot.ring import GenerationRing
from photon_tpu.pilot.serving import PilotServer
from photon_tpu.pilot.state import (
    MODE_ACTIVE,
    MODE_SERVE_ONLY,
    STAGES,
    PilotState,
    load_state,
)

__all__ = [
    "GenerationRing",
    "HealthGatePolicy",
    "MODE_ACTIVE",
    "MODE_SERVE_ONLY",
    "ObservePolicy",
    "PROGRAM_AUDIT",
    "Pilot",
    "PilotConfig",
    "PilotServer",
    "PilotState",
    "PromotionGate",
    "STAGES",
    "load_state",
]
