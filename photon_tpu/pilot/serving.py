"""The pilot's in-process serving stack: tables + ladder + queue, swappable.

One object owns the three serving pieces (``CoefficientTables``,
``ScorePrograms``, ``MicroBatchQueue``) so the control loop has a single
handle to hot-swap (``reload``), probe (``health``), and tear down
(``close``). ``reload`` delegates to ``MicroBatchQueue.reload_model``:
values-only refreshes flip table references under live dispatch (zero
recompiles — the tier-2 ``pilot`` contract proves the static half);
structure changes compile the new ladder off-path and swap under the
queue's quiesce window. Serving is never torn down for a promotion.
"""

from __future__ import annotations

# Memory contract (audited by `python -m photon_tpu.analysis --memory`,
# machinery in analysis/memory.py): the pilot serves through the same
# ladder machinery as serve/programs, so its rungs carry the same
# per-rung budget shape; what is pilot-specific is the PROMOTION path —
# every promotion drives ``CoefficientTables.rebuild_from``, whose
# structure-changing case holds two table generations resident until
# the quiesced swap. That double-residency window is the declared
# transient allowance here.
MEMORY_AUDIT = dict(
    name="pilot-serving-memory",
    entry="pilot.serving.PilotServer (ladder + promotion reload)",
    covers=("pilot",),
    builder="build_pilot_serving_memory",
    budgets={
        "score_b*": (
            "e * s * (wbytes + 4) + d * wbytes + 120 * wbytes"
            " + rung * (d + du + 2 * s + 16) * wbytes"
        ),
    },
    transients={
        "promotion_rebuild": "2 * (d * wbytes + e * s * (wbytes + 4))",
    },
    tolerance=1.5,
)


class PilotServer:
    """Live scorer the pilot promotes into. Thin by design: all the
    concurrency lives in the queue; this object is just the bundle."""

    def __init__(
        self,
        model,
        *,
        rungs=(1, 8, 64),
        max_linger_s: float = 0.002,
        slo=None,
        breaker_threshold: int | None = None,
        queue_kwargs: dict | None = None,
    ):
        from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
        from photon_tpu.serve.queue import MicroBatchQueue
        from photon_tpu.serve.tables import CoefficientTables

        self.tables = CoefficientTables.from_game_model(model)
        self.programs = ScorePrograms(
            self.tables, ladder=ShapeLadder(tuple(rungs))
        )
        self.queue = MicroBatchQueue(
            self.programs,
            max_linger_s=max_linger_s,
            slo=slo,
            breaker_threshold=breaker_threshold,
            **(queue_kwargs or {}),
        )

    #: compile-cache events observed across every ``reload`` — the
    #: runtime half of the zero-recompile promotion claim (a values-only
    #: swap must leave it flat; the tier-2 ``pilot`` contract is the
    #: static half). Only moves while the persistent compile cache's
    #: monitoring listener is installed (``enable_compilation_cache``).
    reload_compile_events: int = 0

    def reload(self, model) -> dict:
        from photon_tpu.utils import compile_event_count

        before = compile_event_count()
        out = self.queue.reload_model(model)
        # A structure-changing swap rebuilt the ladder: track the live
        # programs object so submit-side helpers (synthetic traffic)
        # read the current generation's specs.
        self.programs = self.queue.programs
        out["compile_events"] = compile_event_count() - before
        self.reload_compile_events += out["compile_events"]
        return out

    def submit(self, features, entity_ids=None, **kw):
        return self.queue.submit(features, entity_ids, **kw)

    def health(self) -> dict:
        return self.queue.health()

    def reset_breaker(self) -> None:
        self.queue.reset_breaker()

    def close(self, timeout: float | None = None) -> bool:
        return self.queue.close(timeout)

    def __enter__(self) -> "PilotServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(self.queue.close_timeout_s)
