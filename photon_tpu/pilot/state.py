"""The pilot's durable state machine: every transition is a committed fact.

The supervisor's whole crash-safety story reduces to one rule: the ONLY
authority on where a cycle stands is ``pilot-state.json``, and it only
ever changes through the same atomic tmp+fsync+rename dance every other
durable artifact in this repo uses (``io/model_io.atomic_write_bytes``).
A killed pilot restarted against the same work dir reads the committed
stage and resumes exactly there — mid-TRAIN resumes through the
training checkpointer, mid-PROMOTE re-promotes the staged generation,
mid-OBSERVE re-opens the observation window.

Stage graph (one cycle)::

    IDLE -> INGEST -> TRAIN -> VALIDATE -> PROMOTE -> OBSERVE -> IDLE
                                  |                      |
                                  v (gate refusal)       v (SLO burn)
                                IDLE                 ROLLBACK -> IDLE

ROLLBACK is not a committed stage of its own: it executes inside the
OBSERVE stage's transition back to IDLE, under the ``pilot.rollback``
fault point, so a crash mid-rollback resumes at OBSERVE and re-decides
(the burn evidence is re-read from the live queue, and re-running a
rollback whose ring commit already landed is a no-op).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

SCHEMA_VERSION = 1
STATE_FILE = "pilot-state.json"

# Committed stages, in cycle order. The numeric index doubles as the
# ``pilot_cycle_stage`` gauge value (obs/monitor.py state_family renders
# the one-hot labeled form next to it).
STAGES = ("IDLE", "INGEST", "TRAIN", "VALIDATE", "PROMOTE", "OBSERVE")

MODE_ACTIVE = "active"
MODE_SERVE_ONLY = "serve-only"


@dataclasses.dataclass
class PilotState:
    """Everything a restarted pilot needs to continue mid-cycle."""

    stage: str = "IDLE"
    cycle: int = 0
    mode: str = MODE_ACTIVE
    # Shard bookkeeping: ``processed_shards`` is the set already trained
    # into a PROMOTED (or refused) generation; ``cycle_shards`` is the
    # in-flight cycle's FROZEN snapshot (processed + new, in manifest
    # order) and ``new_shards`` the delta that triggered the cycle.
    processed_shards: list = dataclasses.field(default_factory=list)
    cycle_shards: list = dataclasses.field(default_factory=list)
    new_shards: list = dataclasses.field(default_factory=list)
    # Wall-clock instant the cycle's newest shard landed (mtime max) —
    # the zero point of the staleness metric.
    landed_at: float | None = None
    # Degradation accounting.
    consecutive_failures: int = 0
    deadline_overruns: int = 0
    failures: int = 0
    last_error: str | None = None
    # Control-loop totals (restart-durable; the pilot_* gauges read
    # these, so a supervisor restart never zeroes the counters).
    cycles_completed: int = 0
    promotions: int = 0
    rollbacks: int = 0
    refusals: int = 0
    last_refusal: dict | None = None
    last_promotion: dict | None = None
    last_rollback: dict | None = None
    # The most recent health-gate decision (obs/health.py): reasons +
    # measured drift/skew/ECE/movement numbers. None until a
    # health-armed cycle reaches VALIDATE.
    last_health: dict | None = None
    staleness_seconds: float | None = None
    updated_at: float = 0.0
    schema_version: int = SCHEMA_VERSION

    def require_stage(self, *allowed: str) -> None:
        if self.stage not in allowed:
            raise ValueError(
                f"pilot state machine: stage {self.stage!r} is not one "
                f"of {allowed}")


def state_path(work_dir: str) -> str:
    return os.path.join(work_dir, STATE_FILE)


def commit_state(work_dir: str, state: PilotState) -> None:
    """Atomically commit ``state`` — THE transition primitive. A pilot
    killed at any instant leaves either the previous committed stage or
    the new one, never a torn file."""
    from photon_tpu.io.model_io import atomic_write_bytes

    os.makedirs(work_dir, exist_ok=True)
    state.updated_at = time.time()
    payload = dataclasses.asdict(state)
    atomic_write_bytes(
        state_path(work_dir),
        json.dumps(payload, indent=2, sort_keys=True).encode("utf-8"),
    )


def load_state(work_dir: str) -> PilotState | None:
    """Read the committed state, or None for a fresh work dir. A state
    file from a future schema refuses loudly rather than guessing."""
    path = state_path(work_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        raw = json.load(f)
    version = raw.pop("schema_version", None)
    if version != SCHEMA_VERSION:
        raise ValueError(
            f"pilot state {path}: schema_version {version!r} is not the "
            f"supported {SCHEMA_VERSION}")
    known = {f.name for f in dataclasses.fields(PilotState)}
    state = PilotState(**{k: v for k, v in raw.items() if k in known})
    state.require_stage(*STAGES)
    return state
