"""Bounded on-disk ring of model generations: promote forward, roll back.

Every promotion stages the candidate as ``gen-%06d.npz`` (the native
checkpoint format, written through the atomic dance with the
``pilot.promote`` fault point in its mid-write window) and records it in
``ring.json`` — first as ``staged``, then as ``live`` once the serving
reload committed. The two-step commit is the whole point: a pilot killed
between the stage and the live flip restarts with the server on the OLD
generation and the ring telling it exactly which candidate to finish
promoting.

Rollback flips ``live`` back to the newest OLDER generation and marks
the abandoned one ``rolled_back`` (kept on disk for the post-mortem
until the ring's retention prunes it). Retention keeps the newest
``keep`` generations PLUS whatever is live — the bounded-disk contract
a long-running daemon needs.
"""

from __future__ import annotations

import json
import os
import time

RING_FILE = "ring.json"
SCHEMA_VERSION = 1


class GenerationRing:
    """The pilot's model-generation store under ``<dir>/``."""

    def __init__(self, directory: str, *, keep: int = 3):
        if keep < 2:
            # One previous generation is the minimum rollback inventory.
            raise ValueError("keep must be >= 2 (live + at least one "
                             "rollback target)")
        self.directory = directory
        self.keep = int(keep)
        os.makedirs(directory, exist_ok=True)
        self._meta = self._load()

    # -- durable meta ------------------------------------------------------

    def _ring_path(self) -> str:
        return os.path.join(self.directory, RING_FILE)

    def _load(self) -> dict:
        path = self._ring_path()
        if not os.path.exists(path):
            return {
                "schema_version": SCHEMA_VERSION,
                "live": None,
                "staged": None,
                "entries": [],
            }
        with open(path) as f:
            meta = json.load(f)
        if meta.get("schema_version") != SCHEMA_VERSION:
            raise ValueError(
                f"generation ring {path}: schema_version "
                f"{meta.get('schema_version')!r} is not the supported "
                f"{SCHEMA_VERSION}")
        return meta

    def _commit(self) -> None:
        from photon_tpu.io.model_io import atomic_write_bytes

        atomic_write_bytes(
            self._ring_path(),
            json.dumps(self._meta, indent=2, sort_keys=True).encode(),
        )

    # -- queries -----------------------------------------------------------

    @property
    def live(self) -> int | None:
        return self._meta["live"]

    @property
    def staged(self) -> int | None:
        return self._meta["staged"]

    def entries(self) -> list[dict]:
        return [dict(e) for e in self._meta["entries"]]

    def _entry(self, gen: int) -> dict:
        for e in self._meta["entries"]:
            if e["gen"] == gen:
                return e
        raise KeyError(f"generation {gen} is not in the ring")

    def path(self, gen: int) -> str:
        return os.path.join(self.directory, self._entry(gen)["file"])

    def live_path(self) -> str | None:
        return None if self.live is None else self.path(self.live)

    def load(self, gen: int):
        """Load one generation's GameModel (hash-verified npz)."""
        from photon_tpu.io.model_io import artifact_digest, load_checkpoint
        from photon_tpu.resilience.errors import CorruptModelError

        entry = self._entry(gen)
        path = self.path(gen)
        digest = artifact_digest(path)
        if digest != entry["sha256"]:
            raise CorruptModelError(
                f"generation {gen} at {path}: sha256 {digest[:12]}... "
                f"does not match the ring's {entry['sha256'][:12]}... — "
                "the artifact is torn or was modified after commit")
        return load_checkpoint(path)

    def previous(self, gen: int) -> int | None:
        """The newest generation older than ``gen`` that was never
        rolled back — the rollback target."""
        candidates = [
            e["gen"] for e in self._meta["entries"]
            if e["gen"] < gen and not e.get("rolled_back")
        ]
        return max(candidates) if candidates else None

    # -- transitions -------------------------------------------------------

    def stage_candidate(self, model, *, cycle: int, metrics=None) -> int:
        """Persist ``model`` as the next generation and record it as
        STAGED (not yet serving). The npz write carries the
        ``pilot.promote`` fault point — the deterministic
        kill-during-promotion window chaos CI aims at."""
        from photon_tpu.io.model_io import save_checkpoint

        gen = 1 + max(
            [e["gen"] for e in self._meta["entries"]], default=0
        )
        fname = f"gen-{gen:06d}.npz"
        digest = save_checkpoint(
            model,
            os.path.join(self.directory, fname),
            extra_meta={
                "schema_version": SCHEMA_VERSION,
                "kind": "pilot_generation",
                "gen": gen,
                "cycle": int(cycle),
            },
            fault_point="pilot.promote",
        )
        self._meta["entries"].append({
            "gen": gen,
            "file": fname,
            "sha256": digest,
            "cycle": int(cycle),
            "created_at": time.time(),
            "metrics": dict(metrics or {}),
        })
        self._meta["staged"] = gen
        self._commit()
        return gen

    def commit_live(self, gen: int) -> None:
        """Flip ``gen`` live (the serving reload committed) and prune
        past the retention bound."""
        self._entry(gen)  # must exist
        self._meta["live"] = gen
        if self._meta["staged"] == gen:
            self._meta["staged"] = None
        dropped = self._prune()
        self._commit()
        self._remove_files(dropped)

    def mark_rolled_back(self, gen: int, *, to: int, reason: str) -> None:
        """Record a rollback: ``gen`` is abandoned (kept on disk for the
        post-mortem until retention prunes it), ``to`` is live again."""
        entry = self._entry(gen)
        entry["rolled_back"] = True
        entry["rollback_reason"] = reason
        entry["rolled_back_at"] = time.time()
        self._entry(to)
        self._meta["live"] = to
        if self._meta["staged"] == gen:
            self._meta["staged"] = None
        dropped = self._prune()
        self._commit()
        self._remove_files(dropped)

    def _prune(self) -> list[dict]:
        """Retention: newest ``keep`` generations plus live/staged.
        Returns the dropped entries; their npz files are deleted only
        AFTER the meta commit — a crash between the two leaves an
        orphan file, never a committed entry pointing at nothing."""
        entries = sorted(self._meta["entries"], key=lambda e: e["gen"])
        protected = {self._meta["live"], self._meta["staged"]}
        kept, dropped = [], []
        overflow = len(entries) - self.keep
        for e in entries:
            if overflow > 0 and e["gen"] not in protected:
                dropped.append(e)
                overflow -= 1
            else:
                kept.append(e)
        self._meta["entries"] = kept
        return dropped

    def _remove_files(self, dropped: list[dict]) -> None:
        for e in dropped:
            try:
                os.remove(os.path.join(self.directory, e["file"]))
            except OSError:  # pragma: no cover — concurrent cleanup
                pass
