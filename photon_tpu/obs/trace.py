"""One timeline for everything: trace events + Chrome-trace export.

The spans/metrics/convergence surfaces (PR 4) answer "where did the time
go" in aggregate; this module makes the runtime's history VIEWABLE — one
`trace.json` a browser (Perfetto / chrome://tracing) renders with every
subsystem on the same clock:

- **host spans** from the span tracer become complete ("X") slices on
  per-thread tracks (the ingest planner pools, the background AOT
  compile thread, the serve worker, and the training thread each get
  their own labeled track);
- **instant events** (``instant()``) mark point-in-time facts: injected
  faults firing, retry attempts, circuit-breaker trips, CD rollbacks,
  profiler session start/stop;
- **counter samples** (``counter()``) are time-series gauges — the serve
  queue depth after every batch — rendered as counter tracks; at export
  time every metrics-registry counter/gauge additionally contributes its
  final value as a one-sample counter track;
- **request records** (``request()``) are the serving layer's
  request-scoped span trees (queue-wait → batch-fill → dispatch →
  scatter, minted at ``MicroBatchQueue.submit``), rendered as async
  slices grouped per request id;
- **convergence traces** are re-emitted as counter tracks aligned inside
  their fit's ``fused_fit`` span window, so "is it converging" sits on
  the timeline next to "what was the device doing".

Everything here is host bookkeeping on the ``time.perf_counter`` clock —
the same clock the span tracer stamps — so all sources merge without
translation. Recording is gated on the one telemetry flag
(``obs.enabled()``); disabled, every emit is a single flag check. The
zero-overhead guarantee extends to this layer as an audited contract
(the tier-2 ``trace`` PROGRAM_AUDIT in ``photon_tpu/obs/__init__.py``):
tracing on vs off leaves every fused program byte-identical.

``profile_session`` is THE device-profiling entry point (it replaces the
deprecated ``utils/timed.py`` ``profile_trace`` shim): it wraps a block
in ``jax.profiler.trace`` and brackets it with an obs span + start/stop
instants, so a captured xplane profile is correlated with the fit-level
spans by construction.

Retention is bounded (``set_retention``; default 8192 events, oldest
drop first, ``dropped()`` counts the evicted) — the same concern that
caps spans and convergence traces.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

_DEFAULT_MAX_EVENTS = 8192

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). Events are emitted from every pool the runtime owns
# (the serve worker, retry sites on compile/transfer threads, the
# training thread) and drained by exporters on any thread; the ring and
# its drop counter live under the one module lock. Emission helpers are
# the thread-entry surface. File writes and chrome-trace assembly
# operate on snapshots taken under the lock, never inside it.
CONCURRENCY_AUDIT = dict(
    name="obs-trace",
    locks={
        "_lock": ("_events", "_dropped"),
    },
    thread_entries=("instant", "counter", "request"),
    jax_dispatch_ok={},
)

_lock = threading.Lock()
_events: deque = deque(maxlen=_DEFAULT_MAX_EVENTS)
_dropped = 0

# The request-record outcome taxonomy (OBSERVABILITY.md): every request
# minted at MicroBatchQueue.submit resolves to exactly one of these.
REQUEST_OUTCOMES = (
    "served",     # scored; full segment tree present
    "expired",    # deadline lapsed while queued (failed before dispatch)
    "shed",       # rejected at submit: queue depth at the shed watermark
    "breaker",    # rejected/drained: dispatch circuit breaker open
    "closed",     # rejected at submit: queue already closed
    "error",      # its batch's dispatch raised; error fanned out
    "shutdown",   # stranded by a bounded close() timeout
)


def _enabled() -> bool:
    from photon_tpu import obs

    return obs.TRACER.enabled


def _append(rec: dict) -> None:
    global _dropped
    evicted = False
    with _lock:
        if len(_events) == _events.maxlen:
            _dropped += 1
            evicted = True
        _events.append(rec)
    if evicted:
        # Outside the ring lock (never nested with the registry's):
        # retention pressure is alertable, not just a snapshot header.
        from photon_tpu.obs.metrics import REGISTRY

        REGISTRY.counter("trace_events_dropped_total").inc()


def instant(name: str, *, cat: str = "event", **args) -> None:
    """Record a point-in-time event (no-op when telemetry is disabled)."""
    if not _enabled():
        return
    _append({
        "kind": "instant",
        "name": name,
        "cat": cat,
        "ts": time.perf_counter(),
        "thread": threading.current_thread().name,
        "args": args,
    })


def counter(name: str, value: float, *, ts: float | None = None) -> None:
    """Record one counter-track sample (no-op when disabled). ``ts`` is a
    ``time.perf_counter`` stamp; defaults to now."""
    if not _enabled():
        return
    _append({
        "kind": "counter",
        "name": name,
        "ts": time.perf_counter() if ts is None else float(ts),
        "value": float(value),
    })


def request(record: dict) -> None:
    """Record one serving request's span-tree record (no-op when
    disabled). Required keys: ``id``, ``outcome`` (REQUEST_OUTCOMES),
    ``submit_ts``, ``done_ts``; served requests also carry ``take_ts``,
    ``dispatch_ts``, ``scatter_ts``, ``batch``, ``batch_size``."""
    if not _enabled():
        return
    _append({"kind": "request", **record})


def events() -> list[dict]:
    """Snapshot of the event ring (record order; bounded — ``dropped()``
    counts the evicted)."""
    with _lock:
        return list(_events)


def request_records() -> list[dict]:
    """The ring's request records only (the per-request JSONL payload)."""
    return [e for e in events() if e["kind"] == "request"]


def dropped() -> int:
    with _lock:
        return _dropped


def request_summary(records: list[dict] | None = None) -> dict:
    """Aggregate view of the ring's request records (the serving
    driver's / CLI's ``request_trace`` stats block): outcome counts and
    per-segment mean milliseconds over the requests that carry each
    segment."""
    recs = request_records() if records is None else list(records)
    outcomes: dict[str, int] = {}
    segments: dict[str, list[float]] = {
        name: [] for name, _, _ in REQUEST_SEGMENTS
    }
    for rec in recs:
        outcome = rec.get("outcome", "unknown")
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        for name, a, b in REQUEST_SEGMENTS:
            if a in rec and b in rec and rec[b] >= rec[a]:
                segments[name].append(rec[b] - rec[a])
    return {
        "records": len(recs),
        "outcomes": dict(sorted(outcomes.items())),
        "segment_mean_ms": {
            name: round(sum(vals) / len(vals) * 1e3, 3)
            for name, vals in segments.items()
            if vals
        },
    }


def set_retention(max_events: int) -> None:
    """Rebind the event ring to a new bound (the newest events are
    kept). Events a shrinking bound evicts count as drops — the same
    accounting as ring overflow. The spans ring has the analogous
    ``obs.set_span_retention``."""
    if max_events < 1:
        raise ValueError(f"event retention must be >= 1, got {max_events}")
    global _events, _dropped
    with _lock:
        evicted = max(0, len(_events) - int(max_events))
        _events = deque(_events, maxlen=int(max_events))
        _dropped += evicted
    if evicted:
        from photon_tpu.obs.metrics import REGISTRY

        REGISTRY.counter("trace_events_dropped_total").inc(evicted)


def reset() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


# --------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# --------------------------------------------------------------------------

# Request span-tree segments, in tree order: (slice name, start key,
# end key). A record missing a segment's keys (non-served outcomes)
# renders only the root request slice.
REQUEST_SEGMENTS = (
    ("queue_wait", "submit_ts", "take_ts"),
    ("batch_fill", "take_ts", "dispatch_ts"),
    ("dispatch", "dispatch_ts", "scatter_ts"),
    ("scatter", "scatter_ts", "done_ts"),
)


def _us(t: float) -> float:
    # perf_counter seconds -> chrome-trace microseconds (µs precision
    # kept to 1ns; Perfetto takes floats).
    return round(t * 1e6, 3)


def _request_chrome_events(rec: dict, pid: int) -> list[dict]:
    """One request record -> async ("b"/"e") slices: a root `request`
    slice spanning submit→done plus one nested slice per present
    segment. Perfetto groups async slices by (cat, id) — every request
    renders as its own lane."""
    rid = str(rec["id"])
    cat = "serve.request"
    args = {
        k: rec[k]
        for k in ("outcome", "batch", "batch_size", "error")
        if k in rec
    }
    out = [{
        "name": "request", "cat": cat, "ph": "b", "id": rid,
        "pid": pid, "ts": _us(rec["submit_ts"]), "args": args,
    }]
    for name, a, b in REQUEST_SEGMENTS:
        if a in rec and b in rec and rec[b] >= rec[a]:
            out.append({"name": name, "cat": cat, "ph": "b", "id": rid,
                        "pid": pid, "ts": _us(rec[a])})
            out.append({"name": name, "cat": cat, "ph": "e", "id": rid,
                        "pid": pid, "ts": _us(rec[b])})
    out.append({"name": "request", "cat": cat, "ph": "e", "id": rid,
                "pid": pid, "ts": _us(rec["done_ts"])})
    return out


def chrome_trace() -> dict:
    """Everything on one timeline, as a chrome-trace JSON object.

    Merges (all on the shared ``perf_counter`` clock): completed spans
    as per-thread "X" slices, ring instants/counters, request records as
    async slice trees, the metrics registry's final counter/gauge values
    as one-sample counter tracks, and convergence series as counter
    tracks aligned inside their ``fused_fit`` span windows.
    """
    from photon_tpu import obs
    from photon_tpu.obs import convergence

    pid = os.getpid()
    out: list[dict] = []
    tids: dict[str, int] = {}

    def tid_for(thread: str) -> int:
        t = tids.get(thread)
        if t is None:
            t = tids[thread] = len(tids) + 1
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": t, "args": {"name": thread}})
        return t

    spans = obs.TRACER.completed()
    for sp in spans:
        args: dict = {"path": sp.path}
        if sp.attrs:
            args.update(sp.attrs)
        if sp.device_wait_seconds is not None:
            args["device_wait_seconds"] = round(sp.device_wait_seconds, 6)
        out.append({
            "name": sp.name, "cat": "span", "ph": "X",
            "ts": _us(sp.t0), "dur": _us(max(sp.t1 - sp.t0, 0.0)),
            "pid": pid, "tid": tid_for(sp.thread), "args": args,
        })

    for ev in events():
        kind = ev["kind"]
        if kind == "instant":
            out.append({
                "name": ev["name"], "cat": ev.get("cat", "event"),
                "ph": "i", "s": "t", "ts": _us(ev["ts"]), "pid": pid,
                "tid": tid_for(ev.get("thread", "events")),
                "args": dict(ev.get("args") or {}),
            })
        elif kind == "counter":
            out.append({
                "name": ev["name"], "ph": "C", "ts": _us(ev["ts"]),
                "pid": pid, "args": {"value": ev["value"]},
            })
        else:  # request
            out.extend(_request_chrome_events(ev, pid))

    # Metrics-as-counter-tracks: every registry counter/gauge closes its
    # track with the final value, sampled at export time (live samples,
    # where instrumented, already rode the ring above).
    now_ts = _us(time.perf_counter())
    snap = obs.REGISTRY.snapshot()
    for series, value in sorted(snap["counters"].items()):
        out.append({"name": series, "ph": "C", "ts": now_ts, "pid": pid,
                    "args": {"value": value}})
    for series, value in sorted(snap["gauges"].items()):
        out.append({"name": series, "ph": "C", "ts": now_ts, "pid": pid,
                    "args": {"value": value}})

    # Convergence series -> counter tracks aligned inside their fit's
    # span window. Pairing is presentation-layer: the LAST k parked
    # traces align with the LAST k `fused_fit` spans (both record in
    # completion order on the training thread; the rings bound
    # differently, so only the common tail pairs). Per-iteration values
    # spread evenly across the span — the fit program gives no
    # per-iteration host timestamps, by design.
    fused = [sp for sp in spans if sp.name == "fused_fit"]
    conv = convergence.traces()
    k = min(len(fused), len(conv))
    for fit_span, fit_trace in zip(fused[-k:] if k else [], conv[-k:]):
        t0, dt = fit_span.t0, max(fit_span.t1 - fit_span.t0, 0.0)
        for cid, by_metric in fit_trace.items():
            for metric, values in by_metric.items():
                n = len(values) or 1
                for i, v in enumerate(values):
                    out.append({
                        "name": f"convergence:{cid}:{metric}",
                        "ph": "C",
                        "ts": _us(t0 + dt * (i + 1) / n),
                        "pid": pid, "args": {"value": v},
                    })

    from photon_tpu.obs import fleet

    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "photon_tpu.obs.trace",
            "schema": 1,
            "spans_dropped": obs.TRACER.dropped,
            "events_dropped": dropped(),
            "host": fleet.host_identity(),
        },
    }


def write_chrome_trace(path: str) -> int:
    """Write ``chrome_trace()`` to ``path``; returns the event count."""
    doc = chrome_trace()
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])


_CHROME_PHASES = frozenset({"X", "i", "I", "C", "b", "e", "n", "M"})


def validate_chrome_trace(path: str) -> int:
    """Validate a chrome-trace JSON file (the loadability contract the
    CI telemetry-smoke job enforces on the exported artifact).

    Raises ValueError on the first violation; returns the event count.
    """
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as exc:
            raise ValueError(f"{path}: not JSON ({exc})")
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        raise ValueError(
            f"{path}: not a chrome-trace object (traceEvents missing)"
        )
    evs = doc["traceEvents"]
    if not evs:
        raise ValueError(f"{path}: empty traceEvents")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if ph not in _CHROME_PHASES:
            raise ValueError(
                f"{path}: traceEvents[{i}] has unknown phase {ph!r}"
            )
        if not isinstance(ev.get("pid"), int):
            raise ValueError(f"{path}: traceEvents[{i}] missing int pid")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(
                    f"{path}: traceEvents[{i}] metadata without args"
                )
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(
                f"{path}: traceEvents[{i}] ({ph}) missing numeric ts"
            )
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"{path}: traceEvents[{i}] complete event with bad "
                    f"dur {dur!r}"
                )
        if ph == "C":
            value = (ev.get("args") or {}).get("value")
            if not isinstance(value, (int, float)):
                raise ValueError(
                    f"{path}: traceEvents[{i}] counter without numeric "
                    "args.value"
                )
        if ph in ("b", "e") and ("id" not in ev or "cat" not in ev):
            raise ValueError(
                f"{path}: traceEvents[{i}] async event without id/cat"
            )
    return len(evs)


def write_request_jsonl(path: str) -> int:
    """Write the per-request JSONL stream (header + one ``request``
    record per line; same schema `validate_jsonl` enforces). Returns the
    line count."""
    from photon_tpu import obs

    lines: list[dict] = [{
        "type": "telemetry",
        "version": 1,
        "spans_dropped": obs.TRACER.dropped,
        "events_dropped": dropped(),
    }]
    for rec in request_records():
        lines.append({
            "type": "request",
            **{k: v for k, v in rec.items() if k != "kind"},
        })
    with open(path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    return len(lines)


# --------------------------------------------------------------------------
# the profiler entry point
# --------------------------------------------------------------------------


@contextlib.contextmanager
def profile_session(trace_dir: str | None, *, name: str = "jax_profiler"):
    """THE device-profiling entry point (replaces the deprecated
    ``utils.timed.profile_trace`` shim).

    A falsy ``trace_dir`` is a no-op that never touches jax — call sites
    wire it unconditionally. With a directory, the block runs under
    ``jax.profiler.trace(trace_dir)`` AND inside a ``<name>`` obs span
    carrying the directory, bracketed by ``profile.start``/``profile.stop``
    instants — so the captured xplane profile is correlated with the
    fit-level spans on the one exported timeline by construction (the
    span's window IS the profiler session's window).
    """
    if not trace_dir:
        yield
        return
    import jax

    from photon_tpu import obs

    instant("profile.start", cat="profiler", trace_dir=trace_dir)
    try:
        with obs.span(name, attrs={"trace_dir": trace_dir}):
            with jax.profiler.trace(trace_dir):
                yield
    finally:
        instant("profile.stop", cat="profiler", trace_dir=trace_dir)
