"""Live monitoring for long-running processes: pull, don't post-mortem.

Every telemetry surface before this module materializes at process exit
or on crash (the PR-4 snapshot/JSONL exporters, the PR-8 trace and
flight artifacts). A serving fleet is observed while it runs, by
PULLING — so this module adds the four pieces a scrape-based monitoring
stack needs, all stdlib, no new dependencies:

- **The HTTP exporter** (:class:`MonitorServer`): ``http.server`` on a
  daemon thread serving ``/metrics`` (Prometheus text exposition
  0.0.4, rendered from a consistent snapshot of the metrics registry
  plus any registered collectors), ``/healthz`` (liveness: the process
  is up and the exporter thread is answering) and ``/readyz``
  (readiness: the caller-supplied probe — for ``cli.serve``, tables
  loaded + AOT ladder compiled + breaker closed). Wired into
  ``cli.serve --monitor-port`` and ``cli.train --monitor-port``.
- **Sliding-window latency quantiles** (:class:`RollingHistogram`):
  log-bucketed fixed-size histograms in a ring of rotating windows, so
  ``p50/p99`` describe the LAST N SECONDS, not the whole run —
  whole-run percentiles hide a degrading tail on a long-lived server.
  Quantile error is bounded by the bucket growth factor (a reported
  quantile is the upper bound of the bucket holding the exact one).
- **Declared SLOs with multi-window burn rates** (:class:`SloPolicy` /
  :class:`SloTracker`): ``p99_ms`` (latency objective), ``error_rate``
  and ``cold_entity_rate`` budgets, each tracked as good/bad counts in
  the same rotating-window ring and reported as ``observed / budget``
  burn over a short and a long window — the standard multi-window
  burn-rate alert shape, surfaced through ``/metrics``, the serve
  queue's ``health()``, and the bench JSON.
- **Entity-hotness sketches** (:class:`SpaceSavingSketch`):
  space-saving top-K over per-coordinate ``RandomTable`` lookups — the
  bounded-memory answer to "which entities are hot enough to shard or
  cache" (ROADMAP items 1 and 4 consume exactly this), next to the
  per-coordinate cold-entity counters that replace the single global
  ``serving_cold_entity_rate``.

Everything here is host bookkeeping: no jax import, no traced operand,
no callback. The tier-2 ``monitor`` PROGRAM_AUDIT (declared in
``photon_tpu/obs/__init__.py``, machinery in
``analysis/program.build_monitor``) proves a scrape under load leaves
the serving programs byte-identical with zero added programs; the
CONCURRENCY_AUDIT below is the tier-3 contract for the exporter thread
and the window rings.
"""

from __future__ import annotations

import http.server
import json
import math
import threading
import time

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The exporter's handler threads (one per in-flight
# scrape; ThreadingHTTPServer) READ every surface they render through
# snapshot methods that copy under each surface's own small lock and
# release it before any rendering or socket I/O happens — a scrape
# never holds a lock the serve dispatch worker needs across anything
# blocking. Writers are the serve worker (windows, sketches, SLO
# rings) and producers (SLO rejection counts); each surface keeps its
# own lock, distinctly named so the lockset auditor can tell them
# apart, and no path ever nests two of them.
CONCURRENCY_AUDIT = dict(
    name="obs-monitor",
    locks={
        "RollingHistogram._hist_lock": (
            "RollingHistogram._win_counts",
            "RollingHistogram._win_sums",
            "RollingHistogram._win_totals",
            "RollingHistogram._window_start",
            "RollingHistogram._win_cursor",
        ),
        "SpaceSavingSketch._sketch_lock": (
            "SpaceSavingSketch._sk_counts",
            "SpaceSavingSketch._sk_errors",
            "SpaceSavingSketch._observed",
        ),
        "SloTracker._slo_lock": (
            "SloTracker._rings",
            "SloTracker._ring_start",
            "SloTracker._ring_cursor",
        ),
        "MonitorServer._server_lock": (
            "MonitorServer._scrapes",
            "MonitorServer._scrape_errors",
        ),
    },
    thread_entries=(
        "do_GET",
        "RollingHistogram.observe",
        "SpaceSavingSketch.observe",
        "SloTracker.observe_request",
        "SloTracker.observe_lookups",
    ),
    jax_dispatch_ok={},
)


# --------------------------------------------------------------------------
# Prometheus text exposition (render + shared validator)
# --------------------------------------------------------------------------

# One rendered metric family: ``samples`` is a list of
# (suffix, labels-dict, value) — suffix is "" for plain families and
# "_bucket"/"_count"/"_sum" for histogram series.
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def family(name: str, mtype: str, help_: str, samples) -> dict:
    if mtype not in _TYPES:
        raise ValueError(f"unknown metric type {mtype!r}")
    return {
        "name": metric_name(name),
        "type": mtype,
        "help": help_,
        "samples": list(samples),
    }


def state_family(name: str, states, current, help_: str) -> dict:
    """A Prometheus state-set: one-hot gauge samples labeled by state
    (``name{state="INGEST"} 1`` next to zeros for the others) — the
    queryable form of an enum-valued gauge like the pilot's
    state-machine stage. ``current`` must be one of ``states``."""
    states = tuple(states)
    if current not in states:
        raise ValueError(
            f"state {current!r} is not one of the declared {states}")
    return family(
        name, "gauge", help_,
        [("", {"state": s}, 1.0 if s == current else 0.0)
         for s in states],
    )


def metric_name(raw: str) -> str:
    """Sanitize to the exposition charset ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = [
        c if (c.isascii() and (c.isalnum() or c in "_:")) else "_"
        for c in raw
    ]
    if not out:
        return "_"
    if out[0].isdigit():
        out.insert(0, "_")
    return "".join(out)


def _label_name(raw: str) -> str:
    out = metric_name(raw).replace(":", "_")
    return out


def _label_value(raw) -> str:
    return (
        str(raw)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value: float) -> str:
    value = float(value)
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _parse_series_key(key: str) -> tuple[str, dict]:
    """Invert ``obs.metrics._series_key``: ``name{k=v,...}`` -> parts."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: dict[str, str] = {}
    for pair in rest.rstrip("}").split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        labels[k] = v
    return name, labels


def registry_families(snapshot: dict) -> list[dict]:
    """Metric families from a ``MetricsRegistry.snapshot()``.

    Counters and gauges map one-to-one; the registry's count/sum/min/max
    histograms render as a summary (``_count``/``_sum``) plus ``_min`` /
    ``_max`` gauge families — they carry no buckets by design
    (obs/metrics.py keeps the hot host path to four scalars).
    """
    grouped: dict[tuple[str, str], list] = {}
    for kind in ("counters", "gauges"):
        for key, value in sorted(snapshot.get(kind, {}).items()):
            name, labels = _parse_series_key(key)
            grouped.setdefault((kind, name), []).append(
                ("", labels, float(value))
            )
    out = [
        family(
            name,
            "counter" if kind == "counters" else "gauge",
            f"photon_tpu metrics-registry {kind[:-1]} {name}",
            samples,
        )
        for (kind, name), samples in sorted(grouped.items())
    ]
    hists: dict[str, list] = {}
    extrema: dict[str, list] = {}
    for key, h in sorted(snapshot.get("histograms", {}).items()):
        name, labels = _parse_series_key(key)
        hists.setdefault(name, []).extend(
            [
                ("_count", labels, float(h["count"])),
                ("_sum", labels, float(h["sum"])),
            ]
        )
        for bound in ("min", "max"):
            extrema.setdefault(f"{name}_{bound}", []).append(
                ("", labels, float(h[bound]))
            )
    for name, samples in sorted(hists.items()):
        out.append(
            family(
                name,
                "summary",
                f"photon_tpu metrics-registry histogram {name} "
                "(count/sum; min/max ride as gauges)",
                samples,
            )
        )
    for name, samples in sorted(extrema.items()):
        out.append(
            family(
                name, "gauge",
                f"photon_tpu metrics-registry histogram extremum {name}",
                samples,
            )
        )
    return out


def render_exposition(families: list[dict]) -> str:
    """Families -> Prometheus text exposition format 0.0.4."""
    lines: list[str] = []
    seen: set[str] = set()
    for fam in families:
        name = fam["name"]
        if name in seen:
            raise ValueError(f"duplicate metric family {name!r}")
        seen.add(name)
        help_ = fam["help"].replace("\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for suffix, labels, value in fam["samples"]:
            label_txt = ""
            if labels:
                inner = ",".join(
                    f'{_label_name(k)}="{_label_value(v)}"'
                    for k, v in labels.items()
                )
                label_txt = "{" + inner + "}"
            lines.append(f"{name}{suffix}{label_txt} {_fmt(value)}")
    return "\n".join(lines) + "\n"


_NAME_OK = None  # compiled lazily (keep import time flat)


def _name_re():
    global _NAME_OK
    if _NAME_OK is None:
        import re

        _NAME_OK = (
            re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$"),
            re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$"),
            re.compile(
                r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
                r"(?P<labels>\{.*\})?\s+(?P<value>\S+)$"
            ),
            re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'),
        )
    return _NAME_OK


def validate_exposition(text: str) -> int:
    """Validate Prometheus text exposition; the ONE validator shared by
    the unit tests and the CI scrape step.

    Checks: metric/label name charsets, every sample preceded by its
    family's ``# HELP``/``# TYPE`` pair, known types, parseable values,
    histogram bucket monotonicity (cumulative ``le`` buckets
    nondecreasing, ``+Inf`` present and equal to ``_count``). Raises
    ``ValueError`` on the first violation; returns the sample count.
    """
    name_re, label_re, sample_re, labelpair_re = _name_re()
    typed: dict[str, str] = {}
    helped: set[str] = set()
    samples = 0
    # histogram name -> labels-sans-le key -> [(le, value)], count value
    buckets: dict[str, dict[str, list]] = {}
    counts: dict[str, dict[str, float]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split(None, 3)[2]
            if not name_re.match(name):
                raise ValueError(f"line {i}: bad metric name {name!r}")
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ValueError(f"line {i}: malformed TYPE line")
            name, mtype = parts[2], parts[3]
            if not name_re.match(name):
                raise ValueError(f"line {i}: bad metric name {name!r}")
            if mtype not in _TYPES:
                raise ValueError(f"line {i}: unknown type {mtype!r}")
            if name in typed:
                raise ValueError(f"line {i}: duplicate TYPE for {name!r}")
            typed[name] = mtype
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            raise ValueError(f"line {i}: unparseable sample {line!r}")
        full = m.group("name")
        base = full
        suffix = ""
        for s in ("_bucket", "_count", "_sum"):
            if full.endswith(s) and full[: -len(s)] in typed:
                base, suffix = full[: -len(s)], s
                break
        if base not in typed or base not in helped:
            raise ValueError(
                f"line {i}: sample {full!r} has no HELP/TYPE family"
            )
        value_txt = m.group("value")
        if value_txt not in ("+Inf", "-Inf", "NaN"):
            try:
                float(value_txt)
            except ValueError:
                raise ValueError(
                    f"line {i}: non-numeric value {value_txt!r}"
                )
        labels = {}
        if m.group("labels"):
            for lm in labelpair_re.finditer(m.group("labels")):
                k = lm.group(1)
                if not label_re.match(k):
                    raise ValueError(f"line {i}: bad label name {k!r}")
                labels[k] = lm.group(2)
        if typed[base] == "histogram":
            key = ",".join(
                f"{k}={v}" for k, v in sorted(labels.items())
                if k != "le"
            )
            if suffix == "_bucket":
                le = labels.get("le")
                if le is None:
                    raise ValueError(
                        f"line {i}: histogram bucket without le label"
                    )
                le_val = (
                    math.inf if le == "+Inf" else float(le)
                )
                buckets.setdefault(base, {}).setdefault(key, []).append(
                    (le_val, float(value_txt))
                )
            elif suffix == "_count":
                counts.setdefault(base, {})[key] = float(value_txt)
        samples += 1
    for name, series in buckets.items():
        for key, pairs in series.items():
            ordered = sorted(pairs)
            les = [le for le, _ in ordered]
            vals = [v for _, v in ordered]
            if len(set(les)) != len(les):
                raise ValueError(
                    f"{name}{{{key}}}: duplicate le bucket"
                )
            if any(b < a for a, b in zip(vals, vals[1:])):
                raise ValueError(
                    f"{name}{{{key}}}: bucket counts not monotone "
                    f"({vals})"
                )
            if not les or not math.isinf(les[-1]):
                raise ValueError(f"{name}{{{key}}}: no +Inf bucket")
            cnt = counts.get(name, {}).get(key)
            if cnt is not None and cnt != vals[-1]:
                raise ValueError(
                    f"{name}{{{key}}}: _count {cnt} != +Inf bucket "
                    f"{vals[-1]}"
                )
    return samples


# --------------------------------------------------------------------------
# sliding-window latency quantiles
# --------------------------------------------------------------------------


def log_bucket_bounds(
    lo: float = 1e-4, hi: float = 60.0, growth: float = 2 ** 0.25
) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds covering [lo, hi] seconds.

    ``growth`` is the per-bucket ratio and therefore the quantile
    error bound: a reported quantile is the upper bound of the bucket
    the exact quantile falls in, so it sits within one growth factor
    above it (values below ``lo`` report ``lo``; the +Inf catch-all is
    implicit in :class:`RollingHistogram`).
    """
    if not (0 < lo < hi) or growth <= 1.0:
        raise ValueError(f"bad bucket spec lo={lo} hi={hi} growth={growth}")
    bounds = [lo]
    while bounds[-1] < hi:
        bounds.append(bounds[-1] * growth)
    return tuple(bounds)


class RollingHistogram:
    """Fixed-size log-bucketed histogram over a ring of rotating windows.

    ``num_windows`` sub-windows of ``window_s`` seconds each; quantiles
    and bucket snapshots merge the ring, so they describe the last
    ``num_windows * window_s`` seconds (plus the partially-filled
    current window). Rotation happens lazily on observe/read — no
    timer thread. O(buckets) memory, O(1) observe.
    """

    def __init__(
        self,
        *,
        window_s: float = 10.0,
        num_windows: int = 6,
        bounds: tuple[float, ...] | None = None,
        clock=time.monotonic,
    ):
        if window_s <= 0 or num_windows < 1:
            raise ValueError(
                f"bad ring spec window_s={window_s} "
                f"num_windows={num_windows}"
            )
        self.window_s = float(window_s)
        self.num_windows = int(num_windows)
        self.bounds = tuple(bounds) if bounds else log_bucket_bounds()
        self._clock = clock
        self._hist_lock = threading.Lock()
        n = len(self.bounds) + 1  # +Inf catch-all
        self._win_counts = [
            [0] * n for _ in range(self.num_windows)
        ]
        self._win_sums = [0.0] * self.num_windows
        self._win_totals = [0] * self.num_windows
        self._win_cursor = 0
        self._window_start = self._clock()

    def _rotate_locked(self, now: float) -> None:
        stale = int((now - self._window_start) // self.window_s)
        if stale <= 0:
            return
        for _ in range(min(stale, self.num_windows)):
            self._win_cursor = (self._win_cursor + 1) % self.num_windows  # photon: ignore[unlocked-shared-write] -- _rotate_locked runs only under `with self._hist_lock` (the _locked suffix is the calling convention; see queue._expire_locked)
            self._win_counts[self._win_cursor] = [0] * (len(self.bounds) + 1)  # photon: ignore[unlocked-shared-write] -- same: caller holds _hist_lock
            self._win_sums[self._win_cursor] = 0.0  # photon: ignore[unlocked-shared-write] -- same: caller holds _hist_lock
            self._win_totals[self._win_cursor] = 0  # photon: ignore[unlocked-shared-write] -- same: caller holds _hist_lock
        self._window_start += stale * self.window_s  # photon: ignore[unlocked-shared-write] -- same: caller holds _hist_lock

    def _bucket_index(self, value: float) -> int:
        import bisect

        return bisect.bisect_left(self.bounds, value)

    def observe(self, value: float) -> None:
        value = float(value)
        idx = self._bucket_index(value)
        with self._hist_lock:
            self._rotate_locked(self._clock())
            self._win_counts[self._win_cursor][idx] += 1
            self._win_sums[self._win_cursor] += value
            self._win_totals[self._win_cursor] += 1

    def _merged_locked(self) -> tuple[list[int], int, float]:
        merged = [0] * (len(self.bounds) + 1)
        for win in self._win_counts:
            for i, c in enumerate(win):
                merged[i] += c
        return merged, sum(self._win_totals), sum(self._win_sums)

    def snapshot(self) -> dict:
        """Consistent merged view of the ring (bucket counts per upper
        bound, total count/sum, the window the numbers describe)."""
        with self._hist_lock:
            self._rotate_locked(self._clock())
            merged, total, total_sum = self._merged_locked()
        return {
            "bounds": self.bounds,
            "counts": merged,
            "count": total,
            "sum": total_sum,
            "window_seconds": self.window_s * self.num_windows,
        }

    def _quantile_from(self, snap: dict, q: float) -> float | None:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        total = snap["count"]
        if not total:
            return None
        rank = max(1, math.ceil(q * total))
        seen = 0
        for i, c in enumerate(snap["counts"]):
            seen += c
            if seen >= rank:
                if i < len(self.bounds):
                    return self.bounds[i]
                return math.inf
        return math.inf  # pragma: no cover — rank <= total by construction

    def quantile(self, q: float) -> float | None:
        """Windowed quantile estimate (bucket upper bound; None when the
        ring is empty). Error bound: one bucket growth factor."""
        return self._quantile_from(self.snapshot(), q)

    def quantiles_ms(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """All quantiles (milliseconds) + the sample count from ONE
        snapshot — mutually consistent by construction (independent
        reads could interleave a ring rotation and report
        p99 < p50)."""
        snap = self.snapshot()
        out = {}
        for q in qs:
            v = self._quantile_from(snap, q)
            key = f"p{int(q * 100)}_ms"
            # A quantile in the +Inf catch-all clamps to the top
            # finite bound: the dict feeds json.dumps surfaces
            # (cli --json, bench lines) and a literal Infinity is not
            # valid RFC-8259 JSON. The exposition histogram still
            # shows the +Inf bucket mass, so the overflow is visible.
            out[key] = None if v is None else round(
                min(v, self.bounds[-1]) * 1e3, 3
            )
        out["count"] = snap["count"]
        return out

    def prometheus_family(self, name: str, help_: str) -> dict:
        snap = self.snapshot()
        cumulative = 0
        samples = []
        for bound, c in zip(snap["bounds"], snap["counts"]):
            cumulative += c
            samples.append(
                ("_bucket", {"le": _fmt(bound)}, float(cumulative))
            )
        samples.append(
            ("_bucket", {"le": "+Inf"}, float(snap["count"]))
        )
        samples.append(("_count", {}, float(snap["count"])))
        samples.append(("_sum", {}, float(snap["sum"])))
        return family(name, "histogram", help_, samples)


# --------------------------------------------------------------------------
# entity-hotness sketch (space-saving top-K)
# --------------------------------------------------------------------------


class SpaceSavingSketch:
    """Metwally et al. space-saving top-K heavy hitters.

    Bounded memory (``k`` tracked keys); every tracked key's count
    overestimates its true frequency by at most its recorded ``error``
    — the standard guarantee that makes the top of the list
    trustworthy on skewed streams (entity popularity is exactly such a
    stream). O(k) eviction keeps the implementation dependency-free;
    k is small (default 64 per coordinate).
    """

    def __init__(self, k: int = 64):
        if k < 1:
            raise ValueError(f"sketch capacity must be >= 1, got {k}")
        self.k = int(k)
        self._sketch_lock = threading.Lock()
        self._sk_counts: dict[str, int] = {}
        self._sk_errors: dict[str, int] = {}
        self._observed = 0

    def observe(self, key, weight: int = 1) -> None:
        key = str(key)
        with self._sketch_lock:
            self._observed += weight
            if key in self._sk_counts:
                self._sk_counts[key] += weight
                return
            if len(self._sk_counts) < self.k:
                self._sk_counts[key] = weight
                self._sk_errors[key] = 0
                return
            victim = min(self._sk_counts, key=self._sk_counts.get)
            floor = self._sk_counts.pop(victim)
            self._sk_errors.pop(victim)
            self._sk_counts[key] = floor + weight
            self._sk_errors[key] = floor

    def top(self, n: int | None = None) -> list[dict]:
        with self._sketch_lock:
            items = sorted(
                self._sk_counts.items(), key=lambda kv: -kv[1]
            )[: self.k if n is None else n]
            return [
                {
                    "key": key,
                    "count": count,
                    "error": self._sk_errors[key],
                }
                for key, count in items
            ]

    def observed(self) -> int:
        with self._sketch_lock:
            return self._observed


# --------------------------------------------------------------------------
# declared SLOs + multi-window burn rates
# --------------------------------------------------------------------------


class SloPolicy:
    """Declared serving SLOs.

    ``p99_ms``: the latency objective — 99% of served requests must
    finish under this many milliseconds (error budget: 1%).
    ``error_rate``: the fraction of requests allowed to fail.
    ``cold_entity_rate``: the fraction of entity lookups allowed to
    miss every vocabulary (sustained cold traffic above this means the
    serving model is stale or the vocabulary is mis-sized).
    ``short_window_s``/``long_window_s``: the two burn-rate windows.
    """

    __slots__ = (
        "p99_ms", "error_rate", "cold_entity_rate",
        "short_window_s", "long_window_s",
    )

    def __init__(
        self,
        *,
        p99_ms: float = 250.0,
        error_rate: float = 0.001,
        cold_entity_rate: float = 0.2,
        short_window_s: float = 5.0,
        long_window_s: float = 60.0,
    ):
        if p99_ms <= 0 or not (0 < error_rate < 1) or not (
            0 < cold_entity_rate <= 1
        ):
            raise ValueError("bad SLO policy")
        if not (0 < short_window_s <= long_window_s):
            raise ValueError(
                f"short window {short_window_s}s must be <= long "
                f"window {long_window_s}s"
            )
        self.p99_ms = float(p99_ms)
        self.error_rate = float(error_rate)
        self.cold_entity_rate = float(cold_entity_rate)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


_SLO_NAMES = ("p99_ms", "error_rate", "cold_entity_rate")


class SloTracker:
    """Good/bad counts per SLO in a rotating ring; burn = observed bad
    fraction over the declared budget, computed over the short and the
    long window. Burn 0 means no budget spent at all; burn 1 means
    spending exactly at budget; sustained burn > 1 on both windows is
    the page condition.
    """

    # The short window reads this many ring granules (the current,
    # partially-filled one plus the previous full one). With granules
    # of short_window_s/2, the short burn always covers between
    # short/2 and short seconds of history — a burst can never vanish
    # from the short window at the instant a granule rotates, which a
    # current-granule-only read would allow.
    _SHORT_GRANULES = 2

    def __init__(self, policy: SloPolicy | None = None, *,
                 clock=time.monotonic):
        self.policy = policy or SloPolicy()
        self._clock = clock
        self._granule_s = (
            self.policy.short_window_s / self._SHORT_GRANULES
        )
        self._num_granules = max(
            self._SHORT_GRANULES,
            math.ceil(self.policy.long_window_s / self._granule_s),
        )
        self._slo_lock = threading.Lock()
        # ring[granule][slo] = [bad, total]
        self._rings = [
            {name: [0, 0] for name in _SLO_NAMES}
            for _ in range(self._num_granules)
        ]
        self._ring_cursor = 0
        self._ring_start = self._clock()

    # budgets: the latency SLO is "99% under p99_ms" (budget 1%); the
    # other two ARE their budgets.
    def _budget(self, name: str) -> float:
        if name == "p99_ms":
            return 0.01
        return getattr(self.policy, name)

    def _rotate_locked(self, now: float) -> None:
        stale = int((now - self._ring_start) // self._granule_s)
        if stale <= 0:
            return
        for _ in range(min(stale, self._num_granules)):
            self._ring_cursor = (  # photon: ignore[unlocked-shared-write] -- _rotate_locked runs only under `with self._slo_lock` (the _locked suffix is the calling convention)
                self._ring_cursor + 1
            ) % self._num_granules
            self._rings[self._ring_cursor] = {  # photon: ignore[unlocked-shared-write] -- same: caller holds _slo_lock
                name: [0, 0] for name in _SLO_NAMES
            }
        self._ring_start += stale * self._granule_s  # photon: ignore[unlocked-shared-write] -- same: caller holds _slo_lock

    def _observe_locked(self, name: str, bad: int, total: int) -> None:
        cell = self._rings[self._ring_cursor][name]
        cell[0] += bad
        cell[1] += total

    def observe_request(
        self, latency_s: float | None, *, error: bool = False
    ) -> None:
        """One finished request: served requests carry their latency
        (the latency SLO judges it against ``p99_ms``); failed ones —
        dispatch errors, expired deadlines, shed/breaker/shutdown
        rejections — carry ``error=True`` and no latency."""
        with self._slo_lock:
            self._rotate_locked(self._clock())
            self._observe_locked("error_rate", int(error), 1)
            if latency_s is not None:
                over = latency_s * 1e3 > self.policy.p99_ms
                self._observe_locked("p99_ms", int(over), 1)

    def observe_errors(self, n: int = 1) -> None:
        """``n`` failed requests at once (a breaker drain, a bounded
        close's stranding) — each burns error budget, none carries a
        latency."""
        if n <= 0:
            return
        with self._slo_lock:
            self._rotate_locked(self._clock())
            self._observe_locked("error_rate", n, n)

    def observe_lookups(self, total: int, cold: int) -> None:
        if total <= 0:
            return
        with self._slo_lock:
            self._rotate_locked(self._clock())
            self._observe_locked("cold_entity_rate", cold, total)

    def _window_counts_locked(self, granules: int) -> dict:
        out = {name: [0, 0] for name in _SLO_NAMES}
        for i in range(min(granules, self._num_granules)):
            ring = self._rings[
                (self._ring_cursor - i) % self._num_granules
            ]
            for name in _SLO_NAMES:
                out[name][0] += ring[name][0]
                out[name][1] += ring[name][1]
        return out

    def report(self) -> dict:
        """The burn-rate block ``health()``, ``/metrics`` and the bench
        JSON surface: per SLO — target, budget, short/long-window burn,
        bad/total counts over the long window — plus an aggregate
        ``healthy`` flag (every burn <= 1)."""
        with self._slo_lock:
            self._rotate_locked(self._clock())
            short = self._window_counts_locked(self._SHORT_GRANULES)
            long_ = self._window_counts_locked(self._num_granules)
        out: dict = {"windows_s": {
            "short": self._granule_s * self._SHORT_GRANULES,
            "long": self._granule_s * self._num_granules,
        }}
        healthy = True
        for name in _SLO_NAMES:
            budget = self._budget(name)

            def burn(cell):
                bad, total = cell
                return round(
                    (bad / total) / budget, 4
                ) if total else 0.0

            b_short, b_long = burn(short[name]), burn(long_[name])
            healthy = healthy and b_short <= 1.0 and b_long <= 1.0
            out[name] = {
                "target": getattr(self.policy, name),
                "budget": budget,
                "burn_short": b_short,
                "burn_long": b_long,
                "bad": long_[name][0],
                "total": long_[name][1],
            }
        out["healthy"] = healthy
        return out

    def prometheus_families(self) -> list[dict]:
        rep = self.report()
        burns, bads, totals = [], [], []
        for name in _SLO_NAMES:
            for window in ("short", "long"):
                burns.append((
                    "",
                    {"slo": name, "window": window},
                    rep[name][f"burn_{window}"],
                ))
            bads.append(("", {"slo": name}, float(rep[name]["bad"])))
            totals.append(
                ("", {"slo": name}, float(rep[name]["total"]))
            )
        return [
            family(
                "slo_burn_rate", "gauge",
                "observed bad fraction over the declared budget, per "
                "SLO and burn window (sustained > 1 on both windows "
                "means the budget is burning)",
                burns,
            ),
            family(
                "slo_bad_events", "gauge",
                "SLO-violating events over the long window", bads,
            ),
            family(
                "slo_events", "gauge",
                "SLO-judged events over the long window", totals,
            ),
        ]


# --------------------------------------------------------------------------
# the HTTP exporter
# --------------------------------------------------------------------------

_START_TIME = time.monotonic()


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "photon-monitor/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        mon: "MonitorServer" = self.server.monitor  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = mon.render().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif path == "/healthz":
                body, ctype, code = b"ok\n", "text/plain", 200
            elif path == "/readyz":
                ready, detail = mon.readiness_probe()
                body = (
                    json.dumps(
                        {"ready": bool(ready), **detail}
                    ).encode("utf-8") + b"\n"
                )
                ctype = "application/json"
                code = 200 if ready else 503
            else:
                body, ctype, code = b"not found\n", "text/plain", 404
        except Exception as exc:  # noqa: BLE001 — a scrape must never
            # take the server thread down; the error is the response.
            mon.count_scrape(path, error=True)
            body = f"scrape failed: {exc!r}\n".encode("utf-8")
            ctype, code = "text/plain", 500
        else:
            mon.count_scrape(path, error=False)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response; nothing to save

    def log_message(self, *args):  # noqa: D102 — quiet by design
        pass


class MonitorServer:
    """``/metrics`` + ``/healthz`` + ``/readyz`` on a daemon thread.

    ``collectors`` are zero-arg callables returning metric-family lists
    (``family(...)`` dicts) — the serve CLI registers the queue-health
    and SLO collectors; the metrics registry is always included.
    ``readiness`` is a zero-arg callable returning ``(ready, detail)``;
    ``None`` means ready-when-alive. ``port=0`` binds an ephemeral port
    (tests, the tier-2 audit); ``.port`` reports the bound one.

    Rendering takes a consistent snapshot of each surface (the registry
    under its one lock, each collector under its own) and assembles the
    text with NO lock held — a slow scraper can never stall the serve
    worker.
    """

    def __init__(
        self,
        port: int = 0,
        *,
        host: str = "127.0.0.1",
        readiness=None,
        collectors=(),
    ):
        self.host = host
        self._requested_port = int(port)
        self._readiness = readiness
        self._collectors = list(collectors)
        self._server_lock = threading.Lock()
        self._scrapes: dict[str, int] = {}
        self._scrape_errors = 0
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MonitorServer":
        if self._httpd is not None:
            return self
        httpd = http.server.ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        httpd.daemon_threads = True
        httpd.monitor = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name="photon-monitor",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MonitorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("monitor server is not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- handler-facing surface ------------------------------------------

    def add_collector(self, collector) -> None:
        self._collectors.append(collector)

    def count_scrape(self, path: str, *, error: bool) -> None:
        with self._server_lock:
            self._scrapes[path] = self._scrapes.get(path, 0) + 1
            if error:
                self._scrape_errors += 1

    def scrape_stats(self) -> dict:
        with self._server_lock:
            return {
                "scrapes": dict(self._scrapes),
                "scrape_errors": self._scrape_errors,
            }

    def readiness_probe(self) -> tuple[bool, dict]:
        if self._readiness is None:
            return True, {}
        out = self._readiness()
        if isinstance(out, tuple):
            ready, detail = out
            return bool(ready), dict(detail)
        return bool(out), {}

    def render(self) -> str:
        """One scrape's exposition text. Snapshot-then-render: the
        registry snapshot and every collector hold only their own lock
        while COPYING; rendering and the socket write happen lockless.
        """
        from photon_tpu.obs import REGISTRY

        families = registry_families(REGISTRY.snapshot())
        for collector in self._collectors:
            families.extend(collector())
        # The cost ledger exposes itself on EVERY monitor (train,
        # serve, pilot) without per-CLI wiring: empty when disabled,
        # so an unarmed process scrapes exactly what it always did.
        from photon_tpu.obs import ledger

        families.extend(ledger.metrics_families())
        # Same policy for the model/data-health layer (obs/health.py):
        # health_* families on every monitor, empty when disarmed.
        from photon_tpu.obs import health

        families.extend(health.metrics_families())
        stats = self.scrape_stats()
        scrape_samples = [
            ("", {"path": path}, float(n))
            for path, n in sorted(stats["scrapes"].items())
        ] or [("", {"path": "/metrics"}, 0.0)]
        families.append(
            family(
                "monitor_scrapes_total", "counter",
                "scrapes served by this exporter, per endpoint",
                scrape_samples,
            )
        )
        families.append(
            family(
                "monitor_scrape_errors_total", "counter",
                "scrapes that failed to render",
                [("", {}, float(stats["scrape_errors"]))],
            )
        )
        families.append(
            family(
                "process_uptime_seconds", "gauge",
                "seconds since photon_tpu.obs.monitor was imported",
                [("", {}, time.monotonic() - _START_TIME)],
            )
        )
        return render_exposition(families)
