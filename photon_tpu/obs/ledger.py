"""Per-program cost ledger: every wall-clock second and HBM byte, named.

The roofline gauge (``measured_vs_roofline``, PR 8) says *that* the
fused fit is dispatch/layout-bound; this module says *which* program,
coordinate, and phase is burning the time. Every cost in the runtime
belongs to a ``(coordinate, phase, program)`` triple — the natural unit
of photon-ml's block-coordinate-descent structure — and the ledger is
the runtime half of the attribution: ``analysis/costmodel.py`` already
prices every lowered program statically (FLOPs / HBM bytes / roofline
bound); the ledger joins that static cost to MEASURED dispatches and
live buffers.

What it keeps (all process-global, one module lock, bounded by the
number of distinct programs/coordinates — not by run length):

- a **program census**: every compiled program the instrumented paths
  register (the fused materialize/fit blocks, the serve ladder's score
  rungs, eval programs), each with a lazy static-cost thunk — the
  lowering/pricing runs at REPORT time, never on a dispatch path;
- **dispatch rows** keyed by ``(coordinate, phase, program)``: measured
  seconds, dispatch count, and host-gap seconds (the idle gap between
  the previous dispatch's completion and this one's start — the
  dispatch-bound signature the roofline gap predicts);
- an **HBM live-buffer account**: per-owner resident bytes (serving
  coefficient tables, fused-fit slabs) and a peak-watermark gauge;
- a **compile-time ledger** keyed by the caller's cache key.

``report()`` joins rows to their program's static cost: achieved
FLOP/s and bytes/s vs that program's OWN roofline, wasted seconds
(measured minus roofline lower bound), and a blocking reason —
``dispatch-gap`` when host gaps dominate the measured window,
``bandwidth``/``compute`` from the program's roofline bound otherwise,
``measured-only`` when no static cost exists (a zero-FLOP transfer
program, a backend without cost analysis — attribution degrades, never
divides by zero). ``top_k()`` names the worst offenders; that table is
``python -m photon_tpu.cli.profile``.

Windows: ``mark()`` snapshots the accumulators; ``attribution_since``
returns the delta as named rows plus an EXPLICIT ``unattributed`` row
(the residual against a measured wall), so a bench scenario or a pilot
cycle can say "95% of this window has a name on it" — the acceptance
bar the profile-smoke CI job enforces.

OFF BY DEFAULT, and off means off: every hook is a single flag check,
``register_program`` no-ops (a disabled run adds ZERO programs to the
census), and nothing is ever lowered or priced. Enabling changes host
bookkeeping only — the audited tier-2 ``ledger`` contract
(``photon_tpu/obs/__init__.py`` PROGRAM_AUDIT, machinery in
``analysis/program.build_ledger``) proves the traced programs stay
byte-identical with the ledger armed.
"""

from __future__ import annotations

import threading
import time

from photon_tpu.analysis.costmodel import DEFAULT_CHIP, roofline

# The coordinate slot for costs that belong to no single coordinate
# (the serve ladder, slab materialization, whole-program rows).
NO_COORDINATE = "-"
# The program name of the explicit residual row in attribution windows.
UNATTRIBUTED = "unattributed"

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). Rows are written from every pool the runtime owns —
# the serve worker times score dispatches, the ingest pipeline's
# background compile thread records compile seconds, the training
# thread records fit windows — and read by exporters/reports on any
# thread; all state lives under the one module lock. The recording
# helpers are the thread-entry surface. Reports and snapshots copy
# under the lock and join/price OUTSIDE it (cost thunks may lower a
# program — never inside a lock a dispatch path takes).
CONCURRENCY_AUDIT = dict(
    name="obs-ledger",
    locks={
        "_lock": (
            "_enabled",
            "_programs",
            "_rows",
            "_compiles",
            "_resident",
            "_resident_peak",
            "_last_end",
        ),
    },
    thread_entries=(
        "record_dispatch",
        "record_unattributed",
        "record_compile",
        "set_resident",
        "register_program",
    ),
    jax_dispatch_ok={},
)

_lock = threading.Lock()
_enabled = False
# program key -> {"phase", "cost", "cost_thunk"} — cost is the cached
# {"flops", "hbm_bytes", ...} dict once the thunk has been priced.
_programs: dict[str, dict] = {}
# (coordinate, phase, program) -> {"seconds", "dispatches",
# "host_gap_seconds"}
_rows: dict[tuple, dict] = {}
# cache key -> {"seconds", "count"}
_compiles: dict[str, dict] = {}
_resident: dict[str, float] = {}
_resident_peak = 0.0
_last_end: float | None = None


def enable() -> None:
    """Arm the ledger (host bookkeeping only; the audited ``ledger``
    contract pins that traced programs are byte-identical either way)."""
    global _enabled
    with _lock:
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop every accumulator (census, rows, compiles, resident
    account, watermark). Does not touch the enabled flag — the same
    contract as ``obs.reset``."""
    global _resident_peak, _last_end
    with _lock:
        _programs.clear()
        _rows.clear()
        _compiles.clear()
        _resident.clear()
        _resident_peak = 0.0
        _last_end = None


# --------------------------------------------------------------------------
# recording (the hot-path surface: one flag check when disabled)
# --------------------------------------------------------------------------


def register_program(
    program: str,
    *,
    phase: str,
    cost: dict | None = None,
    cost_thunk=None,
) -> None:
    """Add one compiled program to the census (no-op when disabled —
    a ledger-off run adds ZERO programs).

    ``cost`` is a ready ``{"flops", "hbm_bytes"}`` dict
    (``costmodel.program_cost`` output); ``cost_thunk`` is a zero-arg
    callable producing one, invoked lazily at REPORT time so no
    dispatch path ever pays a lowering. Re-registration refreshes the
    thunk (a new estimator generation re-keys the same program name)
    but keeps an already-priced cost unless a fresh one is given.
    """
    if not _enabled:
        return
    with _lock:
        entry = _programs.get(program)
        if entry is None:
            entry = _programs[program] = {
                "phase": phase, "cost": None, "cost_thunk": None,
            }
        entry["phase"] = phase
        if cost is not None:
            entry["cost"] = dict(cost)
        if cost_thunk is not None:
            entry["cost_thunk"] = cost_thunk


def _row_locked(key: tuple) -> dict:
    """Get-or-create one accumulator row; caller holds ``_lock`` (the
    ``_locked`` suffix is the calling convention)."""
    row = _rows.get(key)
    if row is None:
        row = _rows[key] = {  # photon: ignore[unlocked-shared-write] -- called only from record_* bodies inside their `with _lock` scope (see docstring)
            "seconds": 0.0, "dispatches": 0, "host_gap_seconds": 0.0,
        }
    return row


def record_dispatch(
    program: str,
    seconds: float,
    *,
    phase: str,
    coordinate: str = NO_COORDINATE,
    start: float | None = None,
    end: float | None = None,
    parts: dict[str, float] | None = None,
) -> None:
    """Account one measured dispatch of ``program`` (no-op when
    disabled).

    ``start``/``end`` are ``time.perf_counter`` stamps of the dispatch
    window; when given, the idle gap since the PREVIOUS recorded
    dispatch's completion is charged to this program's
    ``host_gap_seconds`` — the between-dispatch host time the roofline
    gap says we are paying. ``parts`` distributes the measured seconds
    over coordinates (the fused fit's per-coordinate attribution);
    without it the whole window lands on ``coordinate``.

    Also drops one counter sample on the trace timeline
    (``ledger/<program>_seconds``, obs/trace.py) when telemetry is
    recording, so per-dispatch cost rides the exported Perfetto view as
    its own counter track.
    """
    if not _enabled:
        return
    global _last_end
    seconds = float(seconds)
    with _lock:
        if start is not None:
            if _last_end is not None and start > _last_end:
                _row_locked(
                    (coordinate if parts is None else NO_COORDINATE,
                     phase, program)
                )["host_gap_seconds"] += start - _last_end
            if end is not None:
                _last_end = end if _last_end is None else max(
                    _last_end, end)
        if parts:
            for cid, share in parts.items():
                row = _row_locked((str(cid), phase, program))
                row["seconds"] += float(share)
                row["dispatches"] += 1
        else:
            row = _row_locked((coordinate, phase, program))
            row["seconds"] += seconds
            row["dispatches"] += 1
    # Outside the ledger lock (the trace ring takes its own): one
    # counter sample per dispatch, only while telemetry records.
    try:
        from photon_tpu.obs import trace as obs_trace

        obs_trace.counter(
            f"ledger/{program}_seconds", seconds, ts=end,
        )
    except Exception:  # pragma: no cover — telemetry must never abort
        pass


def record_unattributed(
    seconds: float, *, phase: str = "host"
) -> None:
    """Account window time with no program on it (operand assembly,
    AOT-compile waits) as the EXPLICIT residual row — the ledger never
    silently drops wall clock it saw."""
    if not _enabled:
        return
    with _lock:
        row = _row_locked((NO_COORDINATE, phase, UNATTRIBUTED))
        row["seconds"] += float(seconds)
        row["dispatches"] += 1


def record_compile(key: str, seconds: float) -> None:
    """Account one compile under its cache key (no-op when disabled)."""
    if not _enabled:
        return
    with _lock:
        c = _compiles.get(key)
        if c is None:
            c = _compiles[key] = {"seconds": 0.0, "count": 0}
        c["seconds"] += float(seconds)
        c["count"] += 1


def set_resident(owner: str, nbytes: float) -> None:
    """Set one owner's live HBM bytes (a table, a slab set); the peak
    watermark tracks the max TOTAL ever observed across owners —
    including the transient double-residency of an off-path rebuild."""
    if not _enabled:
        return
    global _resident_peak
    with _lock:
        _resident[owner] = float(nbytes)
        total = sum(_resident.values())
        if total > _resident_peak:
            _resident_peak = total


def resident_total() -> float:
    with _lock:
        return sum(_resident.values())


# --------------------------------------------------------------------------
# snapshots, windows, and the priced report
# --------------------------------------------------------------------------


def snapshot() -> dict:
    """JSON-ready view of the raw accumulators (no pricing: cost
    thunks are NOT evaluated here — ``report()`` does that)."""
    with _lock:
        return {
            "enabled": _enabled,
            "programs": {
                k: {"phase": v["phase"], "cost": v["cost"]}
                for k, v in _programs.items()
            },
            "rows": [
                {
                    "coordinate": c, "phase": ph, "program": pr,
                    "seconds": row["seconds"],
                    "dispatches": row["dispatches"],
                    "host_gap_seconds": row["host_gap_seconds"],
                }
                for (c, ph, pr), row in sorted(_rows.items())
            ],
            "compiles": {k: dict(v) for k, v in sorted(
                _compiles.items())},
            "resident_bytes": dict(sorted(_resident.items())),
            "resident_peak_bytes": _resident_peak,
        }


def mark() -> dict | None:
    """Opaque window marker for ``attribution_since`` (None when the
    ledger is disabled — callers wire it unconditionally)."""
    if not _enabled:
        return None
    with _lock:
        return {
            "rows": {k: dict(v) for k, v in _rows.items()},
        }


def attribution_since(
    marker: dict | None, wall_seconds: float | None = None
) -> dict:
    """The window's costs as named rows + the explicit residual.

    Rows are the per-(coordinate, phase, program) DELTAS since
    ``marker`` (None = since reset). With a measured ``wall_seconds``,
    the ``unattributed`` row is the wall minus every named second (the
    recorded residual rows fold into it — never double-counted), and
    ``attributed_fraction`` is named/wall; without a wall, the recorded
    residual rows alone are the unattributed account.
    """
    base = (marker or {}).get("rows", {})
    with _lock:
        deltas: dict[tuple, dict] = {}
        for key, row in _rows.items():
            prev = base.get(key)
            d = {
                "seconds": row["seconds"]
                - (prev["seconds"] if prev else 0.0),
                "dispatches": row["dispatches"]
                - (prev["dispatches"] if prev else 0),
                "host_gap_seconds": row["host_gap_seconds"]
                - (prev["host_gap_seconds"] if prev else 0.0),
            }
            if d["dispatches"] or d["seconds"] or d["host_gap_seconds"]:
                deltas[key] = d
    named: list[dict] = []
    recorded_residual = 0.0
    for (c, ph, pr), d in sorted(deltas.items()):
        if pr == UNATTRIBUTED:
            recorded_residual += d["seconds"]
            continue
        named.append({
            "coordinate": c, "phase": ph, "program": pr,
            "seconds": round(d["seconds"], 6),
            "dispatches": d["dispatches"],
            "host_gap_seconds": round(d["host_gap_seconds"], 6),
        })
    named.sort(key=lambda r: -r["seconds"])
    attributed = sum(r["seconds"] for r in named)
    if wall_seconds is not None:
        unattributed = max(float(wall_seconds) - attributed, 0.0)
        fraction = (
            attributed / float(wall_seconds) if wall_seconds else None
        )
    else:
        unattributed = recorded_residual
        total = attributed + unattributed
        fraction = (attributed / total) if total > 0.0 else None
    rows = named + [{
        "coordinate": NO_COORDINATE, "phase": "host",
        "program": UNATTRIBUTED,
        "seconds": round(unattributed, 6),
        "dispatches": 0, "host_gap_seconds": 0.0,
    }]
    return {
        "rows": rows,
        "attributed_seconds": round(attributed, 6),
        "unattributed_seconds": round(unattributed, 6),
        "attributed_fraction": (
            None if fraction is None else round(min(fraction, 1.0), 4)
        ),
    }


def _priced_cost(program: str) -> dict | None:
    """The program's static cost, pricing (and caching) the lazy thunk
    on first use. A failing thunk degrades to measured-only — the
    error is cached so one broken lowering is priced once, not per
    report row."""
    with _lock:
        entry = _programs.get(program)
        if entry is None:
            return None
        cost = entry["cost"]
        thunk = entry["cost_thunk"]
    if cost is not None or thunk is None:
        return cost
    try:
        cost = dict(thunk())
    except Exception as exc:  # noqa: BLE001 — degrade, never abort
        cost = {"error": repr(exc)}
    with _lock:
        entry = _programs.get(program)
        if entry is not None and entry["cost"] is None:
            entry["cost"] = cost
            entry["cost_thunk"] = None
    return cost


def _blocking_reason(row: dict, roof: dict | None) -> str:
    """Why this row's measured seconds exceed its lower bound:
    host idle between dispatches, the chip's HBM pipe, or its FLOPs —
    or measured-only when the program has no static cost to bound it."""
    if row["host_gap_seconds"] >= row["seconds"] > 0.0:
        return "dispatch-gap"
    if roof is None or not roof.get("min_seconds"):
        return "measured-only"
    return "bandwidth" if roof["bound"] == "hbm" else "compute"


def report(chip: str = DEFAULT_CHIP) -> dict:
    """The priced ledger: every row joined to its program's static
    cost and roofline.

    Per row (only where both sides exist — zero-FLOP / cost-less
    programs keep their measured columns and a ``measured-only``
    blocking reason, never a division): achieved FLOP/s and bytes/s
    over the measured window, ``vs_roofline`` (measured seconds per
    dispatch over the program's own roofline lower bound), wasted
    seconds (measured minus bound x dispatches), and the blocking
    reason. Cost thunks are priced here, outside every lock a dispatch
    path takes.
    """
    snap = snapshot()
    # A parts-split program (the fused fit) spreads ONE program's
    # dispatches over several coordinate rows: each row carries only
    # its share of the program's static cost, or FLOPs would double-
    # count across rows and every per-coordinate vs_roofline /
    # wasted_seconds would compare a slice of the wall against the
    # WHOLE program's bound. The share is the row's fraction of the
    # program's total recorded seconds; shares sum to the program's
    # cost/waste by construction.
    prog_seconds: dict[str, float] = {}
    for row in snap["rows"]:
        if row["program"] != UNATTRIBUTED:
            prog_seconds[row["program"]] = (
                prog_seconds.get(row["program"], 0.0) + row["seconds"]
            )
    rows = []
    for row in snap["rows"]:
        out = dict(row)
        cost = _priced_cost(row["program"])
        roof = None
        if cost and not cost.get("error") and (
            cost.get("flops") or cost.get("hbm_bytes")
        ):
            roof = roofline(cost, chip)
        seconds = row["seconds"]
        n = row["dispatches"]
        if roof is not None and seconds > 0.0 and n > 0:
            total = prog_seconds.get(row["program"], 0.0)
            share = (seconds / total) if total > 0.0 else 1.0
            min_seconds = roof["min_seconds"] * share
            bound = min_seconds * n
            out["achieved_flops_per_sec"] = (
                cost.get("flops", 0.0) * share * n / seconds
            )
            out["achieved_hbm_bytes_per_sec"] = (
                cost.get("hbm_bytes", 0.0) * share * n / seconds
            )
            out["vs_roofline"] = (
                round((seconds / n) / min_seconds, 2)
                if min_seconds > 0.0 else None
            )
            out["wasted_seconds"] = round(max(seconds - bound, 0.0), 6)
            out["roofline_bound"] = roof["bound"]
        else:
            # Measured-only degradation: no static cost (or a pure-
            # transfer zero-cost program) — the measured columns stand
            # alone and every derived ratio is None, by contract.
            out["achieved_flops_per_sec"] = None
            out["achieved_hbm_bytes_per_sec"] = None
            out["vs_roofline"] = None
            out["wasted_seconds"] = round(seconds, 6)
            out["roofline_bound"] = None
        out["blocking"] = _blocking_reason(row, roof)
        if cost and cost.get("error"):
            out["cost_error"] = cost["error"]
        rows.append(out)
    rows.sort(key=lambda r: -(r["wasted_seconds"] or 0.0))
    return {
        "chip": chip,
        "enabled": snap["enabled"],
        "rows": rows,
        "programs": snap["programs"],
        "compiles": snap["compiles"],
        "resident_bytes": snap["resident_bytes"],
        "resident_peak_bytes": snap["resident_peak_bytes"],
    }


def top_k(k: int = 5, chip: str = DEFAULT_CHIP) -> list[dict]:
    """The k worst rows by wasted-seconds-vs-roofline (the profile
    CLI's table), residual rows excluded — they have no program to
    blame by construction."""
    rows = [
        r for r in report(chip)["rows"] if r["program"] != UNATTRIBUTED
    ]
    return rows[: max(int(k), 0)]


def render_top_k(k: int = 5, chip: str = DEFAULT_CHIP) -> str:
    """Human-readable top-k table (one line per row)."""
    rows = top_k(k, chip)
    if not rows:
        return "ledger: no dispatches recorded"
    head = [
        "coordinate", "phase", "program", "seconds", "disp",
        "gap_s", "wasted_s", "vs_roof", "blocking",
    ]
    table = [head]
    for r in rows:
        table.append([
            r["coordinate"], r["phase"], r["program"],
            f"{r['seconds']:.4f}", str(r["dispatches"]),
            f"{r['host_gap_seconds']:.4f}",
            f"{r['wasted_seconds']:.4f}",
            "-" if r["vs_roofline"] is None else f"{r['vs_roofline']:g}",
            r["blocking"],
        ])
    widths = [max(len(row[i]) for row in table) for i in range(len(head))]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        for row in table
    )


# --------------------------------------------------------------------------
# the /metrics collector (obs/monitor.py appends it on every scrape)
# --------------------------------------------------------------------------


def metrics_families() -> list[dict]:
    """``ledger_*`` metric families for the monitor exporter — empty
    when the ledger is disabled, so an unarmed process scrapes exactly
    what it scraped before this module existed."""
    snap = snapshot()
    if not snap["enabled"]:
        return []
    from photon_tpu.obs.monitor import family

    fams = []
    row_labels = [
        (
            {
                "coordinate": r["coordinate"],
                "phase": r["phase"],
                "program": r["program"],
            },
            r,
        )
        for r in snap["rows"]
    ]
    if row_labels:
        fams.append(family(
            "ledger_dispatch_seconds_total", "counter",
            "measured wall seconds per (coordinate, phase, program) "
            "ledger row",
            [("", labels, row["seconds"]) for labels, row in row_labels],
        ))
        fams.append(family(
            "ledger_dispatches_total", "counter",
            "dispatches per ledger row",
            [("", labels, float(row["dispatches"]))
             for labels, row in row_labels],
        ))
        fams.append(family(
            "ledger_host_gap_seconds_total", "counter",
            "host idle seconds between consecutive dispatches, charged "
            "to the program that dispatched next",
            [("", labels, row["host_gap_seconds"])
             for labels, row in row_labels],
        ))
    fams.append(family(
        "ledger_programs_registered", "gauge",
        "compiled programs in the ledger census (0 when the ledger "
        "is off: a disabled run registers nothing)",
        [("", {}, float(len(snap["programs"])))],
    ))
    if snap["compiles"]:
        fams.append(family(
            "ledger_compile_seconds_total", "counter",
            "compile seconds per cache key",
            [("", {"key": k}, v["seconds"])
             for k, v in snap["compiles"].items()],
        ))
    if snap["resident_bytes"]:
        fams.append(family(
            "ledger_resident_bytes", "gauge",
            "live HBM bytes per owner (coefficient tables, fused-fit "
            "slabs)",
            [("", {"owner": k}, v)
             for k, v in snap["resident_bytes"].items()],
        ))
    fams.append(family(
        "ledger_resident_peak_bytes", "gauge",
        "peak watermark of total accounted resident bytes",
        [("", {}, snap["resident_peak_bytes"])],
    ))
    return fams


def tree_nbytes(tree) -> int:
    """Total buffer bytes of a pytree of arrays (host metadata only —
    never pulls device data). The resident-account helper the fused
    fit and serving tables share."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total
