"""photon_tpu.obs — unified runtime telemetry.

One coherent layer over what used to be four unconnected surfaces
(``utils/timed.py`` section logs, ``data/pipeline.py::PIPELINE_STATS``,
``utils/compile_cache.cache_stats()``, and the ``events.py`` listener
bus): hierarchical **spans** with a host/device split measured only at
span roots (``obs/spans.py``), a labeled **metrics registry**
(``obs/metrics.py``), **async device-side convergence traces** computed
inside the already-traced fit programs (``obs/convergence.py``), and
**exporters** — ``snapshot()`` for bench/driver JSON, a documented JSONL
stream, and an end-of-run text table (``obs/export.py``; schema in
OBSERVABILITY.md).

Telemetry is OFF by default and enabling it is a host-side decision
only: the device programs are identical either way. That is not a
promise but an audited contract — see PROGRAM_AUDIT below.

Usage::

    from photon_tpu import obs

    obs.enable()
    with obs.span("prepare"):
        datasets, _ = est.prepare(data)
    ...
    print(obs.summary_table())
    obs.write_jsonl("run-telemetry.jsonl")
"""

from __future__ import annotations

import contextlib
import logging
import time

from photon_tpu.obs import convergence
from photon_tpu.obs import fleet
from photon_tpu.obs import flight
from photon_tpu.obs import health
from photon_tpu.obs import ledger
from photon_tpu.obs import trace


def __getattr__(name: str):
    # Lazy submodule (PEP 562): `photon_tpu.obs` is imported by every
    # training/serving path, and eagerly pulling obs.monitor would tax
    # each of them with the http.server import chain for a surface
    # only `--monitor-port` users touch. `from photon_tpu.obs import
    # monitor` still works — the from-import falls back to this hook.
    if name == "monitor":
        import importlib

        return importlib.import_module("photon_tpu.obs.monitor")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
from photon_tpu.obs.export import (
    snapshot,
    summary_table,
    validate_jsonl,
    write_jsonl,
)
from photon_tpu.obs.metrics import (
    REGISTRY,
    MetricsRegistry,
    metrics_listener,
)
from photon_tpu.obs.spans import Span, SpanTracer
from photon_tpu.obs.trace import profile_session, write_chrome_trace

TRACER = SpanTracer()
span = TRACER.span

# Program contracts (audited by `python -m photon_tpu.analysis
# --semantic`; machinery in analysis/program.py build_telemetry /
# build_trace):
#
# - `telemetry`: the instrumented public entry points — the fused
#   materialize + whole-fit programs, the ones every obs span and
#   convergence trace hangs off — must trace to BYTE-IDENTICAL jaxprs
#   with telemetry enabled vs disabled. Zero new dispatches (census
#   bound is the fused generation's own 2 programs), zero host
#   callbacks (hot_loop), identical recompile keys
#   (stable_under=telemetry_toggle). Convergence metrics achieve this
#   by being UNCONDITIONAL outputs of the fit program: the enable flag
#   only controls host-side recording, never the trace.
# - `trace`: the SAME bar for the timeline layer (obs/trace.py +
#   obs/flight.py): with telemetry enabled, a flight recorder
#   installed, and instants/counters/request records being emitted, the
#   traced programs stay byte-identical to the all-off base
#   (stable_under=trace_toggle) — events and dumps are host-ring
#   bookkeeping, never a traced operand or callback.
PROGRAM_AUDIT = [
    dict(
        name="telemetry",
        entry="obs instrumentation over algorithm.fused_fit "
        "(materialize + whole-fit programs, telemetry on vs off)",
        builder="build_telemetry",
        max_programs=2,
        stable_under=("telemetry_toggle",),
        hot_loop=True,
    ),
    dict(
        name="trace",
        entry="obs.trace event ring + obs.flight recorder over "
        "algorithm.fused_fit (tracing fully armed vs off)",
        builder="build_trace",
        max_programs=2,
        stable_under=("trace_toggle",),
        hot_loop=True,
    ),
    # `monitor`: the live-monitoring layer (obs/monitor.py). The
    # serving score program is traced with the layer fully ARMED — the
    # HTTP exporter up and being scraped, the window ring / hotness
    # sketch / SLO tracker receiving observations from another thread —
    # and must stay byte-identical to the all-off base with ZERO added
    # programs: a scrape is host bookkeeping + socket I/O, never a
    # traced operand, a callback, or a recompile.
    dict(
        name="monitor",
        entry="obs.monitor exporter + window rings + SLO/hotness "
        "surfaces over serve.ScorePrograms (scrape under load vs "
        "all-off)",
        builder="build_monitor",
        max_programs=1,
        stable_under=("monitor_scrape",),
        hot_loop=True,
    ),
    # `ledger`: the cost-attribution layer (obs/ledger.py). The fused
    # materialize + whole-fit programs are traced with the ledger
    # fully ARMED — enabled, a program registered in the census,
    # dispatch/compile/resident records landing from the recording
    # helpers — and must stay byte-identical to the all-off base with
    # ZERO added programs: rows are host dicts under a host lock,
    # static cost is priced at report time from a lazy thunk, never
    # inside (or as) a traced program.
    dict(
        name="ledger",
        entry="obs.ledger cost-attribution census + dispatch rows "
        "over algorithm.fused_fit (ledger armed vs off)",
        builder="build_ledger",
        max_programs=2,
        stable_under=("ledger_toggle",),
        hot_loop=True,
    ),
    # `health`: the model/data-health layer (obs/health.py). The fused
    # materialize + whole-fit programs are traced with health fully
    # ARMED — enabled, a train sketch registered, the serve tap fed,
    # numerics sentinels parked — and must stay byte-identical to the
    # all-off base with ZERO added programs: sketches are host numpy,
    # the sentinel parks a reference to an array the fit ALREADY
    # outputs (the convergence block), and every scan/compare happens
    # at report time, never inside (or as) a traced program.
    dict(
        name="health",
        entry="obs.health sketches + serve tap + numerics sentinels "
        "over algorithm.fused_fit (health armed vs off)",
        builder="build_health",
        max_programs=2,
        stable_under=("health_toggle",),
        hot_loop=True,
    ),
    # `fleet-obs`: the distributed-observability layer (obs/fleet.py).
    # The fused materialize + whole-fit programs are traced with fleet
    # shipping fully ARMED — identity stamped, the clock handshake
    # marked, a bundle committed to disk between traces — and must stay
    # byte-identical to the all-off base with ZERO added programs, zero
    # added collectives, and zero host callbacks in the hot loop:
    # identity is a cached host dict, clock samples are two time() reads,
    # and a bundle ship is ring snapshots + atomic file writes — never a
    # traced operand, a callback, or a cross-host exchange inside a
    # program.
    dict(
        name="fleet-obs",
        entry="obs.fleet identity/clock/bundle shipping over "
        "algorithm.fused_fit (fleet armed + bundle shipped vs off)",
        builder="build_fleet",
        max_programs=2,
        stable_under=("fleet_toggle",),
        hot_loop=True,
    ),
]


@contextlib.contextmanager
def logged_span(msg: str, log: logging.Logger | None = None):
    """A span that also keeps the reference's ``Timed`` logging contract
    ("<msg>: begin execution" / "<msg>: executed in <t> s",
    util/Timed.scala:53-80) — THE one logged-section helper; the CLI
    drivers and the deprecated ``utils.Timed`` shim all route here so the
    log contract and the span naming live in a single place."""
    log = log or logging.getLogger("photon_tpu.timed")
    log.info("%s: begin execution", msg)
    t0 = time.perf_counter()
    try:
        with span(msg):
            yield
    finally:
        log.info(
            "%s: executed in %.3f s", msg, time.perf_counter() - t0
        )


def enable() -> None:
    """Turn telemetry on: spans record, fit-level roots sync for the
    host/device split, convergence traces are parked for async fetch."""
    TRACER.enabled = True


def disable() -> None:
    TRACER.enabled = False


def enabled() -> bool:
    return TRACER.enabled


def reset() -> None:
    """Drop all recorded telemetry (spans, metrics, convergence traces,
    trace events, ledger accumulators, health sketches/sentinels).
    Does not touch the enabled flags."""
    TRACER.reset()
    REGISTRY.reset()
    convergence.reset()
    trace.reset()
    ledger.reset()
    health.reset()
    fleet.reset()


def set_span_retention(max_spans: int) -> None:
    """Rebind the completed-span ring's bound (default 4096; newest
    spans kept). The trace-event ring has ``obs.trace.set_retention``;
    drops feed the ``spans_dropped_total`` / ``trace_events_dropped_total``
    registry counters as well as the snapshot/JSONL headers."""
    TRACER.set_retention(max_spans)


__all__ = [
    "PROGRAM_AUDIT",
    "REGISTRY",
    "MetricsRegistry",
    "Span",
    "SpanTracer",
    "TRACER",
    "convergence",
    "disable",
    "enable",
    "enabled",
    "fleet",
    "flight",
    "health",
    "ledger",
    "logged_span",
    "metrics_listener",
    "monitor",
    "profile_session",
    "reset",
    "set_span_retention",
    "snapshot",
    "span",
    "summary_table",
    "trace",
    "validate_jsonl",
    "write_chrome_trace",
    "write_jsonl",
]
