"""photon_tpu.obs — unified runtime telemetry.

One coherent layer over what used to be four unconnected surfaces
(``utils/timed.py`` section logs, ``data/pipeline.py::PIPELINE_STATS``,
``utils/compile_cache.cache_stats()``, and the ``events.py`` listener
bus): hierarchical **spans** with a host/device split measured only at
span roots (``obs/spans.py``), a labeled **metrics registry**
(``obs/metrics.py``), **async device-side convergence traces** computed
inside the already-traced fit programs (``obs/convergence.py``), and
**exporters** — ``snapshot()`` for bench/driver JSON, a documented JSONL
stream, and an end-of-run text table (``obs/export.py``; schema in
OBSERVABILITY.md).

Telemetry is OFF by default and enabling it is a host-side decision
only: the device programs are identical either way. That is not a
promise but an audited contract — see PROGRAM_AUDIT below.

Usage::

    from photon_tpu import obs

    obs.enable()
    with obs.span("prepare"):
        datasets, _ = est.prepare(data)
    ...
    print(obs.summary_table())
    obs.write_jsonl("run-telemetry.jsonl")
"""

from __future__ import annotations

import contextlib
import logging
import time

from photon_tpu.obs import convergence
from photon_tpu.obs.export import (
    snapshot,
    summary_table,
    validate_jsonl,
    write_jsonl,
)
from photon_tpu.obs.metrics import (
    REGISTRY,
    MetricsRegistry,
    metrics_listener,
)
from photon_tpu.obs.spans import Span, SpanTracer

TRACER = SpanTracer()
span = TRACER.span

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py build_telemetry): the instrumented
# public entry points — the fused materialize + whole-fit programs, the
# ones every obs span and convergence trace hangs off — must trace to
# BYTE-IDENTICAL jaxprs with telemetry enabled vs disabled. Zero new
# dispatches (census bound is the fused generation's own 2 programs),
# zero host callbacks (hot_loop), identical recompile keys
# (stable_under=telemetry_toggle). Convergence metrics achieve this by
# being UNCONDITIONAL outputs of the fit program: the enable flag only
# controls host-side recording, never the trace.
PROGRAM_AUDIT = dict(
    name="telemetry",
    entry="obs instrumentation over algorithm.fused_fit "
    "(materialize + whole-fit programs, telemetry on vs off)",
    builder="build_telemetry",
    max_programs=2,
    stable_under=("telemetry_toggle",),
    hot_loop=True,
)


@contextlib.contextmanager
def logged_span(msg: str, log: logging.Logger | None = None):
    """A span that also keeps the reference's ``Timed`` logging contract
    ("<msg>: begin execution" / "<msg>: executed in <t> s",
    util/Timed.scala:53-80) — THE one logged-section helper; the CLI
    drivers and the deprecated ``utils.Timed`` shim all route here so the
    log contract and the span naming live in a single place."""
    log = log or logging.getLogger("photon_tpu.timed")
    log.info("%s: begin execution", msg)
    t0 = time.perf_counter()
    try:
        with span(msg):
            yield
    finally:
        log.info(
            "%s: executed in %.3f s", msg, time.perf_counter() - t0
        )


def enable() -> None:
    """Turn telemetry on: spans record, fit-level roots sync for the
    host/device split, convergence traces are parked for async fetch."""
    TRACER.enabled = True


def disable() -> None:
    TRACER.enabled = False


def enabled() -> bool:
    return TRACER.enabled


def reset() -> None:
    """Drop all recorded telemetry (spans, metrics, convergence traces).
    Does not touch the enabled flag."""
    TRACER.reset()
    REGISTRY.reset()
    convergence.reset()


__all__ = [
    "PROGRAM_AUDIT",
    "REGISTRY",
    "MetricsRegistry",
    "Span",
    "SpanTracer",
    "TRACER",
    "convergence",
    "disable",
    "enable",
    "enabled",
    "logged_span",
    "metrics_listener",
    "reset",
    "snapshot",
    "span",
    "summary_table",
    "validate_jsonl",
    "write_jsonl",
]
