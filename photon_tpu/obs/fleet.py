"""Distributed observability: host identity, clock-aligned telemetry
bundles, and the fleet merge/straggler machinery behind ``cli.fleetview``.

Every obs surface built so far (spans/metrics PR 4, timeline/flight
PR 8, monitor PR 9, ledger PR 12, health PR 13) is single-process: in a
``jax.distributed`` run each rank records its own rings on its own
``time.perf_counter`` clock and nothing joins them. This module is the
missing fleet layer, in three parts:

**Host identity.** ``host_identity()`` is the provenance block —
process_index/process_count, hostname, pid, device kind/count, jax
version, run id — stamped into every obs snapshot (``export.snapshot``),
JSONL header (``export.write_jsonl``), flight dump (``flight.py``), and
chrome-trace ``otherData`` (``trace.chrome_trace``), so no artifact from
a multi-process run is anonymous. Probing is lazy and guarded: jax is
only consulted when the process already imported it, so stamping never
initializes a backend as a side effect.

**Clock alignment.** Rings record on ``perf_counter`` (monotonic,
process-local, epoch-less); cross-host comparison needs the epoch clock.
The handshake samples the monotonic↔epoch offset twice — at
``maybe_init_distributed`` time (``mark_init``) and again at bundle
commit — as back-to-back (epoch, perf) pairs whose spread bounds the
sampling jitter. ``skew_bound_seconds`` = |offset_commit − offset_init|
+ both spreads: the drift the mapping could have accumulated over the
run plus the uncertainty of each measurement. The merge shifts each
host's events onto the shared epoch clock through its own offset, so
cross-host ordering in the merged timeline is trustworthy to that bound.

**Bundles + merge.** ``ship_bundle(run_dir)`` commits this rank's whole
obs state — spans JSONL (with raw t0/t1 for the timeline), metrics
snapshot, trace-event ring, ledger attribution rows, health state —
into ``<run_dir>/obs-host-<k>/`` via the atomic tmp+fsync+replace
discipline of ``io/model_io.atomic_write_bytes``. ``bundle.json`` is
written LAST and is the commit point: a rank that died mid-ship leaves
no bundle.json and the merge names the gap instead of reading a torn
artifact. ``merge_chrome_trace`` renders all bundles as ONE
Perfetto-loadable timeline (pid per rank,
``trace.validate_chrome_trace``-clean); ``straggler_report`` is the
fleet ledger rollup: per-rank attributed dispatch seconds, per-program
max−min window skew, the slowest rank, and a collective-vs-compute
split where each rank's barrier wait is the residual between the fleet
wall window and its own attributed compute — the wait a straggling peer
imposes through the collectives.

Degradation is first-class: a truncated spans.jsonl (crashed rank), an
unreadable bundle.json, or a missing rank all land in the ``gaps`` list
carried by both the merged trace's ``otherData`` and the straggler
report — a partial fleet still merges, it just says what is missing.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

BUNDLE_SCHEMA = 1
HOST_DIR_PREFIX = "obs-host-"
BUNDLE_FILE = "bundle.json"
SPANS_FILE = "spans.jsonl"

# Per-bundle ring clamps (the flight recorder's post-mortem-sized
# defaults would truncate a full run; bundles ship the whole ring).
_EVENT_LIMIT = 8192

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The cached identity block, run id, and init-time
# clock sample are module state written under the one module lock;
# ``ship_bundle`` may be called from any thread (the train driver, a
# pilot stage, an atexit hook) — it reads ring SNAPSHOTS via the other
# modules' own locks and writes files outside any lock. The merge side
# (discover/merge/report) only touches local state read from disk.
CONCURRENCY_AUDIT = dict(
    name="obs-fleet",
    locks={
        "_lock": ("_identity", "_run_id", "_init_clock"),
    },
    thread_entries=("ship_bundle",),
    jax_dispatch_ok={},
)

_lock = threading.Lock()
_identity: dict | None = None
_run_id: str | None = None
_init_clock: dict | None = None


# --------------------------------------------------------------------------
# host identity
# --------------------------------------------------------------------------


def _probe_identity() -> dict:
    """Assemble the provenance block for THIS process. jax is consulted
    only when the process already imported it — identity stamping must
    never initialize a backend as a side effect — and every jax query is
    guarded: a half-up runtime degrades to nulls, never to a failed
    snapshot/dump."""
    ident: dict = {
        "process_index": 0,
        "process_count": 1,
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "device_kind": None,
        "local_device_count": None,
        "global_device_count": None,
        "jax_version": None,
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        ident["jax_version"] = getattr(jax, "__version__", None)
        try:
            ident["process_index"] = int(jax.process_index())
            ident["process_count"] = int(jax.process_count())
            devices = jax.local_devices()
            ident["local_device_count"] = len(devices)
            ident["global_device_count"] = len(jax.devices())
            if devices:
                ident["device_kind"] = getattr(
                    devices[0], "device_kind", None
                )
        except Exception:  # noqa: BLE001 — backend not up / mid-teardown
            pass
    return ident


def host_identity(*, refresh: bool = False) -> dict:
    """The host-identity provenance block (cached; ``refresh=True``
    re-probes — bundle commit does, so a block cached before
    ``jax.distributed.initialize`` cannot ship a stale rank)."""
    global _identity
    with _lock:
        cached = _identity
    if cached is None or refresh:
        probed = _probe_identity()
        with _lock:
            _identity = probed
            cached = probed
    out = dict(cached)
    out["run_id"] = run_id()
    return out


def set_run_id(value: str | None) -> None:
    """Pin the fleet-shared run id (the coordinator mints one and the
    launcher exports it to every rank via ``PHOTON_RUN_ID``)."""
    global _run_id
    with _lock:
        _run_id = value


def run_id() -> str | None:
    """The run id: an explicit ``set_run_id`` wins, else the
    ``PHOTON_RUN_ID`` environment (how the multiprocess launcher shares
    one id across ranks), else None."""
    with _lock:
        rid = _run_id
    return rid if rid is not None else os.environ.get("PHOTON_RUN_ID")


def reset() -> None:
    """Drop the cached identity, run id, and init clock sample (joined
    into ``obs.reset()`` — identity re-probes lazily on next use)."""
    global _identity, _run_id, _init_clock
    with _lock:
        _identity = None
        _run_id = None
        _init_clock = None


# --------------------------------------------------------------------------
# clock alignment
# --------------------------------------------------------------------------


def clock_sample(n: int = 5) -> dict:
    """One monotonic↔epoch offset measurement: ``n`` back-to-back
    (epoch, perf_counter) pairs. ``offset`` maps perf_counter seconds
    onto the epoch clock (``epoch ≈ perf + offset``); ``spread`` (the
    max−min of the per-pair offsets) bounds the scheduling jitter of the
    measurement itself."""
    offsets = []
    epoch = perf = 0.0
    for _ in range(max(int(n), 1)):
        perf = time.perf_counter()
        epoch = time.time()
        offsets.append(epoch - perf)
    offsets.sort()
    return {
        "offset": offsets[len(offsets) // 2],
        "spread": offsets[-1] - offsets[0],
        "epoch": epoch,
        "perf_counter": perf,
    }


def mark_init() -> dict:
    """The init half of the clock-alignment handshake — called from
    ``cli.common.maybe_init_distributed`` (and the multiprocess dryrun
    children) right after the distributed runtime comes up. Also
    refreshes the cached identity so the rank probed is post-init."""
    sample = clock_sample()
    global _init_clock
    with _lock:
        _init_clock = sample
    host_identity(refresh=True)
    return sample


def init_clock() -> dict | None:
    with _lock:
        return None if _init_clock is None else dict(_init_clock)


def clock_alignment() -> dict:
    """The commit half of the handshake: a fresh offset sample paired
    with the init-time one. ``skew_bound_seconds`` bounds how far this
    host's perf→epoch mapping may have drifted over the run: the offset
    delta between the two samples plus both sampling spreads. With no
    init sample (single-process run that never called ``mark_init``) the
    commit sample stands alone and the bound is its own spread."""
    commit = clock_sample()
    init = init_clock() or commit
    bound = (
        abs(commit["offset"] - init["offset"])
        + commit["spread"]
        + init["spread"]
    )
    return {
        "init": init,
        "commit": commit,
        "skew_bound_seconds": bound,
    }


# --------------------------------------------------------------------------
# bundle shipping (the per-rank write side)
# --------------------------------------------------------------------------


def host_dir(run_dir: str, process_index: int) -> str:
    return os.path.join(run_dir, f"{HOST_DIR_PREFIX}{process_index}")


def ship_bundle(run_dir: str, *, extra: dict | None = None) -> str:
    """Commit this rank's obs state into ``<run_dir>/obs-host-<k>/``.

    Two files, both via the atomic tmp+fsync+replace discipline:
    ``spans.jsonl`` (telemetry header + one ``span`` record per
    completed span, carrying raw ``t0``/``t1`` perf_counter stamps for
    the timeline merge) and — LAST, as the commit point — ``bundle.json``
    (identity, clock alignment, metrics snapshot, trace-event ring,
    ledger attribution rows, health state). Returns the bundle dir.
    ``extra`` merges caller context (the dryrun ships its parity verdict
    through it) into the bundle's ``extra`` block.
    """
    from photon_tpu import obs
    from photon_tpu.obs import health, ledger
    from photon_tpu.obs import trace as obs_trace
    from photon_tpu.io.model_io import atomic_write_bytes

    ident = host_identity(refresh=True)
    out_dir = host_dir(run_dir, ident["process_index"])
    os.makedirs(out_dir, exist_ok=True)

    lines: list[dict] = [{
        "type": "telemetry",
        "version": 1,
        "spans_dropped": obs.TRACER.dropped,
        "host": ident,
    }]
    for sp in obs.TRACER.completed():
        lines.append(dict(sp.to_json(), t0=sp.t0, t1=sp.t1))
    payload = "".join(json.dumps(line) + "\n" for line in lines)
    atomic_write_bytes(
        os.path.join(out_dir, SPANS_FILE), payload.encode()
    )

    bundle: dict = {
        "schema": BUNDLE_SCHEMA,
        "host": ident,
        "clock": clock_alignment(),
        "metrics": obs.REGISTRY.snapshot(),
        "events": obs_trace.events()[-_EVENT_LIMIT:],
        "events_dropped": obs_trace.dropped(),
        "spans_dropped": obs.TRACER.dropped,
        "ledger": ledger.snapshot() if ledger.enabled() else None,
        "health": health.raw_snapshot() if health.enabled() else None,
        "extra": dict(extra or {}),
    }
    atomic_write_bytes(
        os.path.join(out_dir, BUNDLE_FILE),
        json.dumps(bundle).encode(),
    )
    return out_dir


# --------------------------------------------------------------------------
# discovery + merge (the fleetview read side)
# --------------------------------------------------------------------------


def discover_bundles(run_dir: str) -> tuple[list[dict], list[str]]:
    """Read every committed ``obs-host-*/`` bundle under ``run_dir``.

    Returns ``(bundles, gaps)``: each bundle is its ``bundle.json`` dict
    plus a ``"spans"`` list parsed from ``spans.jsonl`` and a ``"dir"``.
    Anything broken degrades to a NAMED gap, never an exception: a host
    dir without a committed bundle.json (rank died before the commit
    point), an unparseable bundle, or a truncated spans.jsonl (the span
    records before the tear are kept).
    """
    bundles: list[dict] = []
    gaps: list[str] = []
    try:
        entries = sorted(os.listdir(run_dir))
    except OSError as exc:
        return [], [f"{run_dir}: unreadable run dir ({exc})"]
    for name in entries:
        if not name.startswith(HOST_DIR_PREFIX):
            continue
        d = os.path.join(run_dir, name)
        if not os.path.isdir(d):
            continue
        bundle_path = os.path.join(d, BUNDLE_FILE)
        try:
            with open(bundle_path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            gaps.append(
                f"{name}: no committed bundle.json ({exc}) — rank "
                "died before the bundle commit point"
            )
            continue
        if not isinstance(bundle, dict) or "host" not in bundle:
            gaps.append(f"{name}: bundle.json missing the host block")
            continue
        spans, span_gap = _read_spans(os.path.join(d, SPANS_FILE))
        if span_gap:
            gaps.append(f"{name}: {span_gap}")
        bundle["spans"] = spans
        bundle["dir"] = d
        bundles.append(bundle)
    bundles.sort(
        key=lambda b: b.get("host", {}).get("process_index", 0)
    )
    return bundles, gaps


def _read_spans(path: str) -> tuple[list[dict], str | None]:
    """Parse a bundle's spans.jsonl; a torn tail (crashed rank) keeps
    every record before the tear and names the gap."""
    spans: list[dict] = []
    try:
        with open(path) as f:
            raw_lines = f.readlines()
    except OSError as exc:
        return [], f"spans.jsonl unreadable ({exc})"
    for lineno, raw in enumerate(raw_lines, 1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError:
            return spans, (
                f"spans.jsonl truncated at line {lineno} — kept "
                f"{len(spans)} span(s) before the tear"
            )
        if rec.get("type") == "span" and "t0" in rec and "t1" in rec:
            spans.append(rec)
    return spans, None


def _to_epoch(bundle: dict, t_perf: float) -> float:
    """Map a bundle's perf_counter stamp onto the epoch clock through
    its commit-time offset sample."""
    clock = bundle.get("clock") or {}
    commit = clock.get("commit") or {}
    return t_perf + float(commit.get("offset", 0.0))


def _bundle_rank(bundle: dict) -> int:
    return int(bundle.get("host", {}).get("process_index", 0))


def _epoch0(bundles: list[dict]) -> float:
    """The merged timeline's zero: the earliest epoch instant any
    bundle knows about (first span start, first ring event, else the
    commit sample itself)."""
    starts: list[float] = []
    for b in bundles:
        spans = b.get("spans", ())
        if spans:
            # Spans record in COMPLETION order (a parent completes after
            # its children), so the earliest start needs the full scan.
            starts.append(
                _to_epoch(b, min(float(sp["t0"]) for sp in spans))
            )
        for ev in b.get("events", ()) or ():
            if "ts" in ev:
                starts.append(_to_epoch(b, float(ev["ts"])))
                break
        commit = (b.get("clock") or {}).get("commit") or {}
        if "epoch" in commit:
            starts.append(float(commit["epoch"]))
    return min(starts) if starts else 0.0


def merge_chrome_trace(
    bundles: list[dict], gaps: tuple[str, ...] | list[str] = ()
) -> dict:
    """All bundles on ONE chrome-trace timeline: pid per rank, each
    host's perf_counter stamps shifted onto the shared epoch clock
    through its own offset, events sorted by fleet time. The document
    passes ``trace.validate_chrome_trace``; ``otherData`` carries the
    fleet provenance, per-host clock bounds, and any merge gaps."""
    from photon_tpu.obs.trace import _request_chrome_events, _us

    epoch0 = _epoch0(bundles)
    out: list[dict] = []
    hosts_meta: list[dict] = []
    skew_bounds: list[float] = []

    for b in bundles:
        ident = b.get("host", {})
        pid = _bundle_rank(b)
        clock = b.get("clock") or {}
        bound = float(clock.get("skew_bound_seconds", 0.0))
        skew_bounds.append(bound)
        hosts_meta.append({
            "process_index": pid,
            "hostname": ident.get("hostname"),
            "pid": ident.get("pid"),
            "run_id": ident.get("run_id"),
            "clock_skew_bound_seconds": bound,
            "spans": len(b.get("spans", ())),
            "events": len(b.get("events", ()) or ()),
        })

        def fleet_us(t_perf: float, b=b) -> float:
            return _us(_to_epoch(b, float(t_perf)) - epoch0)

        out.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {
                "name": f"rank {pid} · {ident.get('hostname', '?')}"
            },
        })
        out.append({
            "name": "process_sort_index", "ph": "M", "pid": pid,
            "args": {"sort_index": pid},
        })
        tids: dict[str, int] = {}

        def tid_for(thread: str, pid=pid, tids=tids) -> int:
            t = tids.get(thread)
            if t is None:
                t = tids[thread] = len(tids) + 1
                out.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": t, "args": {"name": thread},
                })
            return t

        for sp in b.get("spans", ()):
            args: dict = {"path": sp.get("path")}
            if sp.get("attrs"):
                args.update(sp["attrs"])
            if sp.get("device_wait_seconds") is not None:
                args["device_wait_seconds"] = sp["device_wait_seconds"]
            t0, t1 = float(sp["t0"]), float(sp["t1"])
            out.append({
                "name": sp.get("name", "span"), "cat": "span",
                "ph": "X", "ts": fleet_us(t0),
                "dur": _us(max(t1 - t0, 0.0)),
                "pid": pid, "tid": tid_for(sp.get("thread", "main")),
                "args": args,
            })
        for ev in b.get("events", ()) or ():
            kind = ev.get("kind")
            if kind == "instant":
                out.append({
                    "name": ev["name"], "cat": ev.get("cat", "event"),
                    "ph": "i", "s": "t", "ts": fleet_us(ev["ts"]),
                    "pid": pid,
                    "tid": tid_for(ev.get("thread", "events")),
                    "args": dict(ev.get("args") or {}),
                })
            elif kind == "counter":
                out.append({
                    "name": ev["name"], "ph": "C",
                    "ts": fleet_us(ev["ts"]), "pid": pid,
                    "args": {"value": ev["value"]},
                })
            elif kind == "request":
                shifted = dict(ev)
                for k, v in ev.items():
                    if k.endswith("_ts") and isinstance(v, (int, float)):
                        shifted[k] = _to_epoch(b, float(v)) - epoch0
                out.extend(_request_chrome_events(shifted, pid))

    # Stable fleet order: metadata first, then strictly by fleet time —
    # the "monotonic single timeline" the merge promises.
    out.sort(key=lambda ev: (ev["ph"] != "M", ev.get("ts", 0.0)))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "photon_tpu.obs.fleet",
            "schema": BUNDLE_SCHEMA,
            "epoch0": epoch0,
            "hosts": hosts_meta,
            "clock_skew_bound_seconds": (
                max(skew_bounds) if skew_bounds else 0.0
            ),
            "gaps": list(gaps),
        },
    }


# --------------------------------------------------------------------------
# fleet ledger rollup + straggler report
# --------------------------------------------------------------------------


def _rank_window(bundle: dict) -> tuple[float, float] | None:
    """A rank's dispatch window on the fleet epoch clock: first span
    start → last span end (spans are the recorded work envelope)."""
    spans = bundle.get("spans", ())
    if not spans:
        return None
    t0 = min(float(sp["t0"]) for sp in spans)
    t1 = max(float(sp["t1"]) for sp in spans)
    return _to_epoch(bundle, t0), _to_epoch(bundle, t1)


def _ledger_rows(bundle: dict) -> list[dict]:
    led = bundle.get("ledger") or {}
    return list(led.get("rows", ()) or ())


def straggler_report(
    bundles: list[dict], gaps: tuple[str, ...] | list[str] = ()
) -> dict:
    """The fleet ledger rollup + straggler analysis.

    Per rank: attributed dispatch seconds (sum of its ledger rows, the
    PR 12 attribution), dispatch count, and its work window on the fleet
    clock. Per program dispatched on all ranks: per-rank seconds and the
    max−min completion-window skew. The collective-vs-compute split is
    the barrier-wait residual: the fleet wall window is set by the
    slowest rank, every other rank spends (wall − own attributed
    seconds) waiting inside the collectives that keep SPMD ranks in
    lockstep, so ``collective_fraction`` = that wait summed over ranks /
    (ranks × wall). The split is an attribution *estimate* — gloo/ICI
    give no per-collective host timestamps — but its inputs (windows,
    attributed seconds, clock bound) are all measured.
    """
    per_rank: list[dict] = []
    windows: dict[int, tuple[float, float]] = {}
    attributed: dict[int, float] = {}
    prog_rank_seconds: dict[str, dict[int, float]] = {}
    prog_rank_windows: dict[str, dict[int, tuple[float, float]]] = {}
    skew_bounds: list[float] = []
    process_count = 0

    for b in bundles:
        rank = _bundle_rank(b)
        ident = b.get("host", {})
        process_count = max(
            process_count, int(ident.get("process_count", 1))
        )
        clock = b.get("clock") or {}
        skew_bounds.append(float(clock.get("skew_bound_seconds", 0.0)))
        rows = _ledger_rows(b)
        att = sum(float(r.get("seconds", 0.0)) for r in rows)
        dispatches = sum(int(r.get("dispatches", 0)) for r in rows)
        win = _rank_window(b)
        if win is not None:
            windows[rank] = win
        if not rows and win is not None:
            # Ledger-off rank: fall back to the span window as the
            # attributed envelope so the report still ranks it.
            att = win[1] - win[0]
        attributed[rank] = att
        for r in rows:
            prog = str(r.get("program", "?"))
            prog_rank_seconds.setdefault(prog, {})
            prog_rank_seconds[prog][rank] = (
                prog_rank_seconds[prog].get(rank, 0.0)
                + float(r.get("seconds", 0.0))
            )
        for sp in b.get("spans", ()):
            name = str(sp.get("name", "?"))
            e0 = _to_epoch(b, float(sp["t0"]))
            e1 = _to_epoch(b, float(sp["t1"]))
            by_rank = prog_rank_windows.setdefault(name, {})
            if rank in by_rank:
                w0, w1 = by_rank[rank]
                by_rank[rank] = (min(w0, e0), max(w1, e1))
            else:
                by_rank[rank] = (e0, e1)
        per_rank.append({
            "process_index": rank,
            "hostname": ident.get("hostname"),
            "pid": ident.get("pid"),
            "attributed_seconds": round(att, 6),
            "dispatches": dispatches,
            "window": (
                None if win is None else {
                    "start": win[0],
                    "end": win[1],
                    "seconds": round(win[1] - win[0], 6),
                }
            ),
        })

    ranks = sorted(attributed)
    process_count = max(process_count, len(ranks), 1)
    missing = [
        k for k in range(process_count) if k not in set(ranks)
    ]
    gaps = list(gaps) + [
        f"rank {k}: no bundle shipped" for k in missing
    ]

    wall = max(
        (w[1] - w[0] for w in windows.values()), default=0.0
    )
    total_wait = 0.0
    for row in per_rank:
        wait = max(0.0, wall - row["attributed_seconds"])
        row["collective_wait_seconds"] = round(wait, 6)
        total_wait += wait
    collective_fraction = (
        total_wait / (len(per_rank) * wall)
        if per_rank and wall > 0 else 0.0
    )

    straggler = None
    if attributed:
        worst = max(attributed, key=lambda k: attributed[k])
        straggler = {
            "process_index": worst,
            "attributed_seconds": round(attributed[worst], 6),
        }
    straggler_skew = (
        max(attributed.values()) - min(attributed.values())
        if attributed else 0.0
    )

    programs: dict[str, dict] = {}
    for prog in sorted(set(prog_rank_seconds) | set(prog_rank_windows)):
        secs = prog_rank_seconds.get(prog, {})
        wins = prog_rank_windows.get(prog, {})
        on_all = set(secs or wins) >= set(ranks) and bool(ranks)
        entry: dict = {
            "per_rank_seconds": {
                str(k): round(v, 6) for k, v in sorted(secs.items())
            },
            "on_all_ranks": on_all,
        }
        if wins:
            # max−min completion skew: spread of when each rank FINISHED
            # this program's window on the fleet clock.
            ends = {k: w[1] for k, w in wins.items()}
            entry["window_skew_seconds"] = round(
                max(ends.values()) - min(ends.values()), 6
            )
        if secs:
            entry["slowest_rank"] = max(secs, key=lambda k: secs[k])
            entry["seconds_skew"] = round(
                max(secs.values()) - min(secs.values()), 6
            )
        programs[prog] = entry

    return {
        "schema": BUNDLE_SCHEMA,
        "bundles": len(bundles),
        "process_count": process_count,
        "ranks": ranks,
        "missing_ranks": missing,
        "gaps": gaps,
        "per_rank": per_rank,
        "straggler": straggler,
        "straggler_skew_seconds": round(straggler_skew, 6),
        "wall_seconds": round(wall, 6),
        "collective_fraction": round(collective_fraction, 6),
        "clock_skew_bound_seconds": (
            max(skew_bounds) if skew_bounds else 0.0
        ),
        "programs": programs,
    }


def merge_run(
    run_dir: str,
    *,
    trace_path: str | None = None,
) -> tuple[dict, dict]:
    """Discover, merge, and report in one call (the fleetview CLI's and
    the multiprocess dryrun's entry point). Returns ``(report,
    trace_doc)``; ``trace_path`` additionally writes the merged
    timeline (atomically — the artifact CI validates)."""
    bundles, gaps = discover_bundles(run_dir)
    trace_doc = merge_chrome_trace(bundles, gaps)
    report = straggler_report(bundles, gaps)
    if trace_path is not None and bundles:
        from photon_tpu.io.model_io import atomic_write_bytes

        atomic_write_bytes(
            trace_path, json.dumps(trace_doc).encode()
        )
    return report, trace_doc


# --------------------------------------------------------------------------
# MULTICHIP artifact row + monitor-port arbitration
# --------------------------------------------------------------------------


def crosscheck_collective_census(report: dict, census_ops) -> dict:
    """Join the tier-6 STATIC collective census onto a merged fleet report.

    ``census_ops`` is the ordered collective op list the SPMD auditor
    extracted from the compiled HLO (``analysis.spmd
    .collective_sequence`` op names, or the sorted census). The runtime
    ledger observes collective *waits*; the static census says which
    collectives every rank is contractually issuing — joining the two
    makes a mismatched-collective hang attributable: a fleet whose
    static census is non-empty but whose merged run is missing ranks is
    presenting exactly the deadlock signature the ``--spmd``
    collective-order rule proves against. The entry is stored under
    ``report["collective_census"]`` (read by :func:`multichip_row` for
    the benchtrend ``multichip_collective_count`` gauge) and returned.
    """
    ops = [str(o) for o in census_ops]
    mismatches: list[str] = []
    if ops:
        for k in report.get("missing_ranks", ()):
            mismatches.append(
                f"static census orders {len(ops)} collective(s) "
                f"({' -> '.join(ops)}) but rank {k} shipped no bundle — "
                "a mismatched collective order presents exactly this "
                "way; cross-check the --spmd collective-order audit"
            )
    entry = {
        "source": "analysis.spmd",
        "ops": ops,
        "count": len(ops),
        "mismatches": mismatches,
    }
    report["collective_census"] = entry
    return entry


def multichip_row(report: dict, *, n_devices: int | None = None) -> dict:
    """Flatten a straggler report into the MULTICHIP_r*.json row shape.

    Schema 2 keeps the driver-era keys (``n_devices``, ``ok``) and adds
    the structured attribution benchtrend tracks (the ``multichip_*``
    gauges — since PR 20 also the dryrun wall clock, the hosts-reporting
    count, and the static collective count when
    :func:`crosscheck_collective_census` ran); the full report rides
    along under ``"report"``."""
    row = {
        "schema": 2,
        "n_devices": n_devices,
        "ok": bool(report.get("bundles")) and not report.get("gaps"),
        "process_count": report.get("process_count"),
        "bundles": report.get("bundles"),
        "per_rank_dispatch_seconds": {
            str(r["process_index"]): r["attributed_seconds"]
            for r in report.get("per_rank", ())
        },
        "multichip_straggler_skew_seconds": report.get(
            "straggler_skew_seconds"
        ),
        "multichip_collective_fraction": report.get(
            "collective_fraction"
        ),
        "multichip_clock_skew_bound_seconds": report.get(
            "clock_skew_bound_seconds"
        ),
        "multichip_wall_seconds": report.get("wall_seconds"),
        "multichip_hosts_reporting": len(report.get("ranks", ())),
        "report": report,
    }
    census = report.get("collective_census")
    if census is not None:
        row["multichip_collective_count"] = census.get("count")
    return row


def write_multichip_row(
    row: dict, *, root: str = ".", start: int = 1
) -> str:
    """Commit a MULTICHIP row into the next free ``MULTICHIP_r<NN>.json``
    slot under ``root`` (atomic; the dryrun driver's artifact)."""
    from photon_tpu.io.model_io import atomic_write_bytes

    n = start
    while os.path.exists(
        os.path.join(root, f"MULTICHIP_r{n:02d}.json")
    ):
        n += 1
    path = os.path.join(root, f"MULTICHIP_r{n:02d}.json")
    atomic_write_bytes(path, json.dumps(row, indent=1).encode())
    return path


def resolve_monitor_port(
    port: int, process_index: int | None = None
) -> int:
    """The per-rank /metrics bind port: ``port + process_index``, so
    several ranks sharing a host never collide on one ``--monitor-port``
    value. Port 0 (ephemeral, the OS picks) passes through untouched."""
    if port <= 0:
        return port
    k = (
        host_identity()["process_index"]
        if process_index is None else int(process_index)
    )
    return port + k
