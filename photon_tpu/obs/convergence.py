"""Async device-side convergence traces.

The fused whole-fit program (algorithm/fused_fit.py) computes a small
per-(CD-iteration, coordinate) convergence block INSIDE the already-traced
fit — extra outputs of the existing program, so the tier-2 dispatch
census is unchanged and the recompile keys are identical with telemetry
on or off (the audited ``telemetry`` contract). ``FusedFit.run`` hands
the device array here WITHOUT any host sync: the trace is "fetched
asynchronously" — the jax array reference is parked and only converted
to numpy when a consumer (``obs.snapshot()``, the JSONL exporter, a
test) actually reads it, by which point the fit has long completed.

Metric columns, in order (``METRICS``):

- ``loss``: the coordinate's final objective value from its solver
  (fixed-effect coordinates only — the batched per-entity solvers return
  iteration counts, not objective values; 0.0 for random effects);
- ``grad_norm``: final gradient norm at the solution (fixed-effect only,
  same reason);
- ``residual_delta_sq``: sum of squared change of the coordinate's score
  vector this sweep — the residual-bookkeeping convergence signal, and
  the one that exists for EVERY coordinate kind;
- ``weight_delta_sq``: sum of squared coefficient movement this sweep;
- ``weight_norm_sq``: squared norm of the new coefficient table.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

METRICS = (
    "loss",
    "grad_norm",
    "residual_delta_sq",
    "weight_delta_sq",
    "weight_norm_sq",
)

# Bounded: a bench steady-state loop runs dozens of fits; keeping every
# device buffer would pin HBM for telemetry nobody reads.
_MAX_TRACES = 8

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). `record()` runs on the training thread while
# exporters materialize traces from any thread; the parked-trace deque
# and the fit counter share the module lock. The device->host fetch in
# `_series` runs OUTSIDE the lock on purpose (a transfer under the lock
# would block `record()` for its duration — the `blocking-under-lock`
# rule's canonical case) with a double-checked swap installing the
# cached numpy array under the lock.
CONCURRENCY_AUDIT = dict(
    name="obs-convergence",
    locks={
        "_lock": ("_traces", "_fits_recorded"),
    },
    thread_entries=(),
    jax_dispatch_ok={},
)

_lock = threading.Lock()
_traces: deque = deque(maxlen=_MAX_TRACES)
_fits_recorded = 0


def reset() -> None:
    global _fits_recorded
    with _lock:
        _traces.clear()
        _fits_recorded = 0


def record(coordinates: tuple[str, ...], array) -> None:
    """Park one fit's [num_iters, len(coordinates), len(METRICS)] device
    array. No sync, no host transfer — pure reference bookkeeping."""
    global _fits_recorded
    with _lock:
        _traces.append({"coordinates": tuple(coordinates), "array": array})
        _fits_recorded += 1


def _series(t: dict) -> dict:
    """Materialize one parked trace (device->host fetch cached per
    entry: repeated consumers — snapshot then write_jsonl — pay the
    transfer once, which matters on tunneled backends where every pull
    is a ~100ms round trip).

    Double-checked swap: the transfer itself runs OUTSIDE the module
    lock — a concurrent exporter must never block the training thread's
    ``record()`` for the duration of a device->host pull — and the
    cache installs atomically under the lock (a lost race wastes one
    duplicate transfer, never corrupts the entry)."""
    with _lock:
        arr = t.get("np")
        dev = t.get("array")
    if arr is None:
        fetched = np.asarray(dev)
        with _lock:
            arr = t.get("np")
            if arr is None:
                arr = t["np"] = fetched
                t["array"] = None  # drop the device ref once fetched
    return {
        cid: {
            m: [float(v) for v in arr[:, j, k]]
            for k, m in enumerate(METRICS)
        }
        for j, cid in enumerate(t["coordinates"])
    }


def traces() -> list[dict]:
    """Materialized traces, oldest first: per fit a dict
    ``{coordinate: {metric: [per-iteration floats]}}``.

    The fetch inside ``_series`` is the deferred one — by consumption
    time the fit finished, so this is a plain device->host copy, not a
    sync inside any hot loop.
    """
    with _lock:
        parked = list(_traces)
    return [_series(t) for t in parked]


def snapshot() -> dict:
    """JSON-ready summary: fit count, metric names, and the LAST fit's
    full per-coordinate series (the one consumers chart). Only the
    newest trace is materialized here — older parked fits stay on
    device until something (the JSONL exporter) actually reads them."""
    with _lock:
        n = _fits_recorded
        last = _traces[-1] if _traces else None
    return {
        "fits_recorded": n,
        "metrics": list(METRICS),
        "last": None if last is None else _series(last),
    }
