"""Crash flight recorder: the last N seconds of timeline, on disk, always.

A crashed run used to leave nothing behind — the spans, events, and
metric movement that explain the crash died with the process. The
recorder fixes that the way an aircraft FDR does: the telemetry layer's
bounded rings (completed spans, trace events) ARE the recording, and a
dump writes their tails plus metric deltas to
``flight-<pid>.json`` — atomically (tmp + fsync + rename, the
checkpoint discipline of ``resilience/checkpoint.py``) so a dump
interrupted by the dying process never leaves a half-written artifact.

Dumps fire on:

- **signals** — ``install(signals=True)`` chains SIGINT/SIGTERM: dump,
  then the previous handler runs (or the default disposition is
  restored and re-raised, so exit codes keep their signal semantics).
  ``cli.train`` keeps its own handlers (they drive the emergency
  checkpoint) and calls ``dump()`` explicitly from that path instead —
  the post-mortem and the recovery point are committed together.
- **unhandled exceptions** — ``install()`` chains ``sys.excepthook``.
- **``crash``-kind injected faults** — a listener registered with
  ``resilience.faults.on_crash`` dumps at the raise point, so chaos
  runs always leave a post-mortem even when a caller catches
  ``InjectedCrash``.

Installing the recorder ENABLES telemetry recording (and uninstall
restores the prior flag): a flight recorder with empty rings records
nothing, and the recording it turns on is the audited zero-overhead
host layer (the tier-2 ``telemetry``/``trace`` contracts) — never a
device-side cost. The CLIs install it by default (``--no-flight`` opts
out; ``--flight-dir`` picks the destination).

Retention is whatever the rings hold (``obs.set_span_retention`` /
``obs.trace.set_retention``), further clamped per dump by
``span_limit``/``event_limit`` so a dump stays a readable post-mortem,
not a full history.
"""

from __future__ import annotations

import json
import logging
import os
import signal as _signal
import sys
import threading
import time

logger = logging.getLogger(__name__)

_DEFAULT_SPAN_LIMIT = 512
_DEFAULT_EVENT_LIMIT = 1024
# How long a signal handler waits for the off-thread dump before
# letting the process die post-mortem-less (see _on_signal).
_SIGNAL_DUMP_TIMEOUT_S = 5.0

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The one installed-recorder reference is swapped under
# the module lock (install/uninstall from the driver thread; dump reads
# it from signal handlers, the excepthook, and the faults crash path on
# whatever thread crashes). The dump itself runs on ring SNAPSHOTS and
# writes files outside any lock.
CONCURRENCY_AUDIT = dict(
    name="obs-flight",
    locks={
        "_lock": ("_recorder",),
    },
    thread_entries=(),
    jax_dispatch_ok={},
)

_lock = threading.Lock()
_recorder: "FlightRecorder | None" = None


class FlightRecorder:
    """One installed recorder; use ``install()``/``uninstall()`` rather
    than constructing directly (the module keeps the single reference
    the signal/excepthook/crash paths consult)."""

    def __init__(
        self,
        directory: str,
        *,
        span_limit: int = _DEFAULT_SPAN_LIMIT,
        event_limit: int = _DEFAULT_EVENT_LIMIT,
    ):
        self.directory = directory
        self.span_limit = int(span_limit)
        self.event_limit = int(event_limit)
        self.installed_unix = time.time()
        # Counter baseline for the dump's deltas: "what moved since the
        # recorder went in" is the post-mortem question.
        from photon_tpu import obs

        self._baseline = dict(obs.REGISTRY.snapshot()["counters"])
        self._prev_enabled: bool | None = None
        self._prev_handlers: dict = {}
        self._prev_excepthook = None
        self._crash_listener = None
        # Both set by install(); reinstall re-arms with the same choices.
        self._signals = False
        self._enable = True

    # -- dump ------------------------------------------------------------

    def dump(self, reason: str) -> str | None:
        """Write ``flight-<pid>.json`` atomically (multi-process runs
        suffix the rank: ``flight-<pid>-r<process_index>.json``, so two
        ranks on one box can never clobber or confuse each other's
        post-mortems); returns the path, or None if the dump failed (a
        failing dump must never mask the crash it is documenting — it
        logs and returns)."""
        try:
            # THE shared tmp+fsync+replace+dir-fsync dance (PR 7) — a
            # power loss right after the rename must not lose the one
            # post-mortem, and a failed dump must not leave tmp debris.
            from photon_tpu.io.model_io import atomic_write_bytes

            payload = self._payload(reason)
            os.makedirs(self.directory, exist_ok=True)
            host = payload.get("host") or {}
            stem = f"flight-{os.getpid()}"
            if (host.get("process_count") or 1) > 1:
                stem += f"-r{host.get('process_index', 0)}"
            path = os.path.join(self.directory, f"{stem}.json")
            atomic_write_bytes(path, json.dumps(payload).encode())
            return path
        except Exception:  # noqa: BLE001 — the crash path stays alive
            logger.exception("flight-recorder dump failed (%s)", reason)
            return None

    def _payload(self, reason: str) -> dict:
        """Assemble the post-mortem. Each section is independently
        guarded: one wedged surface (a poisoned device array behind a
        convergence fetch) must not cost the rest of the dump."""
        from photon_tpu import obs
        from photon_tpu.obs import trace as obs_trace

        out: dict = {
            "schema": 1,
            "reason": reason,
            "pid": os.getpid(),
            "time_unix": time.time(),
            "perf_counter": time.perf_counter(),
            "installed_unix": self.installed_unix,
        }
        try:
            from photon_tpu.obs import fleet

            out["host"] = fleet.host_identity()
        except Exception as exc:  # noqa: BLE001
            out["host_error"] = repr(exc)
        try:
            spans = obs.TRACER.completed()[-self.span_limit:]
            out["spans"] = [
                dict(sp.to_json(), t0=sp.t0, t1=sp.t1) for sp in spans
            ]
            out["spans_dropped"] = obs.TRACER.dropped
        except Exception as exc:  # noqa: BLE001
            out["spans_error"] = repr(exc)
        try:
            out["events"] = obs_trace.events()[-self.event_limit:]
            out["events_dropped"] = obs_trace.dropped()
        except Exception as exc:  # noqa: BLE001
            out["events_error"] = repr(exc)
        try:
            snap = obs.REGISTRY.snapshot()
            out["metrics"] = snap
            out["counter_deltas"] = {
                k: v - self._baseline.get(k, 0.0)
                for k, v in snap["counters"].items()
                if v != self._baseline.get(k, 0.0)
            }
        except Exception as exc:  # noqa: BLE001
            out["metrics_error"] = repr(exc)
        try:
            from photon_tpu.resilience import faults, retry_stats

            out["retry_stats"] = retry_stats()
            out["faults_fired"] = faults.fired()
        except Exception as exc:  # noqa: BLE001
            out["resilience_error"] = repr(exc)
        try:
            from photon_tpu.obs import ledger

            if ledger.enabled():
                # Raw accumulators only (snapshot never prices a cost
                # thunk): a dump must not lower programs while the
                # process is dying.
                out["ledger"] = ledger.snapshot()
        except Exception as exc:  # noqa: BLE001
            out["ledger_error"] = repr(exc)
        try:
            from photon_tpu.obs import health

            if health.enabled():
                # Counters + last gate decision only (raw_snapshot):
                # a dump must not fetch parked sentinel device arrays
                # while the process is dying — same policy as the
                # ledger's never-price-mid-crash rule.
                out["health"] = health.raw_snapshot()
        except Exception as exc:  # noqa: BLE001
            out["health_error"] = repr(exc)
        return out

    # -- hooks -----------------------------------------------------------

    def _on_signal(self, signum, frame):
        # dump() takes the tracer/ring/registry locks, and a Python
        # signal handler runs on the main thread BETWEEN BYTECODES —
        # possibly inside one of those very `with lock:` blocks (span
        # completion is constant in a serving process). An inline dump
        # would self-deadlock on the non-reentrant lock and the
        # SIGTERM'd process would hang instead of dying. A daemon
        # thread takes the locks safely (the main thread parks in the
        # join, holding nothing in the common case); the bounded join
        # gives up the post-mortem — never the exit — when the
        # interrupted thread does hold one.
        t = threading.Thread(
            target=self.dump, args=(f"signal:{signum}",),
            name="flight-signal-dump", daemon=True,
        )
        t.start()
        t.join(timeout=_SIGNAL_DUMP_TIMEOUT_S)
        if t.is_alive():  # pragma: no cover — needs a lock-holding race
            logger.error(
                "flight-recorder dump wedged on signal %d; exiting "
                "without a post-mortem", signum,
            )
        prev = self._prev_handlers.get(signum)
        if prev is _signal.SIG_IGN:
            return
        if callable(prev):
            prev(signum, frame)
            return
        # Default disposition: restore it and re-raise so the process
        # dies with the signal's own exit semantics (a SIGTERM'd serve
        # process must still read as SIGTERM'd to its supervisor).
        _signal.signal(signum, prev if prev is not None else _signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    def _on_exception(self, exc_type, exc, tb):
        self.dump(f"exception:{exc_type.__name__}")
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)

    def _on_crash_fault(self, point: str, message: str) -> None:
        self.dump(f"fault.crash:{point}")


def install(
    directory: str,
    *,
    signals: bool = False,
    enable: bool = True,
    span_limit: int = _DEFAULT_SPAN_LIMIT,
    event_limit: int = _DEFAULT_EVENT_LIMIT,
) -> FlightRecorder:
    """Install the process flight recorder (replacing any prior one —
    ``reinstall`` hands a replaced recorder back).

    Chains ``sys.excepthook`` and the ``resilience.faults`` crash-fault
    listener; ``signals=True`` additionally chains SIGINT/SIGTERM (the
    serve CLI's mode — the train CLI keeps its own handlers and dumps
    from its emergency-checkpoint path). ``enable=True`` (default) turns
    telemetry recording on so the rings have content; the prior flag is
    restored on ``uninstall``.
    """
    rec = FlightRecorder(
        directory, span_limit=span_limit, event_limit=event_limit
    )
    rec._signals = bool(signals)
    rec._enable = bool(enable)
    return _arm(rec, enable=enable)


def reinstall(rec: FlightRecorder) -> FlightRecorder:
    """Re-arm a previously-uninstalled recorder: same directory, limits,
    counter baseline, signal mode, and enable choice (an ambient
    recorder installed with ``enable=False`` stays recording-off); every
    hook re-chained against the CURRENT process state. How the CLIs
    hand an embedding caller's ambient recorder back after their own
    default-on install replaced it — the caller's post-mortem coverage
    survives the nested run."""
    return _arm(rec, enable=rec._enable)


def _arm(rec: FlightRecorder, *, enable: bool) -> FlightRecorder:
    from photon_tpu import obs
    from photon_tpu.resilience import faults

    uninstall()
    rec._prev_enabled = obs.enabled()
    if enable:
        obs.enable()
    rec._prev_excepthook = sys.excepthook
    sys.excepthook = rec._on_exception
    rec._crash_listener = rec._on_crash_fault
    faults.on_crash(rec._crash_listener)
    rec._prev_handlers = {}
    if rec._signals:
        for sig in (_signal.SIGINT, _signal.SIGTERM):
            try:
                rec._prev_handlers[sig] = _signal.signal(
                    sig, rec._on_signal
                )
            except ValueError:  # pragma: no cover — non-main-thread embed
                pass
    with _lock:
        global _recorder
        _recorder = rec
    return rec


def uninstall() -> None:
    """Remove the installed recorder and restore every chained hook
    (telemetry flag, excepthook, signal handlers, crash listener).
    Idempotent."""
    with _lock:
        global _recorder
        rec, _recorder = _recorder, None
    if rec is None:
        return
    from photon_tpu import obs
    from photon_tpu.resilience import faults

    if rec._crash_listener is not None:
        faults.remove_crash_listener(rec._crash_listener)
    if sys.excepthook == rec._on_exception:
        sys.excepthook = rec._prev_excepthook or sys.__excepthook__
    for sig, prev in rec._prev_handlers.items():
        try:
            # A prior handler installed from C reads back as None —
            # signal.signal(None) is a TypeError; SIG_DFL is the same
            # substitution _on_signal's re-raise path makes.
            _signal.signal(sig, prev if prev is not None else _signal.SIG_DFL)
        except ValueError:  # pragma: no cover
            pass
    if rec._prev_enabled is not None:
        obs.TRACER.enabled = rec._prev_enabled


def installed() -> "FlightRecorder | None":
    return _recorder


def dump(reason: str) -> str | None:
    """Dump via the installed recorder; no-op (None) when none is
    installed — call sites wire it unconditionally."""
    rec = _recorder
    return rec.dump(reason) if rec is not None else None
