"""Telemetry exporters: snapshot dict, JSONL stream, text summary.

Three views over the same state (span tracer + metrics registry +
convergence traces + the pipeline/compile-cache reports they absorb):

- ``snapshot()``: one JSON-ready dict — what ``bench.py`` embeds under
  ``"telemetry"`` and what the train CLI folds into
  ``training-summary.json``;
- ``write_jsonl(path)``: the documented line-per-record stream
  (schema: OBSERVABILITY.md; ``validate_jsonl`` is the shared validator
  CI runs against the smoke artifact);
- ``summary_table()``: the end-of-run human-readable table.

JSONL SCHEMA (version 1) — one JSON object per line, discriminated by
``type``:

  {"type": "telemetry", "version": 1, "spans_dropped": 0,
   "host": {...}}  # header, first record; host = fleet identity block
  {"type": "span", "path", "name", "thread", "seconds",
   "device_wait_seconds": float|null, "attrs": {}}
  {"type": "counter", "series", "value"}
  {"type": "gauge", "series", "value"}
  {"type": "histogram", "series", "count", "sum", "min", "max"}
  {"type": "series", "name": "convergence", "fit", "coordinate",
   "metric", "values": [float, ...]}
  {"type": "report", "name": "pipeline"|"compile_cache", "data": {}}
  {"type": "request", "id", "outcome", "submit_ts", "done_ts",
   ...segment timestamps for served requests}   # obs/trace.py
"""

from __future__ import annotations

import json


def _absorbed_reports() -> tuple[dict, dict]:
    """The two pre-existing scalar surfaces the telemetry layer absorbs:
    the ingest pipeline's per-stage report and the persistent compile
    cache's hit/miss stats.

    Returns ``(reports, errors)``: a surface that fails to import or
    render lands as None in ``reports`` WITH its error recorded in
    ``errors`` — the exporters surface the degradation visibly (a
    ``report`` record noting it, a ``degraded_reports`` snapshot key)
    instead of silently dropping the section."""
    out: dict = {}
    errors: dict = {}
    try:
        from photon_tpu.data.pipeline import PIPELINE_STATS

        out["pipeline"] = PIPELINE_STATS.report()
    except Exception as exc:  # noqa: BLE001 — import cycles in odd embeds
        out["pipeline"] = None
        errors["pipeline"] = repr(exc)
    try:
        from photon_tpu.utils.compile_cache import cache_stats

        out["compile_cache"] = cache_stats()
    except Exception as exc:  # noqa: BLE001
        out["compile_cache"] = None
        errors["compile_cache"] = repr(exc)
    return out, errors


def snapshot() -> dict:
    """Everything the telemetry layer knows, as one JSON-ready dict —
    merged with the absorbed pipeline/compile-cache reports so one
    snapshot answers the whole "where did the time go" question."""
    from photon_tpu.obs import REGISTRY, convergence, enabled

    from photon_tpu.obs import TRACER

    from photon_tpu.obs import fleet

    out = {
        "enabled": enabled(),
        "host": fleet.host_identity(),
        "spans": _spans_aggregated(),
        "spans_dropped": TRACER.dropped,
        "metrics": REGISTRY.snapshot(),
        "convergence": convergence.snapshot(),
    }
    reports, errors = _absorbed_reports()
    out.update(reports)
    if errors:
        out["degraded_reports"] = errors
    from photon_tpu.obs import ledger

    if ledger.enabled():
        out["ledger"] = ledger.snapshot()
    from photon_tpu.obs import health

    if health.enabled():
        # Full view incl. the numerics report — by snapshot time the
        # fits completed, so materializing parked sentinels here is a
        # plain device->host copy (the convergence-trace policy).
        out["health"] = health.snapshot()
    return out


def _spans_aggregated() -> dict:
    from photon_tpu.obs import TRACER
    from photon_tpu.obs.spans import aggregate

    return aggregate(TRACER.completed())


def write_jsonl(path: str) -> int:
    """Write the full telemetry stream; returns the line count."""
    from photon_tpu.obs import TRACER, REGISTRY, convergence, fleet

    lines: list[dict] = [{
        "type": "telemetry",
        "version": 1,
        "spans_dropped": TRACER.dropped,
        "host": fleet.host_identity(),
    }]
    for sp in TRACER.completed():
        lines.append(sp.to_json())
    m = REGISTRY.snapshot()
    for series, value in sorted(m["counters"].items()):
        lines.append({"type": "counter", "series": series, "value": value})
    for series, value in sorted(m["gauges"].items()):
        lines.append({"type": "gauge", "series": series, "value": value})
    for series, h in sorted(m["histograms"].items()):
        lines.append({"type": "histogram", "series": series, **h})
    for fit_i, series in enumerate(convergence.traces()):
        for cid, by_metric in series.items():
            for metric, values in by_metric.items():
                lines.append({
                    "type": "series",
                    "name": "convergence",
                    "fit": fit_i,
                    "coordinate": cid,
                    "metric": metric,
                    "values": values,
                })
    reports, errors = _absorbed_reports()
    for name, data in reports.items():
        if data is None:
            # A degraded surface is still a VISIBLE record: the
            # consumer sees "this export is missing its pipeline /
            # compile-cache section and why", not a silent hole.
            lines.append({
                "type": "report", "name": name,
                "data": {"degraded": True, "error": errors.get(name)},
            })
        else:
            lines.append({"type": "report", "name": name, "data": data})
    from photon_tpu.obs import ledger

    if ledger.enabled():
        lines.append({
            "type": "report", "name": "ledger",
            "data": ledger.snapshot(),
        })
    from photon_tpu.obs import health

    if health.enabled():
        lines.append({
            "type": "report", "name": "health",
            "data": health.snapshot(),
        })
    with open(path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    return len(lines)


_REQUIRED_KEYS = {
    "telemetry": ("version",),
    "span": ("path", "name", "thread", "seconds", "device_wait_seconds"),
    "counter": ("series", "value"),
    "gauge": ("series", "value"),
    "histogram": ("series", "count", "sum", "min", "max"),
    "series": ("name", "fit", "coordinate", "metric", "values"),
    "report": ("name", "data"),
    # Serving request records (obs/trace.py write_request_jsonl):
    # outcome must come from trace.REQUEST_OUTCOMES, checked below.
    "request": ("id", "outcome", "submit_ts", "done_ts"),
}


def validate_jsonl(path: str) -> int:
    """Validate a telemetry JSONL file against the documented schema.

    Raises ValueError on the first violation; returns the number of
    validated lines. Shared by tests and the CI telemetry-smoke job —
    the schema in OBSERVABILITY.md and this validator move together.
    """
    n = 0
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: not JSON ({exc})")
            if not isinstance(rec, dict) or "type" not in rec:
                raise ValueError(
                    f"{path}:{lineno}: record without a 'type' field"
                )
            rtype = rec["type"]
            if rtype not in _REQUIRED_KEYS:
                raise ValueError(
                    f"{path}:{lineno}: unknown record type {rtype!r}"
                )
            # The FIRST RECORD (not merely the first line — blank lines
            # skip) must be the version header.
            if n == 0 and rtype != "telemetry":
                raise ValueError(
                    f"{path}: first record must be the telemetry header"
                )
            missing = [
                k for k in _REQUIRED_KEYS[rtype] if k not in rec
            ]
            if missing:
                raise ValueError(
                    f"{path}:{lineno}: {rtype} record missing "
                    f"{', '.join(missing)}"
                )
            if rtype == "span" and rec["seconds"] < 0:
                raise ValueError(
                    f"{path}:{lineno}: negative span seconds"
                )
            if rtype == "series" and not isinstance(rec["values"], list):
                raise ValueError(
                    f"{path}:{lineno}: series values must be a list"
                )
            if rtype == "request":
                from photon_tpu.obs.trace import REQUEST_OUTCOMES

                if rec["outcome"] not in REQUEST_OUTCOMES:
                    raise ValueError(
                        f"{path}:{lineno}: unknown request outcome "
                        f"{rec['outcome']!r} (known: "
                        f"{', '.join(REQUEST_OUTCOMES)})"
                    )
                if rec["done_ts"] < rec["submit_ts"]:
                    raise ValueError(
                        f"{path}:{lineno}: request done_ts precedes "
                        "submit_ts"
                    )
            n += 1
    if n == 0:
        raise ValueError(f"{path}: empty telemetry file")
    return n


def summary_table() -> str:
    """End-of-run text summary: the span tree + headline metrics."""
    snap = snapshot()
    rows = ["== telemetry summary ==", "-- spans (path, count, s, device-wait s) --"]
    for path, agg in snap["spans"].items():
        depth = path.count("/")
        dw = agg["device_wait_seconds"]
        rows.append(
            f"  {'  ' * depth}{path.rsplit('/', 1)[-1]:<28} "
            f"x{agg['count']:<4} {agg['seconds']:>10.4f} "
            f"{'-' if dw is None else f'{dw:.4f}':>10}"
        )
    m = snap["metrics"]
    if m["counters"]:
        rows.append("-- counters --")
        rows.extend(
            f"  {k} = {v:g}" for k, v in sorted(m["counters"].items())
        )
    if m["gauges"]:
        rows.append("-- gauges --")
        rows.extend(
            f"  {k} = {v:g}" for k, v in sorted(m["gauges"].items())
        )
    if m["histograms"]:
        rows.append("-- histograms (count/sum/min/max) --")
        rows.extend(
            f"  {k}: n={h['count']} sum={h['sum']:.4f} "
            f"min={h['min']:.4f} max={h['max']:.4f}"
            for k, h in sorted(m["histograms"].items())
        )
    conv = snap["convergence"]
    if conv["fits_recorded"]:
        rows.append(
            f"-- convergence: {conv['fits_recorded']} fit(s) recorded; "
            f"metrics {', '.join(conv['metrics'])} --"
        )
    return "\n".join(rows)
