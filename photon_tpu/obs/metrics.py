"""Labeled metrics registry: counters, gauges, histograms.

The runtime's scalar telemetry — event-bus training events, compile-cache
hits/misses, ingest pipeline stages, fused-fit wall times — all lands in
one process-global, thread-safe registry (the reference's equivalent is
whatever the Spark UI surfaces plus ``OptimizationStatesTracker``; ours
must survive the ingest pipeline's thread pools, so every mutation takes
one lock and the hammer test in tests/test_obs.py pins no-lost-updates).

Naming follows the Prometheus convention loosely: snake_case metric
names, a small flat label set, and series keyed by
``name{label=value,...}``. Histograms keep count/sum/min/max — enough
for the summary table and the JSONL stream without bucket bookkeeping on
the hot host path.
"""

from __future__ import annotations

import threading

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). Every mutation path — counters from the event bus,
# histograms from pipeline stage exits on worker threads, gauges from
# exporters — funnels through the one registry lock; the handle classes
# (`_Counter.inc` / `_Gauge.set` / `_Histogram.observe`) are the
# thread-entry surface because pipeline and compile threads call them
# directly. The hammer test in tests/test_obs.py is the runtime
# counterpart (no lost updates under a thread pool).
CONCURRENCY_AUDIT = dict(
    name="obs-metrics",
    locks={
        "MetricsRegistry._lock": (
            "MetricsRegistry._counters",
            "MetricsRegistry._gauges",
            "MetricsRegistry._histograms",
        ),
    },
    thread_entries=("_Counter.inc", "_Gauge.set", "_Histogram.observe"),
    jax_dispatch_ok={},
)


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class _Counter:
    __slots__ = ("registry", "key")

    def __init__(self, registry: "MetricsRegistry", key: str):
        self.registry = registry
        self.key = key

    def inc(self, value: float = 1.0) -> None:
        with self.registry._lock:
            c = self.registry._counters
            c[self.key] = c.get(self.key, 0.0) + value


class _Gauge:
    __slots__ = ("registry", "key")

    def __init__(self, registry: "MetricsRegistry", key: str):
        self.registry = registry
        self.key = key

    def set(self, value: float) -> None:
        with self.registry._lock:
            self.registry._gauges[self.key] = float(value)


class _Histogram:
    __slots__ = ("registry", "key")

    def __init__(self, registry: "MetricsRegistry", key: str):
        self.registry = registry
        self.key = key

    def observe(self, value: float) -> None:
        value = float(value)
        with self.registry._lock:
            h = self.registry._histograms.get(self.key)
            if h is None:
                self.registry._histograms[self.key] = {
                    "count": 1,
                    "sum": value,
                    "min": value,
                    "max": value,
                }
            else:
                h["count"] += 1
                h["sum"] += value
                h["min"] = min(h["min"], value)
                h["max"] = max(h["max"], value)


class MetricsRegistry:
    """Thread-safe registry; one process-global instance at
    ``photon_tpu.obs.REGISTRY``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, dict] = {}

    def reset(self) -> None:
        with self._lock:
            self._counters = {}
            self._gauges = {}
            self._histograms = {}

    def counter(self, name: str, **labels) -> _Counter:
        return _Counter(self, _series_key(name, labels))

    def gauge(self, name: str, **labels) -> _Gauge:
        return _Gauge(self, _series_key(name, labels))

    def histogram(self, name: str, **labels) -> _Histogram:
        return _Histogram(self, _series_key(name, labels))

    def snapshot(self) -> dict:
        """JSON-ready view: {counters, gauges, histograms}."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: dict(v) for k, v in self._histograms.items()
                },
            }


REGISTRY = MetricsRegistry()


def metrics_listener(event) -> None:
    """An ``EventEmitter`` listener feeding the registry from the training
    event bus (events.py): per-coordinate update counters + dispatch-time
    histograms, per-config fit-end counters.

    Opt-in by design: registering ANY listener routes the estimator onto
    the unfused per-update path (fused programs have no host boundary
    between updates — ``fused_fit.fuse_ineligibility_reasons``), so this
    is for callers already paying for per-update events. The fused path
    feeds the registry directly from ``FusedFit.run`` instead.
    """
    from photon_tpu.events import CoordinateUpdateEvent, FitEndEvent

    if isinstance(event, CoordinateUpdateEvent):
        REGISTRY.counter(
            "coordinate_updates_total", coordinate=event.coordinate_id
        ).inc()
        if event.seconds is not None:
            REGISTRY.histogram(
                "coordinate_update_dispatch_seconds",
                coordinate=event.coordinate_id,
            ).observe(event.seconds)
    elif isinstance(event, FitEndEvent):
        REGISTRY.counter("fit_configs_total").inc()
