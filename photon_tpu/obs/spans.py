"""Hierarchical span tracer: one tree answering "where did the time go".

The reference scatters runtime visibility across the Spark UI plus ad-hoc
``Timed{}`` wall-clock logging (util/Timed.scala:33); our rebuild had
grown the same scatter — ``utils/timed.py``, ``PIPELINE_STATS.stage``,
per-update ``time.perf_counter()`` in the descent loops. This module is
the one surface they all feed: thread-safe, hierarchical spans recording
wall seconds and — at span ROOTS only — the host-vs-device split.

Design constraints (the audited zero-overhead contract,
``photon_tpu/obs/__init__.py`` PROGRAM_AUDIT):

- **Nothing device-side.** Spans are pure host bookkeeping around
  dispatch; no span ever appears inside a jitted program, so the traced
  jaxprs are byte-identical with telemetry on or off.
- **Device time only at roots.** A span constructed with ``sync=...`` (or
  given ``span.sync = outputs`` before exit) calls
  ``jax.block_until_ready`` ON EXIT and records the blocked wait as
  ``device_wait_seconds``. Only coarse fit-level spans pass ``sync`` —
  never per-iteration code — so telemetry adds at most one host sync per
  fit, at a point the caller's first blocking read would have paid
  anyway.
- **Disabled == free.** With the tracer disabled, ``span()`` is a single
  flag check yielding ``None``; no allocation, no lock, no sync.

Hierarchy is per thread: each thread keeps its own span stack, and a
span's ``path`` is its ancestors' names joined with ``/`` (worker-pool
spans — the ingest planners, the background AOT compile — root their own
subtrees, labeled by thread). Aggregation by path happens at export time
(``obs/export.py``), so recording stays O(1) per span.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

# Default retention bound on completed spans — the same concern that
# caps convergence traces: a long telemetry-on production run (or the
# bench's steady-state loop) must not grow host memory linearly. Oldest
# spans drop first; the tracer counts drops (and feeds the
# `spans_dropped_total` registry counter) so exporters can say so
# instead of silently under-reporting. Configurable per tracer via
# ``SpanTracer.set_retention`` / ``obs.set_span_retention``.
_MAX_SPANS = 4096

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). Worker threads (ingest planners, the AOT compile
# thread) record spans concurrently with the training thread, so the
# completed-span deque and the drop counter live under one lock; the
# per-thread span STACKS are `threading.local` and need none. The
# `enabled` flag is deliberately unguarded: it is a benign latch read
# once per span entry, and a racing enable/disable can only gain or
# lose one span at the boundary, never corrupt the record.
CONCURRENCY_AUDIT = dict(
    name="obs-spans",
    locks={
        "SpanTracer._lock": (
            "SpanTracer._spans",
            "SpanTracer.dropped",
        ),
    },
    thread_entries=(),
    jax_dispatch_ok={},
)


class Span:
    """One completed (or in-flight) timed section."""

    __slots__ = (
        "name",
        "path",
        "thread",
        "t0",
        "t1",
        "seconds",
        "device_wait_seconds",
        "sync",
        "attrs",
    )

    def __init__(self, name: str, path: str, thread: str):
        self.name = name
        self.path = path
        self.thread = thread
        self.t0 = 0.0
        self.t1 = 0.0
        self.seconds = 0.0
        # Time spent blocked in jax.block_until_ready at span exit — the
        # device-work tail the host had to wait out. None when the span
        # carried no sync (host-only span).
        self.device_wait_seconds: float | None = None
        # Arrays (any pytree) to block on at exit; set via the ``sync=``
        # kwarg or assigned inside the ``with`` body once outputs exist.
        self.sync = None
        self.attrs: dict | None = None

    def to_json(self) -> dict:
        return {
            "type": "span",
            "path": self.path,
            "name": self.name,
            "thread": self.thread,
            "seconds": round(self.seconds, 6),
            "device_wait_seconds": (
                None
                if self.device_wait_seconds is None
                else round(self.device_wait_seconds, 6)
            ),
            "attrs": self.attrs or {},
        }


class SpanTracer:
    """Thread-safe span recorder with per-thread hierarchy.

    One process-global instance lives at ``photon_tpu.obs.TRACER``;
    ``obs.enable()/disable()`` flip recording for the whole telemetry
    layer (spans, convergence capture, metric side-feeds).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._spans: deque[Span] = deque(maxlen=_MAX_SPANS)
        self.dropped = 0
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def set_retention(self, max_spans: int) -> None:
        """Rebind the completed-span ring to a new bound (the newest
        spans are kept). Spans a shrinking bound evicts count as drops —
        the same accounting as ring overflow. The trace-event ring has
        the analogous ``obs.trace.set_retention``."""
        if max_spans < 1:
            raise ValueError(
                f"span retention must be >= 1, got {max_spans}"
            )
        with self._lock:
            evicted = max(0, len(self._spans) - int(max_spans))
            self._spans = deque(self._spans, maxlen=int(max_spans))
            self.dropped += evicted
        if evicted:
            from photon_tpu.obs.metrics import REGISTRY

            REGISTRY.counter("spans_dropped_total").inc(evicted)

    def completed(self) -> list[Span]:
        """Snapshot of the completed spans (record order; bounded to the
        most recent _MAX_SPANS — ``dropped`` counts the evicted)."""
        with self._lock:
            return list(self._spans)

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, *, sync=None, attrs: dict | None = None):
        """Record a named section; yields the live Span (or None when
        telemetry is disabled — callers must tolerate both).

        ``sync``: pytree of jax arrays to ``block_until_ready`` at exit
        (roots-only policy: pass it on fit-level spans, never inside
        loops). The blocked time lands in ``device_wait_seconds``.
        """
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        path = f"{stack[-1].path}/{name}" if stack else name
        sp = Span(name, path, threading.current_thread().name)
        if attrs:
            sp.attrs = dict(attrs)
        sp.sync = sync
        stack.append(sp)
        sp.t0 = time.perf_counter()
        try:
            yield sp
        finally:
            t1 = time.perf_counter()
            try:
                if sp.sync is not None:
                    import jax

                    # Clear before blocking: don't pin device arrays in
                    # the record, and a raising sync (async device
                    # failure surfacing here) must not leave them held.
                    sync, sp.sync = sp.sync, None
                    jax.block_until_ready(sync)
                    t_done = time.perf_counter()
                    sp.device_wait_seconds = t_done - t1
                    t1 = t_done
            finally:
                # Pop + record UNCONDITIONALLY: if block_until_ready
                # raised, the exception propagates, but the thread's
                # span stack must not keep the dead span (every later
                # span on this thread would inherit its path prefix).
                sp.t1 = t1
                sp.seconds = t1 - sp.t0
                stack.pop()
                evicted = False
                with self._lock:
                    if len(self._spans) == self._spans.maxlen:
                        self.dropped += 1
                        evicted = True
                    self._spans.append(sp)
                if evicted:
                    # Outside the tracer lock (never nest it with the
                    # registry's): retention pressure is a REAL metric —
                    # the snapshot header's spans_dropped only says what
                    # was lost, the counter makes it alertable.
                    from photon_tpu.obs.metrics import REGISTRY

                    REGISTRY.counter("spans_dropped_total").inc()


def aggregate(spans: list[Span]) -> dict[str, dict]:
    """Path -> {count, seconds, device_wait_seconds} over completed spans.

    The rendered "span tree": paths sort hierarchically, seconds are the
    SUM over occurrences (a path entered from several threads or fits
    accumulates), and ``device_wait_seconds`` sums only over occurrences
    that carried a sync (None when none did).
    """
    out: dict[str, dict] = {}
    for sp in spans:
        agg = out.setdefault(
            sp.path,
            {"count": 0, "seconds": 0.0, "device_wait_seconds": None},
        )
        agg["count"] += 1
        agg["seconds"] += sp.seconds
        if sp.device_wait_seconds is not None:
            agg["device_wait_seconds"] = (
                agg["device_wait_seconds"] or 0.0
            ) + sp.device_wait_seconds
    for agg in out.values():
        agg["seconds"] = round(agg["seconds"], 6)
        if agg["device_wait_seconds"] is not None:
            agg["device_wait_seconds"] = round(
                agg["device_wait_seconds"], 6
            )
    return dict(sorted(out.items()))
