"""photon_tpu.obs.health — model & data health: drift, skew, calibration.

Four observability PRs (4, 8, 9, 12) can attribute every wall-clock
second and HBM byte, yet none of them can say whether the model being
served is still *correct for today's traffic*. This module is the fifth
and final observability surface: the STATISTICAL health of the model
and its data, built from bounded-memory, host-only machinery —

- **Streaming data-distribution sketches** (:class:`DistSketch`,
  :class:`FeatureMoments`, :class:`DataSketch`): per-column
  moment/quantile/missing-rate sketches plus per-feature moments and
  per-shard value/nnz histograms. Mergeable (counts add — window by
  window, day by day), serializable with BYTE-STABLE canonical JSON
  (``to_bytes``; a sketch round-tripped through disk re-serializes to
  the identical bytes), and recorded per PR-10 ingest window by
  ``data/stream.py`` (persisted beside the cursor, so a kill-and-resume
  ingest reproduces the identical sketch).
- **Skew & drift scoring** (:func:`psi`, :func:`ks`, :func:`compare`):
  population-stability-index and KS-style distance between any two
  sketch snapshots — train-window vs train-window (temporal drift) and
  train vs serve (skew, fed from the serve queue's request batches at a
  bounded sample rate through :func:`observe_serve_batch`).
- **Model-health trackers**: expected-calibration-error on
  (score, label) pairs (:class:`CalibrationSketch`, fed from the
  validation scoring path via ``GameEstimator.evaluate_model``'s
  ``score_sink``), score-distribution summaries on the serve path, and
  per-coordinate coefficient-movement norms across warm-start
  generations (:func:`coefficient_movement`: L2/L∞ plus the top-moved
  entities of every random-effect table).
- **Numerics sentinels** (:func:`sentinel_watch`,
  :func:`numerics_report`): non-finite detection per (fit, coordinate,
  metric, iteration) over the fused fit's EXISTING convergence-trace
  block — the sentinel piggybacks the PR-4 async readback (the device
  array reference is parked; ``np.asarray`` happens at report time),
  so arming it adds zero host syncs to the hot loop. The trace's
  ``loss``/``grad_norm`` columns cover the solver objective and
  gradient directly; a non-finite Hessian diagonal in the batched
  Newton solves propagates into ``weight_delta_sq``/``weight_norm_sq``
  the same sweep, which is what the sentinel's coefficient columns
  catch (obs/convergence.py documents the column contract).
  :func:`scan_model` is the companion host-side check on a candidate's
  coefficient tables.

Everything is OFF by default (``enable()`` arms it) and host-only:
no jax import, no traced operand, no callback — the tier-2 ``health``
PROGRAM_AUDIT (declared in ``photon_tpu/obs/__init__.py``, machinery
in ``analysis/program.build_health``) proves the fused
materialize/fit programs trace byte-identical with the layer fully
armed. The payoff consumer is the pilot: ``PilotConfig.health`` turns
:class:`HealthGatePolicy` violations into promotion REFUSALS with
recorded ``health:*`` reasons (PILOT.md).
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
from collections import deque

import numpy as np

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). One module lock guards the process-global state: the
# serve tap (written by the queue's dispatch worker through
# `observe_serve_batch`, read by snapshot/metrics consumers), the
# parked sentinel traces, and the enable flag's companion counters.
# All numpy preparation happens OUTSIDE the lock (the worker converts
# and bins before acquiring it; sentinel materialization fetches the
# device array outside and installs the cache under the lock — the
# obs/convergence.py double-checked pattern), so the serve worker
# never blocks a scrape and a scrape never blocks the worker for more
# than a dict copy. The lock is a LEAF: no call made while holding it
# acquires any other lock.
CONCURRENCY_AUDIT = dict(
    name="obs-health",
    locks={
        "_LOCK": (
            "_STATE",
            "_ENABLED",
        ),
    },
    thread_entries=("observe_serve_batch",),
    jax_dispatch_ok={},
)

SCHEMA_VERSION = 1

# Per-feature moment tracking is bounded: indices past this cap pool
# into one overflow slot, so a 100M-feature vocabulary costs the same
# three arrays as a 4096-feature one (the per-shard value HISTOGRAM
# still sees every value — only the per-feature split is capped).
HEALTH_MAX_FEATURES = 4096

# Bounded sentinel inventory — same policy as obs/convergence.py's
# parked-trace deque: a bench steady-state loop runs dozens of fits.
_MAX_SENTINELS = 8


def signed_log_bounds(
    lo: float = 1e-3, hi: float = 1e4, per_decade: int = 2
) -> tuple[float, ...]:
    """Symmetric signed-log bucket upper bounds for arbitrary real
    feature/score streams: ``-hi .. -lo, 0, lo .. hi`` with
    ``per_decade`` buckets per decade (values above ``hi`` land in the
    implicit +Inf catch-all; below ``-hi`` in bucket 0). Fixed,
    data-independent edges are what make two sketches comparable — PSI
    and KS are defined bucket-by-bucket."""
    if not (0 < lo < hi) or per_decade < 1:
        raise ValueError(
            f"bad bounds spec lo={lo} hi={hi} per_decade={per_decade}")
    decades = int(round(math.log10(hi / lo) * per_decade))
    pos = [lo * 10 ** (i / per_decade) for i in range(decades + 1)]
    return tuple([-v for v in reversed(pos)] + [0.0] + pos)


DEFAULT_BOUNDS = signed_log_bounds()
# Unit-interval bounds for probability-like streams (calibration bins
# use their own uniform grid; this is for score DISTRIBUTIONS).
UNIT_BOUNDS = tuple(i / 20 for i in range(21))


class DistSketch:
    """Bounded-memory sketch of one scalar stream.

    Fixed-edge histogram (``bounds`` are upper edges + an implicit +Inf
    catch-all) plus exact moments (count/sum/sumsq/min/max) and a
    missing counter (non-finite observations). Mergeable when the
    bounds match; quantiles report the upper edge of the bucket holding
    the exact quantile (the RollingHistogram error contract).
    """

    __slots__ = (
        "bounds", "counts", "count", "missing", "sum", "sumsq",
        "min", "max",
    )

    def __init__(self, bounds: tuple[float, ...] | None = None):
        self.bounds = tuple(bounds) if bounds else DEFAULT_BOUNDS
        self.counts = np.zeros(len(self.bounds) + 1, dtype=np.int64)
        self.count = 0
        self.missing = 0
        self.sum = 0.0
        self.sumsq = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, values: np.ndarray) -> None:
        """Fold a float64 ndarray in (the CALLER converts — keeping
        ``np.asarray`` outside any lock this sketch is updated under)."""
        v = values.reshape(-1)
        if v.size == 0:
            return
        finite = np.isfinite(v)
        self.missing += int(v.size - finite.sum())
        v = v[finite]
        if v.size == 0:
            return
        idx = np.searchsorted(np.asarray(self.bounds), v, side="left")
        self.counts += np.bincount(idx, minlength=len(self.counts))
        self.count += int(v.size)
        self.sum += float(v.sum())
        self.sumsq += float((v * v).sum())
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))

    # -- algebra -----------------------------------------------------------

    def merge(self, other: "DistSketch") -> "DistSketch":
        if self.bounds != other.bounds:
            raise ValueError(
                "cannot merge sketches with different bucket bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} edges)")
        self.counts = self.counts + other.counts
        self.count += other.count
        self.missing += other.missing
        self.sum += other.sum
        self.sumsq += other.sumsq
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def clone(self) -> "DistSketch":
        """Cheap structural copy: array memcpys + scalars, no
        per-element boxing — safe to take under a lock."""
        out = DistSketch(self.bounds)
        out.counts = self.counts.copy()
        out.count = self.count
        out.missing = self.missing
        out.sum = self.sum
        out.sumsq = self.sumsq
        out.min = self.min
        out.max = self.max
        return out

    def diff_from(self, baseline: "DistSketch") -> "DistSketch":
        """The WINDOW ``self - baseline`` (a cumulative sketch minus an
        earlier snapshot of itself): counts and moments subtract
        exactly, so PSI/KS/mean-shift over the window are exact;
        extrema keep the cumulative values (conservative — min/max are
        not invertible)."""
        if self.bounds != baseline.bounds:
            raise ValueError(
                "cannot diff sketches with different bucket bounds")
        out = DistSketch(self.bounds)
        out.counts = np.maximum(self.counts - baseline.counts, 0)
        out.count = max(self.count - baseline.count, 0)
        out.missing = max(self.missing - baseline.missing, 0)
        out.sum = self.sum - baseline.sum
        out.sumsq = self.sumsq - baseline.sumsq
        out.min = self.min
        out.max = self.max
        return out

    # -- summaries ---------------------------------------------------------

    def mean(self) -> float | None:
        return self.sum / self.count if self.count else None

    def std(self) -> float | None:
        if not self.count:
            return None
        var = max(self.sumsq / self.count - (self.sum / self.count) ** 2,
                  0.0)
        return math.sqrt(var)

    def missing_rate(self) -> float | None:
        total = self.count + self.missing
        return self.missing / total if total else None

    def quantile(self, q: float) -> float | None:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        if not self.count:
            return None
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += int(c)
            if seen >= rank:
                if i < len(self.bounds):
                    return self.bounds[i]
                return self.max  # +Inf catch-all: report the seen max
        return self.max  # pragma: no cover — rank <= count

    def summary(self) -> dict:
        return {
            "count": self.count,
            "missing": self.missing,
            "missing_rate": self.missing_rate(),
            "mean": self.mean(),
            "std": self.std(),
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
        }

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "bounds": list(self.bounds),
            "counts": [int(c) for c in self.counts],
            "count": int(self.count),
            "missing": int(self.missing),
            "sum": float(self.sum),
            "sumsq": float(self.sumsq),
            "min": None if self.count == 0 else float(self.min),
            "max": None if self.count == 0 else float(self.max),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DistSketch":
        out = cls(tuple(d["bounds"]))
        out.counts = np.asarray(d["counts"], dtype=np.int64)
        out.count = int(d["count"])
        out.missing = int(d["missing"])
        out.sum = float(d["sum"])
        out.sumsq = float(d["sumsq"])
        out.min = math.inf if d["min"] is None else float(d["min"])
        out.max = -math.inf if d["max"] is None else float(d["max"])
        return out


class FeatureMoments:
    """Per-feature-index count/sum/sumsq for one feature shard.

    Bounded: indices ``>= cap`` pool into one overflow slot (index
    ``cap``), so memory is ``O(min(num_features, cap))`` whatever the
    vocabulary. Values of exactly 0 are treated as absent — the ingest
    layer drops explicit zeros (data/stream.py decode), so in ELL
    buffers a zero value is indistinguishable from padding by design.
    """

    __slots__ = ("num_features", "cap", "counts", "sums", "sumsqs")

    def __init__(self, num_features: int, cap: int = HEALTH_MAX_FEATURES):
        self.num_features = int(num_features)
        self.cap = min(self.num_features, int(cap))
        n = self.cap + 1  # + the overflow pool
        self.counts = np.zeros(n, dtype=np.int64)
        self.sums = np.zeros(n, dtype=np.float64)
        self.sumsqs = np.zeros(n, dtype=np.float64)

    def update(self, idx: np.ndarray, val: np.ndarray) -> None:
        """Fold an (indices, values) pair in — ELL blocks ([n, k]) or
        flat arrays; zero values (padding/absent) are skipped."""
        i = idx.reshape(-1)
        v = val.reshape(-1).astype(np.float64)
        live = v != 0.0
        i = np.minimum(i[live], self.cap)
        v = v[live]
        n = len(self.counts)
        self.counts += np.bincount(i, minlength=n).astype(np.int64)
        self.sums += np.bincount(i, weights=v, minlength=n)
        self.sumsqs += np.bincount(i, weights=v * v, minlength=n)

    def merge(self, other: "FeatureMoments") -> "FeatureMoments":
        if (self.num_features, self.cap) != (other.num_features, other.cap):
            raise ValueError(
                "cannot merge feature moments with different shapes "
                f"({self.num_features}/{self.cap} vs "
                f"{other.num_features}/{other.cap})")
        self.counts = self.counts + other.counts
        self.sums = self.sums + other.sums
        self.sumsqs = self.sumsqs + other.sumsqs
        return self

    def clone(self) -> "FeatureMoments":
        out = FeatureMoments(self.num_features, cap=self.cap)
        out.counts = self.counts.copy()
        out.sums = self.sums.copy()
        out.sumsqs = self.sumsqs.copy()
        return out

    def diff_from(self, baseline: "FeatureMoments") -> "FeatureMoments":
        if (self.num_features, self.cap) != (
            baseline.num_features, baseline.cap
        ):
            raise ValueError(
                "cannot diff feature moments with different shapes")
        out = FeatureMoments(self.num_features, cap=self.cap)
        out.counts = np.maximum(self.counts - baseline.counts, 0)
        out.sums = self.sums - baseline.sums
        out.sumsqs = self.sumsqs - baseline.sumsqs
        return out

    def means(self) -> np.ndarray:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(
                self.counts > 0, self.sums / self.counts, np.nan)

    def stds(self) -> np.ndarray:
        with np.errstate(invalid="ignore", divide="ignore"):
            var = np.where(
                self.counts > 0,
                self.sumsqs / self.counts
                - (self.sums / np.maximum(self.counts, 1)) ** 2,
                np.nan,
            )
        return np.sqrt(np.maximum(var, 0.0))

    def to_dict(self) -> dict:
        return {
            "num_features": self.num_features,
            "cap": self.cap,
            "counts": [int(c) for c in self.counts],
            "sums": [float(s) for s in self.sums],
            "sumsqs": [float(s) for s in self.sumsqs],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FeatureMoments":
        out = cls(int(d["num_features"]), cap=int(d["cap"]))
        out.counts = np.asarray(d["counts"], dtype=np.int64)
        out.sums = np.asarray(d["sums"], dtype=np.float64)
        out.sumsqs = np.asarray(d["sumsqs"], dtype=np.float64)
        return out


class DataSketch:
    """One dataset snapshot's full health sketch.

    ``columns`` holds per-column :class:`DistSketch`es (label / offset /
    weight on the train side; score on the serve side); ``shards`` holds
    per-feature-shard blocks — the pooled value distribution, the
    per-row nonzero-count distribution, and the per-feature moments.
    """

    __slots__ = ("rows", "columns", "shards")

    def __init__(self):
        self.rows = 0
        self.columns: dict[str, DistSketch] = {}
        self.shards: dict[str, dict] = {}

    # -- building ----------------------------------------------------------

    def column(self, name: str,
               bounds: tuple[float, ...] | None = None) -> DistSketch:
        sk = self.columns.get(name)
        if sk is None:
            sk = self.columns[name] = DistSketch(bounds)
        return sk

    def shard(self, name: str, num_features: int) -> dict:
        blk = self.shards.get(name)
        if blk is None:
            blk = self.shards[name] = {
                "values": DistSketch(),
                "nnz": DistSketch(),
                "moments": FeatureMoments(num_features),
            }
        return blk

    def update_window(
        self,
        labels: np.ndarray,
        offsets: np.ndarray,
        weights: np.ndarray,
        shards: dict[str, tuple[np.ndarray, np.ndarray]],
        widths: dict[str, int],
    ) -> None:
        """Fold one decoded ingest window in (data/stream.py `_Window`
        arrays: columns + per-shard ELL (idx, val) blocks; ``widths``
        maps shard -> vocabulary size). Pure numpy — the streaming
        ingest calls this on the training thread, never inside a jit."""
        self.rows += int(labels.shape[0])
        self.column("label").observe(labels.astype(np.float64))
        self.column("offset").observe(offsets.astype(np.float64))
        self.column("weight").observe(weights.astype(np.float64))
        for name, (idx, val) in shards.items():
            blk = self.shard(name, widths[name])
            v = val.astype(np.float64)
            blk["values"].observe(v[v != 0.0])
            blk["nnz"].observe((v != 0.0).sum(axis=1).astype(np.float64))
            blk["moments"].update(idx, v)

    def update_requests_sparse(
        self, name: str, idx: np.ndarray, val: np.ndarray,
        num_features: int, rows: int,
    ) -> None:
        blk = self.shard(name, num_features)
        v = val.astype(np.float64)
        blk["values"].observe(v[v != 0.0])
        blk["nnz"].observe(
            (v != 0.0).reshape(rows, -1).sum(axis=1).astype(np.float64))
        blk["moments"].update(idx, v)

    def update_requests_dense(self, name: str, x: np.ndarray) -> None:
        """Fold dense [n, d] request vectors in with the SAME
        zero-is-absent convention as the sparse/ELL train side: the
        ingest layer drops explicit zeros at decode, so a dense zero
        on the serve side means "feature absent", not "observed 0" —
        folding zeros as observations would pile (d - nnz)/d of the
        serve histogram's mass into a bucket the training sketch never
        has and make the skew gate refuse identical traffic."""
        blk = self.shard(name, x.shape[1])
        v = x.astype(np.float64)
        blk["values"].observe(v[v != 0.0])
        blk["nnz"].observe(
            (v != 0.0).sum(axis=1).astype(np.float64))
        idx = np.broadcast_to(
            np.arange(x.shape[1]), v.shape)
        blk["moments"].update(idx, v)  # update() skips zeros

    def merge(self, other: "DataSketch") -> "DataSketch":
        self.rows += other.rows
        for name, sk in other.columns.items():
            if name in self.columns:
                self.columns[name].merge(sk)
            else:
                self.columns[name] = sk.clone()
        for name, blk in other.shards.items():
            if name in self.shards:
                mine = self.shards[name]
                mine["values"].merge(blk["values"])
                mine["nnz"].merge(blk["nnz"])
                mine["moments"].merge(blk["moments"])
            else:
                self.shards[name] = {
                    k: blk[k].clone()
                    for k in ("values", "nnz", "moments")
                }
        return self

    def clone(self) -> "DataSketch":
        """Cheap structural copy (array memcpys only — safe under a
        lock; the serve tap's snapshot path)."""
        out = DataSketch()
        out.rows = self.rows
        out.columns = {n: sk.clone() for n, sk in self.columns.items()}
        out.shards = {
            n: {
                "values": blk["values"].clone(),
                "nnz": blk["nnz"].clone(),
                "moments": blk["moments"].clone(),
            }
            for n, blk in self.shards.items()
        }
        return out

    def diff_from(self, baseline: "DataSketch") -> "DataSketch":
        """The window ``self - baseline``: surfaces the baseline lacks
        copy through whole; shared surfaces subtract (see
        ``DistSketch.diff_from``). This is how a long-lived serve tap
        yields a PER-CYCLE traffic window for the skew gate — without
        it, day 31's shifted traffic is 1/31 of the cumulative mass
        and the gate's sensitivity decays toward zero."""
        out = DataSketch()
        out.rows = max(self.rows - baseline.rows, 0)
        for n, sk in self.columns.items():
            base = baseline.columns.get(n)
            out.columns[n] = (
                sk.clone() if base is None else sk.diff_from(base)
            )
        for n, blk in self.shards.items():
            base = baseline.shards.get(n)
            if base is None:
                out.shards[n] = {
                    k: blk[k].clone()
                    for k in ("values", "nnz", "moments")
                }
            else:
                out.shards[n] = {
                    k: blk[k].diff_from(base[k])
                    for k in ("values", "nnz", "moments")
                }
        return out

    # -- serialization (canonical, byte-stable) ---------------------------

    def to_dict(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "rows": int(self.rows),
            "columns": {
                n: sk.to_dict() for n, sk in sorted(self.columns.items())
            },
            "shards": {
                n: {
                    "values": blk["values"].to_dict(),
                    "nnz": blk["nnz"].to_dict(),
                    "moments": blk["moments"].to_dict(),
                }
                for n, blk in sorted(self.shards.items())
            },
        }

    def to_bytes(self) -> bytes:
        """Canonical serialization: sorted keys, no whitespace — the
        byte-stability contract (save -> load -> to_bytes reproduces
        the identical bytes; pinned by tests/test_health.py)."""
        return json.dumps(
            self.to_dict(), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")

    @classmethod
    def from_dict(cls, d: dict) -> "DataSketch":
        version = d.get("schema_version")
        if version != SCHEMA_VERSION:
            raise ValueError(
                f"health sketch schema_version {version!r} is not the "
                f"supported {SCHEMA_VERSION}")
        out = cls()
        out.rows = int(d["rows"])
        for n, sk in d.get("columns", {}).items():
            out.columns[n] = DistSketch.from_dict(sk)
        for n, blk in d.get("shards", {}).items():
            out.shards[n] = {
                "values": DistSketch.from_dict(blk["values"]),
                "nnz": DistSketch.from_dict(blk["nnz"]),
                "moments": FeatureMoments.from_dict(blk["moments"]),
            }
        return out

    def save(self, path: str) -> None:
        from photon_tpu.io.model_io import atomic_write_bytes

        atomic_write_bytes(path, self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "DataSketch":
        with open(path, "rb") as f:
            return cls.from_dict(json.loads(f.read().decode("utf-8")))

    def summary(self) -> dict:
        return {
            "rows": self.rows,
            "columns": {
                n: sk.summary() for n, sk in sorted(self.columns.items())
            },
            "shards": {
                n: {
                    "values": blk["values"].summary(),
                    "nnz": blk["nnz"].summary(),
                }
                for n, blk in sorted(self.shards.items())
            },
        }


# --------------------------------------------------------------------------
# drift / skew scoring
# --------------------------------------------------------------------------


def psi(p_counts, q_counts, eps: float = 1e-6) -> float:
    """Population stability index between two aligned histograms.

    Add-half (Jeffreys) smoothing per bucket before the log: with a
    bare epsilon floor, a bucket holding ONE sample on one side and
    zero on the other contributes ``(1/n) * ln(1/(n*eps))`` — at small
    sample counts that empty-bucket noise alone crosses typical gate
    ceilings (a 120-row window "drifted" 0.5+ against its own
    distribution). The pseudo-count shrinks sampling noise to O(1/n)
    while a real mass relocation still scores in the units the 0.1/0.25
    PSI folklore thresholds assume. Finite, SYMMETRIC in its
    arguments, and exactly 0.0 on identical inputs."""
    p = np.asarray(p_counts, dtype=np.float64)
    q = np.asarray(q_counts, dtype=np.float64)
    if p.shape != q.shape:
        raise ValueError(
            f"PSI needs aligned histograms ({p.shape} vs {q.shape})")
    if p.sum() == 0 or q.sum() == 0:
        return 0.0
    if np.array_equal(p, q):
        return 0.0
    n = p.size
    p = np.maximum((p + 0.5) / (p.sum() + 0.5 * n), eps)
    q = np.maximum((q + 0.5) / (q.sum() + 0.5 * n), eps)
    return float(np.sum((p - q) * np.log(p / q)))


def ks(p_counts, q_counts) -> float:
    """KS-style distance: the max absolute CDF gap over the shared
    bucket grid (0 on identical, 1 on disjoint)."""
    p = np.asarray(p_counts, dtype=np.float64)
    q = np.asarray(q_counts, dtype=np.float64)
    if p.shape != q.shape:
        raise ValueError(
            f"KS needs aligned histograms ({p.shape} vs {q.shape})")
    if p.sum() == 0 or q.sum() == 0:
        return 0.0
    return float(np.max(np.abs(
        np.cumsum(p) / p.sum() - np.cumsum(q) / q.sum()
    )))


def sketch_distance(a: DistSketch, b: DistSketch) -> dict:
    """PSI + KS + moment shift between two scalar sketches."""
    ma, mb = a.mean(), b.mean()
    sa, sb = a.std(), b.std()
    pooled = None
    if sa is not None and sb is not None:
        pooled = math.sqrt((sa * sa + sb * sb) / 2.0)
    shift = None
    if ma is not None and mb is not None:
        shift = (
            abs(ma - mb) / pooled if pooled else abs(ma - mb)
        )
    miss = None
    ra, rb = a.missing_rate(), b.missing_rate()
    if ra is not None and rb is not None:
        miss = rb - ra
    return {
        "psi": round(psi(a.counts, b.counts), 6),
        "ks": round(ks(a.counts, b.counts), 6),
        "mean_a": ma,
        "mean_b": mb,
        "mean_shift": None if shift is None else round(shift, 6),
        "missing_rate_delta": None if miss is None else round(miss, 6),
    }


def compare(a: DataSketch, b: DataSketch, top_k: int = 10) -> dict:
    """Full drift/skew report between two :class:`DataSketch`es.

    Surfaces only what BOTH sides carry (a serve-side sketch has no
    label column; the comparison is over the intersection). Per column
    and per shard: PSI/KS/mean-shift; per shard additionally the
    top-``top_k`` features by normalized mean movement. ``max_psi`` /
    ``max_ks`` aggregate over every compared distribution — the numbers
    a gate thresholds."""
    out: dict = {"rows_a": a.rows, "rows_b": b.rows,
                 "columns": {}, "shards": {}}
    worst_psi = 0.0
    worst_ks = 0.0
    worst_surface = None
    for name in sorted(set(a.columns) & set(b.columns)):
        d = sketch_distance(a.columns[name], b.columns[name])
        out["columns"][name] = d
        if d["psi"] >= worst_psi:
            worst_psi, worst_surface = d["psi"], f"column:{name}"
        worst_ks = max(worst_ks, d["ks"])
    for name in sorted(set(a.shards) & set(b.shards)):
        blk_a, blk_b = a.shards[name], b.shards[name]
        d = {
            "values": sketch_distance(blk_a["values"], blk_b["values"]),
            "nnz": sketch_distance(blk_a["nnz"], blk_b["nnz"]),
        }
        fm_a, fm_b = blk_a["moments"], blk_b["moments"]
        if (fm_a.num_features, fm_a.cap) == (fm_b.num_features, fm_b.cap):
            mean_a, mean_b = fm_a.means(), fm_b.means()
            std_a, std_b = fm_a.stds(), fm_b.stds()
            both = (fm_a.counts > 0) & (fm_b.counts > 0)
            with np.errstate(invalid="ignore", divide="ignore"):
                pooled = np.sqrt((std_a ** 2 + std_b ** 2) / 2.0)
                moved = np.abs(mean_a - mean_b) / np.where(
                    pooled > 0, pooled, 1.0)
            moved = np.where(both, moved, 0.0)
            order = np.argsort(-moved)[:top_k]
            d["top_moved_features"] = [
                {
                    "index": int(i),
                    "mean_shift": round(float(moved[i]), 6),
                    "mean_a": round(float(mean_a[i]), 6),
                    "mean_b": round(float(mean_b[i]), 6),
                }
                for i in order if moved[i] > 0.0
            ]
        out["shards"][name] = d
        for key in ("values", "nnz"):
            if d[key]["psi"] >= worst_psi:
                worst_psi = d[key]["psi"]
                worst_surface = f"shard:{name}/{key}"
            worst_ks = max(worst_ks, d[key]["ks"])
    out["max_psi"] = round(worst_psi, 6)
    out["max_ks"] = round(worst_ks, 6)
    out["max_psi_surface"] = worst_surface
    return out


def render_comparison(report: dict) -> str:
    """Human-readable table for ``python -m photon_tpu.cli.health``."""
    rows = [
        "== health comparison ==",
        f"rows: {report.get('rows_a')} vs {report.get('rows_b')}",
        f"max PSI {report.get('max_psi')} "
        f"({report.get('max_psi_surface')}); "
        f"max KS {report.get('max_ks')}",
        f"{'surface':<28} {'psi':>9} {'ks':>9} {'mean shift':>11}",
    ]
    for name, d in report.get("columns", {}).items():
        rows.append(
            f"column:{name:<21} {d['psi']:>9.4f} {d['ks']:>9.4f} "
            f"{d['mean_shift'] if d['mean_shift'] is not None else '-':>11}"
        )
    for name, blk in report.get("shards", {}).items():
        for key in ("values", "nnz"):
            d = blk[key]
            label = f"shard:{name}/{key}"
            rows.append(
                f"{label:<28} {d['psi']:>9.4f} {d['ks']:>9.4f} "
                f"{d['mean_shift'] if d['mean_shift'] is not None else '-':>11}"
            )
        moved = blk.get("top_moved_features") or []
        if moved:
            tops = ", ".join(
                f"#{m['index']}({m['mean_shift']:.2f})"
                for m in moved[:5]
            )
            rows.append(f"  top-moved features: {tops}")
    return "\n".join(rows)


# --------------------------------------------------------------------------
# calibration
# --------------------------------------------------------------------------


class CalibrationSketch:
    """Expected-calibration-error accumulator over uniform [0, 1] bins.

    Per bin: count / Σpredicted / Σlabel. ``ece()`` is the standard
    count-weighted mean of |accuracy - confidence| per non-empty bin.
    Mergeable; serializable with the same canonical-bytes contract as
    :class:`DistSketch`.
    """

    __slots__ = ("bins", "counts", "pred_sums", "label_sums", "missing")

    def __init__(self, bins: int = 10):
        if bins < 1:
            raise ValueError(f"calibration bins must be >= 1, got {bins}")
        self.bins = int(bins)
        self.counts = np.zeros(self.bins, dtype=np.int64)
        self.pred_sums = np.zeros(self.bins, dtype=np.float64)
        self.label_sums = np.zeros(self.bins, dtype=np.float64)
        self.missing = 0

    def update(self, probs: np.ndarray, labels: np.ndarray) -> None:
        p = probs.reshape(-1).astype(np.float64)
        y = labels.reshape(-1).astype(np.float64)
        # Non-finite pairs count as MISSING, never bin: a NaN-scoring
        # candidate is exactly what the health layer exists to refuse —
        # a NaN here must reach the numerics gate as a refusal, not
        # crash the VALIDATE stage in bincount (garbage bin index) or
        # poison label_sums so ece() goes NaN and 'NaN > ceiling'
        # silently passes the calibration gate.
        ok = np.isfinite(p) & np.isfinite(y)
        self.missing += int(p.size - ok.sum())
        p = np.clip(p[ok], 0.0, 1.0)
        y = y[ok]
        if p.size == 0:
            return
        idx = np.minimum((p * self.bins).astype(np.int64), self.bins - 1)
        self.counts += np.bincount(idx, minlength=self.bins)
        self.pred_sums += np.bincount(idx, weights=p, minlength=self.bins)
        self.label_sums += np.bincount(idx, weights=y, minlength=self.bins)

    def merge(self, other: "CalibrationSketch") -> "CalibrationSketch":
        if self.bins != other.bins:
            raise ValueError(
                f"cannot merge {other.bins}-bin calibration into "
                f"{self.bins}-bin")
        self.counts = self.counts + other.counts
        self.pred_sums = self.pred_sums + other.pred_sums
        self.label_sums = self.label_sums + other.label_sums
        self.missing += other.missing
        return self

    def ece(self) -> float | None:
        total = int(self.counts.sum())
        if not total:
            return None
        live = self.counts > 0
        conf = self.pred_sums[live] / self.counts[live]
        acc = self.label_sums[live] / self.counts[live]
        return float(
            np.sum(self.counts[live] * np.abs(acc - conf)) / total)

    def summary(self) -> dict:
        return {
            "bins": self.bins,
            "samples": int(self.counts.sum()),
            "missing": self.missing,
            "ece": self.ece(),
            "per_bin": [
                {
                    "count": int(c),
                    "confidence": (float(p / c) if c else None),
                    "accuracy": (float(s / c) if c else None),
                }
                for c, p, s in zip(
                    self.counts, self.pred_sums, self.label_sums)
            ],
        }

    def to_dict(self) -> dict:
        return {
            "bins": self.bins,
            "counts": [int(c) for c in self.counts],
            "pred_sums": [float(p) for p in self.pred_sums],
            "label_sums": [float(s) for s in self.label_sums],
            "missing": int(self.missing),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CalibrationSketch":
        out = cls(int(d["bins"]))
        out.counts = np.asarray(d["counts"], dtype=np.int64)
        out.pred_sums = np.asarray(d["pred_sums"], dtype=np.float64)
        out.label_sums = np.asarray(d["label_sums"], dtype=np.float64)
        out.missing = int(d.get("missing", 0))
        return out


def calibration_sink(task) -> tuple[CalibrationSketch, object] | None:
    """(sketch, score_sink) for ``GameEstimator.evaluate_model``.

    Binary tasks map raw margins through the logistic link to
    probabilities; non-binary tasks return None — ECE is undefined
    without a probability semantic, and a gate configured with
    ``max_ece`` on a regression task records that instead of guessing.
    """
    from photon_tpu.types import TaskType

    if task not in (TaskType.LOGISTIC_REGRESSION,
                    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        return None
    cal = CalibrationSketch()

    def sink(scores: np.ndarray, labels: np.ndarray) -> None:
        z = np.clip(scores.astype(np.float64), -60.0, 60.0)
        cal.update(1.0 / (1.0 + np.exp(-z)), labels)

    return cal, sink


# --------------------------------------------------------------------------
# coefficient movement
# --------------------------------------------------------------------------


def coefficient_movement(old_model, new_model, top_k: int = 10) -> dict:
    """Per-coordinate movement between two warm-start generations.

    For every coordinate both models carry: L2 and L∞ of the
    coefficient delta plus ``rel_l2`` (delta norm over the old norm —
    the scale-free "lurch" number a gate thresholds). Random-effect
    tables additionally report the ``top_k`` most-moved entities by
    per-row L2 (exact — the table is already in host reach at gate
    time; the streaming counterpart of "which entities are hot" stays
    with the serve-side SpaceSavingSketch)."""
    out: dict = {}
    shared = [
        cid for cid, _ in new_model.items() if cid in old_model
    ]
    for cid in shared:
        old_m, new_m = old_model[cid], new_model[cid]
        entity_keys = getattr(new_m, "entity_keys", None)
        if entity_keys is not None:
            w_old = np.asarray(old_m.coefficients, dtype=np.float64)
            w_new = np.asarray(new_m.coefficients, dtype=np.float64)
            if w_old.shape != w_new.shape:
                out[cid] = {
                    "structure_changed": True,
                    "shape_old": list(w_old.shape),
                    "shape_new": list(w_new.shape),
                }
                continue
            delta = w_new - w_old
            row_l2 = np.sqrt((delta * delta).sum(axis=1))
            order = np.argsort(-row_l2)[:top_k]
            entry = {
                "l2": float(np.sqrt((delta * delta).sum())),
                "linf": float(np.abs(delta).max()) if delta.size else 0.0,
                "norm_old": float(np.sqrt((w_old * w_old).sum())),
                "top_moved_entities": [
                    {
                        "entity": str(entity_keys[i]),
                        "l2": round(float(row_l2[i]), 6),
                    }
                    for i in order if row_l2[i] > 0.0
                ],
            }
        else:
            glm_old = getattr(old_m, "model", old_m)
            glm_new = getattr(new_m, "model", new_m)
            w_old = np.asarray(
                glm_old.coefficients.means, dtype=np.float64)
            w_new = np.asarray(
                glm_new.coefficients.means, dtype=np.float64)
            if w_old.shape != w_new.shape:
                out[cid] = {
                    "structure_changed": True,
                    "shape_old": list(w_old.shape),
                    "shape_new": list(w_new.shape),
                }
                continue
            delta = w_new - w_old
            entry = {
                "l2": float(np.sqrt((delta * delta).sum())),
                "linf": float(np.abs(delta).max()) if delta.size else 0.0,
                "norm_old": float(np.sqrt((w_old * w_old).sum())),
            }
        entry["rel_l2"] = round(
            entry["l2"] / (entry["norm_old"] + 1e-12), 6)
        out[cid] = entry
    return out


def scan_model(model) -> list[str]:
    """Non-finite scan over a model's coefficient tables (host numpy;
    called once per gate decision, never on a dispatch path). Returns
    one message per offending coordinate."""
    out = []
    for cid, m in model.items():
        glm = getattr(m, "model", None)
        coef = (
            glm.coefficients.means if glm is not None
            else m.coefficients
        )
        arr = np.asarray(coef)
        bad = int((~np.isfinite(arr)).sum())
        if bad:
            out.append(
                f"coordinate {cid!r}: {bad} non-finite coefficient(s) "
                f"of {arr.size}")
    return out


# --------------------------------------------------------------------------
# evaluation coverage
# --------------------------------------------------------------------------


def count_undefined_groups(per_group: dict) -> dict:
    """Coverage summary over ``EvaluationSuite.evaluate_per_group``
    output: per metric — group count, how many groups the metric is
    UNDEFINED on (the documented NaN convention for single-class-AUC
    groups), and the mean over DEFINED groups only. The undefined
    count is first-class: silently averaging over NaN groups (or
    worse, dropping them without saying so) is exactly the kind of
    quiet statistical rot this module exists to surface."""
    out = {}
    for metric, values in per_group.items():
        arr = np.asarray(values, dtype=np.float64)
        defined = np.isfinite(arr)
        out[metric] = {
            "groups": int(arr.size),
            "undefined_groups": int(arr.size - defined.sum()),
            "mean_defined": (
                float(arr[defined].mean()) if defined.any() else None
            ),
        }
    return out


# --------------------------------------------------------------------------
# numerics sentinels (piggybacking the convergence-trace readback)
# --------------------------------------------------------------------------


def sentinel_watch(coordinates: tuple, array) -> None:
    """Park one fit's convergence block for lazy non-finite scanning.

    Called by ``FusedFit.run`` with the [iters, coords, metrics] device
    array that is ALREADY an output of the fit program — pure reference
    bookkeeping, no sync, no transfer (the obs/convergence.py
    contract). Scanning happens at :func:`numerics_report` time."""
    with _LOCK:
        _STATE["sentinel_seq"] += 1
        _STATE["sentinels"].append({
            "seq": _STATE["sentinel_seq"],
            "coordinates": tuple(coordinates),
            "array": array,
            "np": None,
        })


def sentinel_seq() -> int:
    """Monotonic count of fits ever parked — callers window a
    :func:`numerics_report` to "fits since my mark" with it (the pilot
    marks at cycle trigger so an old cycle's violation can never
    re-refuse a later, healthy retrain)."""
    with _LOCK:
        return _STATE["sentinel_seq"]


def _materialize_sentinel(entry: dict) -> np.ndarray:
    """Device->host fetch OUTSIDE the module lock, cache installed
    under it (the obs/convergence.py double-checked pattern)."""
    with _LOCK:
        arr = entry.get("np")
        dev = entry.get("array")
    if arr is None:
        fetched = np.asarray(dev)
        with _LOCK:
            arr = entry.get("np")
            if arr is None:
                arr = entry["np"] = fetched
                entry["array"] = None
    return arr


def numerics_report(since_seq: int = 0) -> dict:
    """Scan parked sentinel blocks for non-finite values.

    Returns ``{"fits_scanned", "nonfinite_total", "violations"}`` where
    each violation names (fit seq, coordinate, metric, first bad
    iteration, count). ``since_seq`` windows the scan to fits parked
    AFTER a :func:`sentinel_seq` mark. The fetch happens HERE — by
    report/gate time the fits completed long ago, so this is a plain
    device->host copy, not a hot-loop sync."""
    from photon_tpu.obs.convergence import METRICS

    with _LOCK:
        parked = [
            e for e in _STATE["sentinels"] if e["seq"] > since_seq
        ]
    violations = []
    total = 0
    for entry in parked:
        fit_i = entry["seq"]
        arr = _materialize_sentinel(entry)
        bad = ~np.isfinite(arr)
        if not bad.any():
            continue
        for j, cid in enumerate(entry["coordinates"]):
            for k, metric in enumerate(METRICS):
                col = bad[:, j, k]
                n = int(col.sum())
                if n:
                    total += n
                    violations.append({
                        "fit": fit_i,
                        "coordinate": cid,
                        "metric": metric,
                        "first_iteration": int(np.argmax(col)),
                        "count": n,
                    })
    return {
        "fits_scanned": len(parked),
        "nonfinite_total": total,
        "violations": violations,
    }


# --------------------------------------------------------------------------
# the serve tap (bounded-rate request/score sampling)
# --------------------------------------------------------------------------


def observe_serve_batch(features_list, scores, widths=None) -> None:
    """Sample one dispatched serving batch into the serve-side sketches.

    Called by the queue's dispatch worker AFTER scoring, outside the
    queue lock (serve/queue.py). Bounded: only every
    ``serve_sample_every``-th batch is folded in, so the tap's cost is
    amortized to ~zero at the default rate; a no-op when the layer is
    disabled. ``features_list`` holds the batch's raw request feature
    dicts (shard -> dense vector | (indices, values)); ``scores`` the
    served raw scores; ``widths`` maps shard -> the serving spec's
    feature-space size — WITHOUT it a sparse shard's per-feature
    moments would be pinned by the first sampled batch's max index and
    could never align with the training sketch's (vocabulary-sized)
    moments, so ``compare`` would silently drop the per-feature skew
    evidence."""
    with _LOCK:
        if not _ENABLED:
            return
        _STATE["serve_batches_seen"] += 1
        if (_STATE["serve_batches_seen"] - 1) % _STATE[
            "serve_sample_every"
        ] != 0:
            return
    # All numpy preparation outside the lock: the dispatch worker holds
    # no lock while packing, and a concurrent scrape only ever waits
    # for the fold below.
    widths = widths or {}
    score_arr = np.asarray(scores, dtype=np.float64).reshape(-1)
    dense: dict[str, np.ndarray] = {}
    sparse: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for name in features_list[0].keys() if features_list else ():
        leaves = [req[name] for req in features_list]
        if isinstance(leaves[0], tuple):
            sparse[name] = (
                np.concatenate(
                    [np.asarray(ix).reshape(-1) for ix, _ in leaves]),
                np.concatenate(
                    [np.asarray(v, dtype=np.float64).reshape(-1)
                     for _, v in leaves]),
            )
        else:
            dense[name] = np.stack([
                np.asarray(x, dtype=np.float64) for x in leaves
            ])
    with _LOCK:
        if not _ENABLED:  # disabled between check and fold
            return
        _STATE["serve_batches_sampled"] += 1
        _STATE["serve_requests_sampled"] += len(features_list)
        sketch = _STATE["serve_sketch"]
        sketch.rows += len(features_list)
        sketch.column("score").observe(score_arr)
        for name, x in dense.items():
            sketch.update_requests_dense(name, x)
        for name, (ix, v) in sparse.items():
            nf = max(
                int(widths.get(name) or 0),
                int(ix.max()) + 1 if ix.size else 1,
            )
            blk = sketch.shards.get(name)
            if blk is not None:
                nf = max(nf, blk["moments"].num_features)
            sketch.update_requests_sparse(
                name, ix, v, nf, len(features_list))


def set_serve_sample_every(n: int) -> None:
    """Tap rate: fold every ``n``-th dispatched batch (default 8)."""
    if n < 1:
        raise ValueError(f"sample_every must be >= 1, got {n}")
    with _LOCK:
        _STATE["serve_sample_every"] = int(n)


def serve_mark() -> DataSketch:
    """A snapshot of the tap to window later reads against: the skew
    gate wants THIS CYCLE's traffic, and ``serve_sketch(since=mark)``
    subtracts the mark from the (cumulative) tap — without a window, a
    month-old tap dilutes a fresh traffic shift to invisibility."""
    with _LOCK:
        return _STATE["serve_sketch"].clone()


def serve_sketch(since: DataSketch | None = None) -> DataSketch:
    """A consistent COPY of the serve tap's sketch (safe to compare or
    persist while the worker keeps folding); ``since`` (a
    :func:`serve_mark`) windows it to the traffic sampled after the
    mark. The lock hold is array memcpys only (``clone``) — a reader
    never stalls the dispatch worker for a serialization."""
    with _LOCK:
        snap = _STATE["serve_sketch"].clone()
    return snap if since is None else snap.diff_from(since)


def serve_snapshot() -> dict:
    with _LOCK:
        out = {
            "batches_seen": _STATE["serve_batches_seen"],
            "batches_sampled": _STATE["serve_batches_sampled"],
            "requests_sampled": _STATE["serve_requests_sampled"],
            "sample_every": _STATE["serve_sample_every"],
        }
        snap = _STATE["serve_sketch"].clone()
    out["sketch_summary"] = snap.summary()
    return out


def save_serve_sketch(path: str) -> int:
    """Persist the tap's sketch (the ``cli.serve --health-sketch``
    artifact ``cli.health`` compares against a training manifest's
    ``ingest-sketch.json``). Serialization happens OUTSIDE the module
    lock (``serve_sketch`` clones under it). Returns the
    sampled-request count."""
    sk = serve_sketch()
    sk.save(path)
    with _LOCK:
        return _STATE["serve_requests_sampled"]


# --------------------------------------------------------------------------
# train-side reference
# --------------------------------------------------------------------------


def set_train_sketch(sketch: DataSketch) -> None:
    """Register the most recent training-data sketch (the streaming
    ingest calls this at the end of a health-armed run) so skew
    (train vs serve tap) is computable in-process."""
    with _LOCK:
        _STATE["train_sketch"] = sketch


def train_sketch() -> DataSketch | None:
    with _LOCK:
        return _STATE["train_sketch"]


# --------------------------------------------------------------------------
# promotion gate policy
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HealthGatePolicy:
    """Thresholds that REFUSE a pilot promotion (PILOT.md).

    Every reason is prefixed ``health:`` so refusal bookkeeping (state
    file, flight post-mortem) distinguishes statistical refusals from
    metric-delta ones. ``None`` disables the individual check.

    - ``max_drift_psi``: ceiling on the max PSI between this cycle's
      ingest sketch and the last PROMOTED cycle's (temporal drift).
    - ``max_skew_psi``: ceiling on the max PSI between this cycle's
      ingest sketch and the serve tap's request sketch (train/serve
      skew; skipped until the tap has sampled ``min_skew_requests``).
    - ``max_ece``: ceiling on the candidate's expected calibration
      error on the validation scores (binary tasks only).
    - ``max_coefficient_rel_l2``: ceiling on any coordinate's
      relative coefficient movement vs the serving generation.
    - ``forbid_nonfinite``: refuse when the fit's numerics sentinels
      saw any non-finite convergence value or the candidate's tables
      carry non-finite coefficients.
    """

    max_drift_psi: float | None = 0.25
    max_skew_psi: float | None = None
    max_ece: float | None = None
    max_coefficient_rel_l2: float | None = None
    forbid_nonfinite: bool = True
    min_skew_requests: int = 64

    def evaluate(
        self,
        *,
        drift: dict | None = None,
        skew: dict | None = None,
        skew_requests: int = 0,
        ece: float | None = None,
        movement: dict | None = None,
        nonfinite: dict | None = None,
        model_scan: list | tuple = (),
    ) -> list[str]:
        """Refusal reasons (empty = healthy); inputs absent when their
        surface is unarmed are skipped, never guessed."""
        reasons: list[str] = []
        if self.max_drift_psi is not None and drift is not None:
            if drift["max_psi"] > self.max_drift_psi:
                reasons.append(
                    f"health:drift PSI {drift['max_psi']:.4f} > "
                    f"{self.max_drift_psi:g} on "
                    f"{drift['max_psi_surface']} (this cycle's input "
                    "distribution moved vs the last promoted cycle)")
        if (
            self.max_skew_psi is not None
            and skew is not None
            and skew_requests >= self.min_skew_requests
        ):
            if skew["max_psi"] > self.max_skew_psi:
                reasons.append(
                    f"health:skew PSI {skew['max_psi']:.4f} > "
                    f"{self.max_skew_psi:g} on "
                    f"{skew['max_psi_surface']} (training features "
                    "diverge from sampled serving traffic)")
        if self.max_ece is not None and ece is not None:
            if ece > self.max_ece:
                reasons.append(
                    f"health:calibration ECE {ece:.4f} > "
                    f"{self.max_ece:g} (candidate scores are "
                    "mis-calibrated on the validation set)")
        if self.max_coefficient_rel_l2 is not None and movement:
            for cid, m in sorted(movement.items()):
                if m.get("structure_changed"):
                    continue
                if m["rel_l2"] > self.max_coefficient_rel_l2:
                    reasons.append(
                        f"health:coefficients {cid} moved rel_l2 "
                        f"{m['rel_l2']:.4f} > "
                        f"{self.max_coefficient_rel_l2:g} "
                        "(warm-start generation lurched)")
        if self.forbid_nonfinite:
            if nonfinite is not None and nonfinite["nonfinite_total"]:
                v = nonfinite["violations"][0]
                reasons.append(
                    "health:numerics "
                    f"{nonfinite['nonfinite_total']} non-finite "
                    "convergence value(s) during the fit (first: "
                    f"coordinate {v['coordinate']!r} metric "
                    f"{v['metric']} iteration {v['first_iteration']})")
            for msg in model_scan:
                reasons.append(f"health:numerics {msg}")
        return reasons


# --------------------------------------------------------------------------
# process-global state + surfaces
# --------------------------------------------------------------------------

_LOCK = threading.Lock()

# Lock-free read mirror of the armed flag (the ledger's pattern): the
# serve dispatch worker and FusedFit.run check `enabled()` on their hot
# paths even when the layer is off — a disabled check must never queue
# behind a scrape holding the module lock. Writes stay under _LOCK.
_ENABLED = False


def _fresh_state() -> dict:
    return {
        "serve_sample_every": 8,
        "serve_batches_seen": 0,
        "serve_batches_sampled": 0,
        "serve_requests_sampled": 0,
        "serve_sketch": DataSketch(),
        "train_sketch": None,
        "sentinel_seq": 0,
        "sentinels": deque(maxlen=_MAX_SENTINELS),
        "last_gate": None,  # the pilot records its last decision here
    }


_STATE = _fresh_state()


def enable() -> None:
    """Arm the health layer (sketching, the serve tap, sentinels).
    Host-side only: the audited ``health`` contract proves the traced
    programs are byte-identical either way."""
    global _ENABLED
    with _LOCK:
        _ENABLED = True


def disable() -> None:
    global _ENABLED
    with _LOCK:
        _ENABLED = False


def enabled() -> bool:
    # Deliberately lock-free: a plain bool read on the dispatch/fit
    # hot paths (see _ENABLED above).
    return _ENABLED


def reset() -> None:
    """Drop all recorded health state; keeps the enabled flag (the
    ``obs.reset()`` contract — flags are policy, records are data)."""
    global _STATE
    with _LOCK:
        sample = _STATE["serve_sample_every"]
        _STATE = _fresh_state()
        _STATE["serve_sample_every"] = sample


def record_gate(decision: dict) -> None:
    """The pilot's last health-gate decision (reasons + measured
    numbers) — what ``snapshot()`` and the gauges surface."""
    with _LOCK:
        _STATE["last_gate"] = decision


def raw_snapshot() -> dict:
    """Crash-safe view: counters and serve-tap sizes only — NO device
    materialization (a flight dump must not fetch device arrays while
    the process is dying; same policy as the ledger's raw dump)."""
    with _LOCK:
        return {
            "enabled": _ENABLED,
            "serve_batches_seen": _STATE["serve_batches_seen"],
            "serve_batches_sampled": _STATE["serve_batches_sampled"],
            "serve_requests_sampled": _STATE["serve_requests_sampled"],
            "sentinels_parked": len(_STATE["sentinels"]),
            "train_sketch_rows": (
                _STATE["train_sketch"].rows
                if _STATE["train_sketch"] is not None else None
            ),
            "last_gate": _STATE["last_gate"],
        }


def snapshot() -> dict:
    """Full JSON-ready view (obs.snapshot()['health'] when armed):
    serve tap summary, train-sketch summary, the numerics report (this
    is where parked sentinels materialize — by snapshot time every fit
    completed), and the last gate decision."""
    out = raw_snapshot()
    out["numerics"] = numerics_report()
    with _LOCK:
        train = _STATE["train_sketch"]
        serve = _STATE["serve_sketch"].clone()  # memcpy-cheap hold
    out["train_sketch"] = (
        train.summary() if train is not None else None
    )
    out["serve_sketch"] = serve.summary()
    return out


def metrics_families() -> list[dict]:
    """``health_*`` /metrics families; EMPTY when the layer is off, so
    an unarmed process scrapes exactly what it always did (the monitor
    appends this next to the ledger's — obs/monitor.py render)."""
    with _LOCK:
        if not _ENABLED:
            return []
        sampled = _STATE["serve_requests_sampled"]
        seen = _STATE["serve_batches_seen"]
        gate = _STATE["last_gate"]
        sentinels = len(_STATE["sentinels"])
    from photon_tpu.obs import monitor

    fams = [
        monitor.family(
            "health_enabled", "gauge",
            "1 while the model/data health layer is armed",
            [("", {}, 1.0)],
        ),
        monitor.family(
            "health_serve_batches_seen_total", "counter",
            "serving batches the health tap observed (sampled at "
            "1/sample_every)",
            [("", {}, float(seen))],
        ),
        monitor.family(
            "health_serve_requests_sampled_total", "counter",
            "serving requests folded into the serve-side sketch",
            [("", {}, float(sampled))],
        ),
        monitor.family(
            "health_sentinel_fits", "gauge",
            "fused fits with a parked numerics-sentinel trace",
            [("", {}, float(sentinels))],
        ),
    ]
    if gate is not None:
        fams.append(monitor.family(
            "health_gate_violations", "gauge",
            "health-gate refusal reasons at the last pilot decision",
            [("", {}, float(len(gate.get("reasons") or ())))],
        ))
        for key, label in (
            ("drift", "drift"), ("skew", "skew"),
        ):
            block = gate.get(key)
            if isinstance(block, dict) and "max_psi" in block:
                fams.append(monitor.family(
                    f"health_{label}_max_psi", "gauge",
                    f"max PSI at the last {label} comparison",
                    [("", {}, float(block["max_psi"]))],
                ))
        if gate.get("ece") is not None:
            fams.append(monitor.family(
                "health_ece", "gauge",
                "candidate expected-calibration-error at the last "
                "gate decision",
                [("", {}, float(gate["ece"]))],
            ))
    return fams
