"""OWL-QN: orthant-wise limited-memory quasi-Newton for L1/elastic-net.

TPU-native counterpart of the reference's Breeze-backed OWLQN wrapper
(photon-lib optimization/OWLQN.scala:39-83), which the optimizer factory
substitutes for L-BFGS whenever the regularization mix contains an L1 term
(optimization/OptimizerFactory.scala). Following the reference (and Breeze's
``OWLQN(_, _, (_: Int) => regularizationWeight, _)``), the L1 weight is
uniform across coordinates — the intercept is NOT excluded from the L1
penalty (unlike the L2 mixin).

Algorithm (Andrew & Gao 2007):
  - pseudo-gradient of F(w) = f(w) + l1 * |w|_1 taken as the minimum-norm
    subgradient;
  - two-loop direction computed from the smooth-gradient history, projected
    onto the descent orthant of the pseudo-gradient;
  - line search on F with backtracking-Armijo, each trial point projected
    onto the chosen orthant (sign consistency with the reference point).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    OptResult,
    OptimizerConfig,
    Tolerances,
    ValueAndGrad,
    _l2norm,
    convergence_code,
)
from photon_tpu.optim.lbfgs import (
    _C1,
    _BACKTRACK,
    _History,
    _State,
    _push_history,
    _two_loop_direction,
)

Array = jax.Array


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Minimum-norm subgradient of f(w) + l1*|w|_1."""
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0.0, right, jnp.where(left > 0.0, left, 0.0))
    return jnp.where(w > 0.0, right, jnp.where(w < 0.0, left, at_zero))


def owlqn_solve(
    fun: ValueAndGrad,
    w0: Array,
    l1_weight,
    config: OptimizerConfig | None = None,
    *,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Minimize f(w) + l1_weight * |w|_1 where ``fun`` evaluates the smooth
    part; jit- and vmap-compatible. ``l1_weight`` may be a scalar or a
    per-coordinate array (the reference always passes a scalar)."""
    config = config or OptimizerConfig()
    m = config.num_corrections
    d = w0.shape[-1]
    dtype = w0.dtype
    l1 = jnp.asarray(l1_weight, dtype=dtype)

    def total(w):
        f, g = fun(w)
        return f + jnp.sum(l1 * jnp.abs(w)), g

    # Absolute tolerances from the zero-coefficient state of the FULL
    # objective (reference computes them on the objective the optimizer sees).
    if tolerances is None:
        f0z, g0z = fun(jnp.zeros_like(w0))
        tolerances = Tolerances(
            loss_abs=jnp.abs(f0z) * config.tolerance,
            gradient_abs=_l2norm(_pseudo_gradient(jnp.zeros_like(w0), g0z, l1))
            * config.tolerance,
        )

    f0s, g0 = fun(w0)
    f0 = f0s + jnp.sum(l1 * jnp.abs(w0))
    losses = jnp.full((config.max_iterations + 1,), f0, dtype=dtype)
    init = _State(
        w=w0,
        f=f0,
        g=g0,  # smooth gradient; pseudo-gradient derived where needed
        hist=_History(
            s=jnp.zeros((m, d), dtype=dtype),
            y=jnp.zeros((m, d), dtype=dtype),
            rho=jnp.zeros((m,), dtype=dtype),
            count=jnp.asarray(0),
        ),
        iteration=jnp.asarray(0),
        code=jnp.asarray(0, dtype=jnp.int32),
        losses=losses,
    )

    def cond(state: _State):
        return state.code == 0

    def body(state: _State) -> _State:
        pg = _pseudo_gradient(state.w, state.g, l1)
        direction = _two_loop_direction(pg, state.hist)
        # Orthant-wise constraint: discard components where the quasi-Newton
        # direction disagrees in sign with steepest descent (-pg).
        direction = jnp.where(direction * pg < 0.0, direction, 0.0)
        dderiv = jnp.dot(pg, direction)
        bad = dderiv >= 0.0
        direction = jnp.where(bad, -pg, direction)
        dderiv = jnp.where(bad, -jnp.dot(pg, pg), dderiv)

        # Chosen orthant: sign(w), or steepest-descent sign at zeros.
        orthant = jnp.where(state.w != 0.0, jnp.sign(state.w), jnp.sign(-pg))

        pgnorm = _l2norm(pg)
        t0 = jnp.where(
            state.hist.count == 0,
            jnp.minimum(jnp.asarray(1.0, dtype), 1.0 / jnp.maximum(pgnorm, 1e-12)),
            jnp.asarray(1.0, dtype),
        )

        def project(t):
            w_t = state.w + t * direction
            return jnp.where(jnp.sign(w_t) == orthant, w_t, 0.0)

        def ls_cond(s):
            t, f_new, it, done = s
            return (~done) & (it < config.max_line_search_iterations)

        def ls_body(s):
            t, _, it, _ = s
            f_new, _ = total(project(t))
            ok = f_new <= state.f + _C1 * t * dderiv
            return jnp.where(ok, t, t * _BACKTRACK), f_new, it + 1, ok

        t, f_ls, _, ls_ok = lax.while_loop(
            ls_cond, ls_body, (t0, state.f, jnp.asarray(0), jnp.asarray(False))
        )

        w_new = project(t)
        f_new, g_new = total(w_new)
        accept = ls_ok & (f_new < state.f)
        w_acc = jnp.where(accept, w_new, state.w)
        f_acc = jnp.where(accept, f_new, state.f)
        g_acc = jnp.where(accept, g_new, state.g)
        # History from SMOOTH gradient differences (standard OWL-QN).
        hist = _push_history(state.hist, w_acc - state.w, g_acc - state.g)
        hist = jax.tree.map(
            lambda new, old: jnp.where(accept, new, old), hist, state.hist
        )

        iteration = state.iteration + jnp.where(accept, 1, 0)
        code = convergence_code(
            iteration=iteration,
            max_iterations=config.max_iterations,
            loss_delta=state.f - f_acc,
            gradient_norm=_l2norm(_pseudo_gradient(w_acc, g_acc, l1)),
            tol=tolerances,
            not_improving=~accept,
        )
        losses = state.losses.at[iteration].set(f_acc)
        return _State(w_acc, f_acc, g_acc, hist, iteration, code, losses)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=_l2norm(_pseudo_gradient(final.w, final.g, l1)),
        iterations=final.iteration,
        convergence_reason=final.code,
        loss_history=final.losses,
    )
