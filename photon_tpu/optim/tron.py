"""TRON: trust-region Newton method with a truncated-CG inner solver.

TPU-native counterpart of the reference's LIBLINEAR port
(photon-lib optimization/TRON.scala:78-330). Constants and control flow match
the reference exactly: (eta0, eta1, eta2) = (1e-4, 0.25, 0.75),
(sigma1, sigma2, sigma3) = (0.25, 0.5, 4.0) (TRON.scala:93-94), initial trust
radius = ||g0|| (init, :108), at most MAX_CG_ITERATIONS = 20 inner CG steps
(:256) with tolerance 0.1*||g|| (:283), trust-region boundary handling via the
quadratic formula of Lin & More eq. 13 (:296-311), the same four-way radius
update (:198-206), and retry-on-improvement-failure up to
maxNumImprovementFailures = 5 (:161-246).

Structure: the outer ``lax.while_loop`` advances one *trial* per step — an
accepted trial bumps the iteration counter, a rejected one bumps the failure
counter — which flattens the reference's nested do/while into a single
jit/vmap-friendly loop with identical semantics. Each CG step is one
Hessian-vector product: on sharded data that is two matvecs + one allreduce,
the pattern the reference pays a treeAggregate round trip for
(HessianVectorAggregator.scala:235).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    HessianVectorProduct,
    OptResult,
    OptimizerConfig,
    Tolerances,
    ValueAndGrad,
    _l2norm,
    absolute_tolerances,
    convergence_code,
    project_box,
)

Array = jax.Array

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGState(NamedTuple):
    step: Array
    residual: Array
    direction: Array
    rtr: Array
    iteration: Array
    boundary_hit: Array


def _truncated_cg(
    hvp, g: Array, delta: Array, max_cg_iterations: int
) -> tuple[Array, Array, Array]:
    """Approximately solve min_s g.s + 0.5 s.H.s subject to ||s|| <= delta.

    Returns (step, residual, cg_iterations). Reference:
    TRON.truncatedConjugateGradientMethod (TRON.scala:272-329).
    """
    dtype = g.dtype
    cg_tol = 0.1 * _l2norm(g)
    tiny = jnp.finfo(dtype).tiny

    init = _CGState(
        step=jnp.zeros_like(g),
        residual=-g,
        direction=-g,
        rtr=jnp.dot(g, g),
        iteration=jnp.asarray(0),
        boundary_hit=jnp.asarray(False),
    )

    def cond(s: _CGState):
        return (
            (s.iteration < max_cg_iterations)
            & (~s.boundary_hit)
            & (_l2norm(s.residual) > cg_tol)
        )

    def body(s: _CGState) -> _CGState:
        hd = hvp(s.direction)
        dhd = jnp.dot(s.direction, hd)
        alpha = s.rtr / jnp.maximum(dhd, tiny)
        step_try = s.step + alpha * s.direction
        over = _l2norm(step_try) > delta

        # Boundary case: walk back to s.step and extend to the sphere
        # (TRON.scala:296-311, eq. 13 of Lin & More).
        std = jnp.dot(s.step, s.direction)
        sts = jnp.dot(s.step, s.step)
        dtd = jnp.dot(s.direction, s.direction)
        dsq = delta * delta
        rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
        alpha_b = jnp.where(
            std >= 0.0,
            (dsq - sts) / jnp.maximum(std + rad, tiny),
            (rad - std) / jnp.maximum(dtd, tiny),
        )

        alpha_used = jnp.where(over, alpha_b, alpha)
        step_new = s.step + alpha_used * s.direction
        residual_new = s.residual - alpha_used * hd

        rtr_new = jnp.dot(residual_new, residual_new)
        beta = rtr_new / jnp.maximum(s.rtr, tiny)
        direction_new = jnp.where(
            over, s.direction, residual_new + beta * s.direction
        )
        return _CGState(
            step=step_new,
            residual=residual_new,
            direction=direction_new,
            rtr=jnp.where(over, s.rtr, rtr_new),
            iteration=s.iteration + 1,
            boundary_hit=over,
        )

    final = lax.while_loop(cond, body, init)
    return final.step, final.residual, final.iteration


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    delta: Array
    iteration: Array
    failures: Array
    code: Array
    losses: Array


def tron_solve(
    fun: ValueAndGrad,
    hvp: HessianVectorProduct,
    w0: Array,
    config: OptimizerConfig | None = None,
    *,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Minimize ``fun`` (with Gauss-Newton ``hvp``) from ``w0``; jit- and
    vmap-compatible."""
    config = config or OptimizerConfig.tron()

    tol = tolerances if tolerances is not None else absolute_tolerances(
        fun, w0, config.tolerance)

    f0, g0 = fun(w0)
    dtype = w0.dtype
    losses = jnp.full((config.max_iterations + 1,), f0, dtype=dtype)
    init = _State(
        w=w0,
        f=f0,
        g=g0,
        delta=_l2norm(g0),  # TRON.init (TRON.scala:108)
        iteration=jnp.asarray(0),
        failures=jnp.asarray(0),
        code=jnp.asarray(0, dtype=jnp.int32),
        losses=losses,
    )

    def cond(state: _State):
        return state.code == 0

    def body(state: _State) -> _State:
        step, residual, _ = _truncated_cg(
            lambda v: hvp(state.w, v), state.g, state.delta,
            config.max_cg_iterations,
        )
        w_try = state.w + step
        gs = jnp.dot(state.g, step)
        predicted = -0.5 * (gs - jnp.dot(step, residual))
        f_try, g_try = fun(w_try)
        actual = state.f - f_try
        step_norm = _l2norm(step)

        # First-iteration initial-radius adjustment (TRON.scala:189-191).
        delta = jnp.where(
            state.iteration == 0,
            jnp.minimum(state.delta, step_norm),
            state.delta,
        )

        denom = f_try - state.f - gs
        alpha = jnp.where(
            denom <= 0.0,
            jnp.asarray(_SIGMA3, dtype),
            jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(denom <= 0.0, 1.0, denom))),
        )

        # Four-way trust-region radius update (TRON.scala:198-206).
        a_sn = alpha * step_norm
        delta = jnp.where(
            actual < _ETA0 * predicted,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * step_norm, _SIGMA2 * delta),
            jnp.where(
                actual < _ETA1 * predicted,
                jnp.maximum(_SIGMA1 * delta, jnp.minimum(a_sn, _SIGMA2 * delta)),
                jnp.where(
                    actual < _ETA2 * predicted,
                    jnp.maximum(_SIGMA1 * delta, jnp.minimum(a_sn, _SIGMA3 * delta)),
                    jnp.maximum(delta, jnp.minimum(a_sn, _SIGMA3 * delta)),
                ),
            ),
        )

        accept = actual > _ETA0 * predicted
        w_new = jnp.where(
            accept, project_box(w_try, config.box_constraints), state.w
        )
        f_new = jnp.where(accept, f_try, state.f)
        g_new = jnp.where(accept, g_try, state.g)
        iteration = state.iteration + jnp.where(accept, 1, 0)
        # Failure counter is per outer iteration in the reference
        # (local to runOneIteration): reset on accept.
        failures = jnp.where(accept, 0, state.failures + 1)

        # Convergence cascade applies to accepted trials; a rejected trial
        # either retries with the shrunken radius (code 0) or, once retries
        # are exhausted, reports ObjectiveNotImproving — the reference's
        # iter-did-not-advance signal (Optimizer.scala:131-132).
        accepted_code = convergence_code(
            iteration=iteration,
            max_iterations=config.max_iterations,
            loss_delta=state.f - f_new,
            gradient_norm=_l2norm(g_new),
            tol=tol,
        )
        rejected_code = jnp.where(
            failures >= config.max_improvement_failures,
            jnp.asarray(4, dtype=jnp.int32),  # OBJECTIVE_NOT_IMPROVING
            jnp.asarray(0, dtype=jnp.int32),
        )
        code = jnp.where(accept, accepted_code, rejected_code)
        losses = state.losses.at[iteration].set(f_new)
        return _State(w_new, f_new, g_new, delta, iteration, failures, code, losses)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=_l2norm(final.g),
        iterations=final.iteration,
        convergence_reason=final.code,
        loss_history=final.losses,
    )
