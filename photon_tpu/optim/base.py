"""Optimizer protocol: configs, convergence reasons, results, tolerance setup.

TPU-native counterpart of the reference's ``Optimizer`` skeleton
(photon-lib optimization/Optimizer.scala:35-244) and
``OptimizationStatesTracker`` (OptimizationStatesTracker.scala:121).

Design: each solver is a pure function ``solve(fun, w0, cfg) -> OptResult``
built from ``lax.while_loop`` steps with static shapes, so one and the same
implementation serves both execution modes required by the GAME engine:

- *distributed* (fixed effect): ``fun`` closes over row-sharded data; XLA
  turns the contained reductions into ICI collectives under jit — this is the
  moral equivalent of the reference's broadcast + treeAggregate per iteration
  (ValueAndGradientAggregator.scala:299-320), minus the per-iteration host
  round trip.
- *batched* (random effects): the solver is ``vmap``-ed over an entity axis;
  JAX's while_loop batching rule yields masked per-entity convergence
  automatically (entities that converged stop changing), the TPU analog of
  thousands of independent executor-local solves
  (RandomEffectCoordinate.scala:243-292).

Convergence semantics match Optimizer.scala:126-139 exactly: absolute
tolerances are derived from the state at **zero coefficients**
(Optimizer.scala setAbsTolerances usage in optimize :162-187), and the
reasons are MAX_ITERATIONS / FUNCTION_VALUES_CONVERGED / GRADIENT_CONVERGED /
OBJECTIVE_NOT_IMPROVING.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array
# fun(w) -> (value, gradient). Everything the solver needs about data lives in
# this closure.
ValueAndGrad = Callable[[Array], tuple[Array, Array]]
# hvp(w, d) -> H(w) @ d, for TRON's inner CG.
HessianVectorProduct = Callable[[Array, Array], Array]


class OptimizerType(enum.Enum):
    """Reference: optimization/OptimizerType.scala (LBFGS, TRON)."""

    LBFGS = "LBFGS"
    TRON = "TRON"


class ConvergenceReason(enum.IntEnum):
    """Why the solver stopped. Integer-coded so batched solves can return one
    per entity as an array (RandomEffectOptimizationTracker aggregates counts
    of these, reference *Tracker.scala).

    Reference: Optimizer.getConvergenceReason (Optimizer.scala:126-139).
    """

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static solver configuration.

    Reference: optimization/OptimizerConfig.scala + LBFGS.scala:148-154
    (tolerance 1e-7, 100 iters, 10 corrections) and TRON.scala:251-256
    (tolerance 1e-5, 15 iters, 5 improvement failures, 20 CG iters).
    ``box_constraints`` mirrors the reference's constraintMap projection
    (OptimizationUtils.projectCoefficientsToSubspace).
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    tolerance: float = 1e-7
    max_iterations: int = 100
    num_corrections: int = 10
    # TRON-specific
    max_improvement_failures: int = 5
    max_cg_iterations: int = 20
    # Line-search (L-BFGS/OWL-QN)
    max_line_search_iterations: int = 25
    # Optional (lower, upper) arrays broadcastable to the coefficient shape.
    box_constraints: tuple | None = None

    @staticmethod
    def lbfgs(**kw) -> "OptimizerConfig":
        return OptimizerConfig(optimizer_type=OptimizerType.LBFGS, **kw)

    @staticmethod
    def tron(**kw) -> "OptimizerConfig":
        kw.setdefault("tolerance", 1e-5)
        kw.setdefault("max_iterations", 15)
        return OptimizerConfig(optimizer_type=OptimizerType.TRON, **kw)


class OptResult(NamedTuple):
    """Solver output; a pytree so it flows through jit/vmap.

    ``loss_history`` is fixed length ``max_iterations + 1`` padded with the
    final value — the tracker equivalent of OptimizationStatesTracker's state
    ring (per-iteration losses for observability / tests).
    """

    coefficients: Array
    value: Array
    gradient_norm: Array
    iterations: Array
    convergence_reason: Array  # int32, ConvergenceReason codes
    loss_history: Array


class Tolerances(NamedTuple):
    loss_abs: Array
    gradient_abs: Array


def absolute_tolerances(fun: ValueAndGrad, template: Array, tolerance: float) -> Tolerances:
    """Derive absolute tolerances from the zero-coefficient state.

    Reference: Optimizer.optimize (Optimizer.scala:167-170) — 'We set the
    absolute tolerances from the magnitudes of the first loss and gradient',
    computed at zero coefficients even on warm start.
    """
    f0, g0 = fun(jnp.zeros_like(template))
    return Tolerances(
        loss_abs=jnp.abs(f0) * tolerance,
        gradient_abs=_l2norm(g0) * tolerance,
    )


def _l2norm(x: Array) -> Array:
    return jnp.sqrt(jnp.sum(x * x))


def convergence_code(
    *,
    iteration: Array,
    max_iterations: int,
    loss_delta: Array,
    gradient_norm: Array,
    tol: Tolerances,
    not_improving: Array | None = None,
) -> Array:
    """Evaluate the reference's convergence cascade and return a reason code
    (0 if still running). Order matches Optimizer.scala:126-139.
    """
    # Cascade order matches the reference exactly: MaxIterations, then
    # ObjectiveNotImproving (iter did not advance), then FunctionValues,
    # then Gradient. A rejected step has loss_delta == 0, so NotImproving
    # must be checked before the function-value test.
    if not_improving is None:
        not_improving = jnp.asarray(False)
    code = jnp.where(
        iteration >= max_iterations,
        ConvergenceReason.MAX_ITERATIONS,
        jnp.where(
            not_improving,
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING,
            jnp.where(
                jnp.abs(loss_delta) <= tol.loss_abs,
                ConvergenceReason.FUNCTION_VALUES_CONVERGED,
                jnp.where(
                    gradient_norm <= tol.gradient_abs,
                    ConvergenceReason.GRADIENT_CONVERGED,
                    ConvergenceReason.NOT_CONVERGED,
                ),
            ),
        ),
    )
    return code.astype(jnp.int32)


def project_box(w: Array, box_constraints: tuple | None) -> Array:
    """Project coefficients into box constraints after a step.

    Reference: OptimizationUtils.projectCoefficientsToSubspace applied in
    LBFGS.scala:56-79 and TRON.scala (post-accept projection).
    """
    if box_constraints is None:
        return w
    lower, upper = box_constraints
    return jnp.clip(w, lower, upper)
