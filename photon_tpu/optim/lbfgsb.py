"""Bound-constrained L-BFGS: gradient-projection active set + subspace steps.

TPU-native counterpart of the reference's LBFGSB (photon-lib
optimization/LBFGSB.scala:39-92), which wraps Breeze's implementation of the
Byrd-Lu-Nocedal-Zhu algorithm. The earlier rebuild handled bounds by
projecting after an unconstrained L-BFGS step (LBFGS.scala:56-79 semantics);
that can stall on active-set boundaries: the quasi-Newton direction keeps
pointing into the bound, the projection keeps undoing the step, and the
Armijo test keeps failing even though feasible descent exists in the free
subspace.

This solver follows the gradient-projection active-set structure as a pure
``lax.while_loop`` program (jit/vmap-safe, like every other solver here):

1. **Active set** from the projected gradient: a variable is active when it
   sits at a bound whose gradient sign pushes outward.
2. **Subspace minimization**: the two-loop L-BFGS direction of the FREE
   gradient, re-masked to the free subspace — the limited-memory analog of
   BLNZ's subspace step (their eq. (5.7) solved with the same curvature
   pairs).
3. **Projected Armijo line search** along the bent path w(t) = P(w + t d)
   with the Bertsekas sufficient-decrease test
   f(w(t)) <= f + c1 * g . (w(t) - w), which remains valid when the path
   bends at bounds (a plain g.d test does not).

Convergence uses the projected-gradient norm ||P(w - g) - w|| — zero exactly
at KKT points — in the reference's convergence cascade.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    OptResult,
    OptimizerConfig,
    Tolerances,
    ValueAndGrad,
    _l2norm,
    absolute_tolerances,
    convergence_code,
)
from photon_tpu.optim.lbfgs import (
    _BACKTRACK,
    _C1,
    _History,
    _push_history,
    _two_loop_direction,
)

Array = jax.Array


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    hist: _History
    iteration: Array
    code: Array
    losses: Array


def _projected_gradient(w, g, lower, upper):
    """P(w - g) - w: zero exactly at KKT points of the box problem."""
    return jnp.clip(w - g, lower, upper) - w


def lbfgsb_solve(
    fun: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig | None = None,
    *,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Minimize ``fun`` subject to ``config.box_constraints``; jit/vmap-safe.

    Reference semantics: LBFGSB.scala:39-92 (a true bound-constrained
    solver, not projection-after-step).
    """
    config = config or OptimizerConfig()
    if config.box_constraints is None:
        raise ValueError("lbfgsb_solve requires config.box_constraints")
    lower, upper = config.box_constraints
    lower = jnp.asarray(lower, dtype=w0.dtype)
    upper = jnp.asarray(upper, dtype=w0.dtype)
    m = config.num_corrections
    d_dim = w0.shape[-1]
    dtype = w0.dtype

    tol = tolerances if tolerances is not None else absolute_tolerances(
        fun, w0, config.tolerance)

    w0 = jnp.clip(w0, lower, upper)
    f0, g0 = fun(w0)
    losses = jnp.full((config.max_iterations + 1,), f0, dtype=dtype)
    init = _State(
        w=w0,
        f=f0,
        g=g0,
        hist=_History(
            s=jnp.zeros((m, d_dim), dtype=dtype),
            y=jnp.zeros((m, d_dim), dtype=dtype),
            rho=jnp.zeros((m,), dtype=dtype),
            count=jnp.asarray(0),
        ),
        iteration=jnp.asarray(0),
        code=jnp.asarray(0, dtype=jnp.int32),
        losses=losses,
    )

    def cond(state: _State):
        return state.code == 0

    def body(state: _State):
        w, f, g = state.w, state.f, state.g
        # 1. Active set: at a bound with the gradient pushing outward.
        at_lower = (w <= lower) & (g > 0)
        at_upper = (w >= upper) & (g < 0)
        active = at_lower | at_upper
        g_free = jnp.where(active, 0.0, g)

        # 2. Subspace quasi-Newton direction (two-loop on the free
        # gradient, re-masked so active variables do not move).
        d = jnp.where(active, 0.0, _two_loop_direction(g_free, state.hist))
        dderiv = jnp.dot(g_free, d)
        # Safeguard: fall back to steepest feasible descent when the
        # quasi-Newton direction is not a descent direction.
        bad = dderiv >= 0.0
        d = jnp.where(bad, -g_free, d)

        # 3. Projected Armijo backtracking along the bent path. The probe
        # carries the full gradient so the accepted point needs no extra
        # objective evaluation.
        def ls_cond(carry):
            t, _w_t, _f_t, _g_t, it, done = carry
            return (~done) & (it < config.max_line_search_iterations)

        def ls_body(carry):
            t, _, _, _, it, _ = carry
            w_t = jnp.clip(w + t * d, lower, upper)
            f_t, g_t = fun(w_t)
            # Bertsekas projected-Armijo decrease: the model term follows
            # the ACTUAL (bent) displacement, not t * g.d.
            ok = f_t <= f + _C1 * jnp.dot(g, w_t - w)
            t_next = jnp.where(ok, t, t * _BACKTRACK)
            return t_next, w_t, f_t, g_t, it + 1, ok

        # First step along an unscaled free gradient: temper by 1/|g| (the
        # same first-iteration heuristic as lbfgs_solve — without it, an
        # ill-scaled problem's first probe overshoots beyond what 25
        # halvings can repair and the solve dies at w0).
        gnorm = _l2norm(g_free)
        t0 = jnp.where(
            state.hist.count == 0,
            jnp.minimum(
                jnp.asarray(1.0, dtype), 1.0 / jnp.maximum(gnorm, 1e-12)
            ),
            jnp.asarray(1.0, dtype),
        )
        _, w_new, f_new, g_new, _, improved = lax.while_loop(
            ls_cond, ls_body,
            (t0, w, f, g, jnp.asarray(0), jnp.asarray(False)),
        )
        improved = improved & (f_new < f)

        hist = jax.tree.map(
            lambda a, b: jnp.where(improved, a, b),
            _push_history(state.hist, w_new - w, g_new - g),
            state.hist,
        )
        w_acc = jnp.where(improved, w_new, w)
        f_acc = jnp.where(improved, f_new, f)
        g_acc = jnp.where(improved, g_new, g)

        iteration = state.iteration + 1
        losses = state.losses.at[iteration].set(f_acc)
        pg_norm = _l2norm(_projected_gradient(w_acc, g_acc, lower, upper))
        code = convergence_code(
            iteration=iteration,
            max_iterations=config.max_iterations,
            loss_delta=f - f_acc,
            gradient_norm=pg_norm,
            tol=tol,
            not_improving=~improved,
        )
        return _State(
            w=w_acc, f=f_acc, g=g_acc, hist=hist,
            iteration=iteration, code=code, losses=losses,
        )

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=_l2norm(
            _projected_gradient(final.w, final.g, lower, upper)
        ),
        iterations=final.iteration,
        convergence_reason=final.code,
        loss_history=final.losses,
    )
