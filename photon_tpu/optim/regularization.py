"""Regularization: contexts and objective-closure composition.

TPU-native counterpart of the reference's stackable mixins:
- ``RegularizationContext`` / ``RegularizationType`` with the elastic-net
  alpha split of lambda into L1/L2 parts
  (photon-lib optimization/RegularizationContext.scala:134).
- ``L2Regularization`` traits adding the L2 term to value/gradient/Hessian
  with the intercept excluded from the penalty
  (photon-lib function/L2Regularization.scala:26-97).

The Scala trait stacking becomes plain closure composition: ``with_l2`` wraps
a ``fun(w) -> (value, grad)`` closure (and optionally an hvp closure). L1 is
NOT handled here — as in the reference, the L1 term belongs to the OWL-QN
optimizer itself (OWLQN.scala:39, OptimizerFactory substitution).
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from photon_tpu.optim.base import HessianVectorProduct, ValueAndGrad

Array = jax.Array


class RegularizationType(enum.Enum):
    """Reference: optimization/RegularizationType.scala."""

    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight lambda into L1/L2 parts.

    For ELASTIC_NET, ``alpha`` is the L1 fraction: l1 = alpha * lambda,
    l2 = (1 - alpha) * lambda (RegularizationContext.scala:134 semantics;
    alpha defaults to 1.0 there, i.e. pure L1).
    """

    regularization_type: RegularizationType = RegularizationType.NONE
    alpha: float | None = None

    def __post_init__(self):
        if self.regularization_type == RegularizationType.ELASTIC_NET:
            a = 1.0 if self.alpha is None else self.alpha
            if not (0.0 <= a <= 1.0):
                raise ValueError(f"elastic net alpha must be in [0, 1]: {a}")
        elif self.alpha is not None:
            raise ValueError(
                f"alpha is only valid for ELASTIC_NET, not {self.regularization_type}"
            )

    def l1_weight(self, reg_weight: float) -> float:
        t = self.regularization_type
        if t == RegularizationType.L1:
            return reg_weight
        if t == RegularizationType.ELASTIC_NET:
            a = 1.0 if self.alpha is None else self.alpha
            return a * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        t = self.regularization_type
        if t == RegularizationType.L2:
            return reg_weight
        if t == RegularizationType.ELASTIC_NET:
            a = 1.0 if self.alpha is None else self.alpha
            return (1.0 - a) * reg_weight
        return 0.0


def _l2_mask(w: Array, intercept_index: int | None) -> Array:
    if intercept_index is None:
        return w
    return w.at[intercept_index].set(0.0)


def with_l2_masked(
    fun: ValueAndGrad,
    l2_weight,
    penalty_mask: Array,
) -> ValueAndGrad:
    """``with_l2`` with an array penalty mask instead of a static intercept
    index — the batched (vmapped) form used by random-effect coordinates,
    where each entity has its own intercept slot and its own set of valid
    (non-padding) subspace slots. ``penalty_mask`` is 1 for penalized
    coefficients, 0 for the intercept and padded slots.
    """

    def wrapped(w: Array):
        f, g = fun(w)
        wm = w * penalty_mask
        return f + 0.5 * l2_weight * jnp.dot(wm, wm), g + l2_weight * wm

    return wrapped


def with_l2_hvp_masked(
    hvp: HessianVectorProduct,
    l2_weight,
    penalty_mask: Array,
) -> HessianVectorProduct:
    """Masked-array counterpart of ``with_l2_hvp`` (see ``with_l2_masked``)."""

    def wrapped(w: Array, d: Array):
        return hvp(w, d) + l2_weight * (d * penalty_mask)

    return wrapped


def with_l2(
    fun: ValueAndGrad,
    l2_weight,
    intercept_index: int | None = None,
) -> ValueAndGrad:
    """Add 0.5 * l2 * ||w||^2 (intercept excluded) to a value-and-grad closure.

    Reference: L2Regularization.l2RegValue / l2RegGradient
    (function/L2Regularization.scala:73-97, 126-140).
    """

    def wrapped(w: Array):
        f, g = fun(w)
        wm = _l2_mask(w, intercept_index)
        return f + 0.5 * l2_weight * jnp.dot(wm, wm), g + l2_weight * wm

    return wrapped


def with_l2_hvp(
    hvp: HessianVectorProduct,
    l2_weight,
    intercept_index: int | None = None,
) -> HessianVectorProduct:
    """Add the L2 term's Hessian contribution l2 * d (intercept row/col
    excluded) to a Hessian-vector-product closure.

    Reference: L2RegularizationTwiceDiff.l2RegHessianVector
    (function/L2Regularization.scala:181-200).
    """

    def wrapped(w: Array, d: Array):
        return hvp(w, d) + l2_weight * _l2_mask(d, intercept_index)

    return wrapped


# MathConst.EPSILON (photon-lib constants/MathConst.scala:21): variances at
# or below this magnitude mean "feature absent from the prior model".
PRIOR_VARIANCE_EPSILON = 1e-12


def inverse_prior_variances(prior_variances: Array, l2_weight) -> Array:
    """1/variance with the l2 fallback for absent features.

    Reference: PriorDistribution.inversePriorVariances via
    VectorUtils.invertVectorWithZeroHandler (util/VectorUtils.scala:298-299):
    features not in the prior model carry variance 0 and fall back to the
    plain L2 weight.
    """
    return jnp.where(
        jnp.abs(prior_variances) > PRIOR_VARIANCE_EPSILON,
        1.0 / prior_variances,
        l2_weight,
    )


def with_gaussian_prior(
    fun: ValueAndGrad,
    incremental_weight,
    prior_means: Array,
    inv_prior_variances: Array,
) -> ValueAndGrad:
    """Add the incremental-training Gaussian prior penalty.

    Reference: PriorDistribution.l2RegValue / PriorDistributionDiff
    .l2RegGradient (function/PriorDistribution.scala:31-137):
      value += iw/2 * sum((w - m)^2 / var),  grad += iw * (w - m) / var,
    in the transformed space (``prior_means`` / ``inv_prior_variances`` are
    already transformed via normalizePrior :49-60). Unlike plain L2, the
    intercept is NOT excluded — the prior model constrains it too.
    """

    def wrapped(w: Array):
        f, g = fun(w)
        dw = (w - prior_means) * inv_prior_variances
        val = 0.5 * incremental_weight * jnp.dot(w - prior_means, dw)
        return f + val, g + incremental_weight * dw

    return wrapped


def with_gaussian_prior_hvp(
    hvp: HessianVectorProduct,
    incremental_weight,
    inv_prior_variances: Array,
) -> HessianVectorProduct:
    """Prior term's Hessian contribution iw * d / var.

    Reference: PriorDistributionTwiceDiff.l2RegHessianVector
    (function/PriorDistribution.scala:141-186)."""

    def wrapped(w: Array, d: Array):
        return hvp(w, d) + incremental_weight * (d * inv_prior_variances)

    return wrapped
