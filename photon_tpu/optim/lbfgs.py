"""L-BFGS with two-loop recursion as a pure ``lax.while_loop`` program.

TPU-native counterpart of the reference's Breeze-backed LBFGS wrapper
(photon-lib optimization/LBFGS.scala:38-154). The reference delegates to
``breeze.optimize.LBFGS`` on the driver JVM and pays a broadcast +
treeAggregate round trip per function evaluation; here the entire solve —
history updates, line search, convergence cascade — is one XLA program, so in
distributed mode the only cross-device traffic is the gradient reduction XLA
inserts inside ``fun``, and in batched (vmap) mode thousands of independent
solves share one fused kernel.

Shapes are static: the (s, y) history lives in fixed ``[m, d]`` ring buffers
(``num_corrections`` = m, default 10 like LBFGS.scala:150), and the line
search is a bounded strong-Wolfe bracketing/zoom loop (Breeze's
StrongWolfeLineSearch counterpart). Box-constrained configs route to the
bound-constrained solver in ``lbfgsb.py`` (LBFGSB.scala semantics).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    OptResult,
    OptimizerConfig,
    Tolerances,
    ValueAndGrad,
    _l2norm,
    absolute_tolerances,
    convergence_code,
)

Array = jax.Array

# Armijo sufficient-decrease constant (standard c1; Breeze StrongWolfe uses
# the same decrease constant).
_C1 = 1e-4
_BACKTRACK = 0.5
# Curvature-pair acceptance guard: skip history updates when s.y is not
# sufficiently positive (keeps the inverse-Hessian estimate PSD without a
# strong-Wolfe curvature line search).
_CURVATURE_EPS = 1e-10


class _History(NamedTuple):
    s: Array  # [m, d] steps
    y: Array  # [m, d] gradient differences
    rho: Array  # [m] 1/(s.y), 0 marks an empty/skipped slot
    count: Array  # total number of accepted updates (ring position = count % m)


def _two_loop_direction(g: Array, hist: _History) -> Array:
    """Classic two-loop recursion producing d = -H_k g with ring-buffered
    history; empty slots are skipped via their zero rho."""
    m = hist.s.shape[0]
    k = hist.count

    def backward(j, carry):
        q, alphas = carry
        idx = (k - 1 - j) % m
        valid = (j < k) & (hist.rho[idx] != 0.0)
        a = jnp.where(valid, hist.rho[idx] * jnp.dot(hist.s[idx], q), 0.0)
        q = q - a * hist.y[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(
        0, m, backward, (g, jnp.zeros(m, dtype=g.dtype))
    )

    # Initial Hessian scaling gamma = s.y / y.y of the newest valid pair.
    newest = (k - 1) % m
    have_any = k > 0
    y_newest = hist.y[newest]
    yy = jnp.dot(y_newest, y_newest)
    gamma = jnp.where(
        have_any & (hist.rho[newest] != 0.0) & (yy > 0.0),
        1.0 / jnp.maximum(hist.rho[newest] * yy, jnp.finfo(g.dtype).tiny),
        1.0,
    )
    r = gamma * q

    def forward(j, r):
        nvalid = jnp.minimum(k, m)
        u = k - nvalid + j  # oldest-first update number
        idx = u % m
        valid = (j < nvalid) & (hist.rho[idx] != 0.0)
        beta = jnp.where(valid, hist.rho[idx] * jnp.dot(hist.y[idx], r), 0.0)
        return r + (alphas[idx] - beta) * hist.s[idx]

    r = lax.fori_loop(0, m, forward, r)
    return -r


def _push_history(hist: _History, s: Array, y: Array) -> _History:
    """Append an (s, y) pair, skipping low-curvature pairs."""
    sy = jnp.dot(s, y)
    ok = sy > _CURVATURE_EPS * _l2norm(s) * _l2norm(y)
    idx = hist.count % hist.s.shape[0]
    rho_new = jnp.where(ok, 1.0 / jnp.where(ok, sy, 1.0), 0.0)
    return _History(
        s=jnp.where(ok, hist.s.at[idx].set(s), hist.s),
        y=jnp.where(ok, hist.y.at[idx].set(y), hist.y),
        rho=jnp.where(ok, hist.rho.at[idx].set(rho_new), hist.rho),
        count=hist.count + jnp.where(ok, 1, 0),
    )


# Strong-Wolfe curvature constant (Breeze StrongWolfeLineSearch: c1 = 1e-4,
# c2 = 0.9 — the standard L-BFGS pairing; Armijo-only backtracking accepts
# steps with poor curvature on ill-conditioned problems and the history
# degrades toward steepest descent).
_C2 = 0.9


class _WolfeState(NamedTuple):
    t: Array
    f_t: Array
    g_t: Array  # full gradient at w + t d (reused by the caller)
    t_lo: Array
    f_lo: Array
    t_hi: Array
    bracketed: Array
    it: Array
    done: Array


def _wolfe_line_search(
    fun: ValueAndGrad, w: Array, f0: Array, g0: Array, d: Array,
    dderiv: Array, t0: Array, max_iters: int,
):
    """Strong-Wolfe line search (Nocedal-Wright 3.5/3.6, bisection zoom).

    Returns (t, f_t, g_t, ok): ``ok`` certifies the Armijo condition; the
    curvature condition holds on all but pathological exits. One
    value-and-grad evaluation per probe; the accepted gradient is returned
    so the caller pays no extra evaluation.
    """
    dtype = f0.dtype

    def phi(t):
        f_t, g_t = fun(w + t * d)
        return f_t, g_t, jnp.dot(g_t, d)

    def cond(s: _WolfeState):
        return (~s.done) & (s.it < max_iters)

    def body(s: _WolfeState):
        t = jnp.where(
            s.bracketed, 0.5 * (s.t_lo + s.t_hi), s.t
        )
        f_t, g_t, dphi = phi(t)
        armijo = f_t <= f0 + _C1 * t * dderiv
        curv = jnp.abs(dphi) <= -_C2 * dderiv

        # Case 1: Armijo fails (or no progress over the best point) — the
        # minimum lies below t.
        shrink = (~armijo) | (s.bracketed & (f_t >= s.f_lo))
        # Case 2: both conditions hold — accept.
        accept = armijo & curv
        # Case 3: Armijo holds but curvature fails. Inside the zoom the
        # bracket may be stored reversed (t_lo > t_hi after a flip), so the
        # end-replacement test must be the SIGNED slope relative to the
        # bracket direction (N&W zoom: dphi*(t_hi - t_lo) >= 0 flips
        # t_hi := t_lo); unbracketed expansion moves in increasing t where
        # the plain dphi >= 0 test applies.
        flip = jnp.where(
            s.bracketed, dphi * (s.t_hi - s.t_lo) >= 0, dphi >= 0
        )
        pos_slope = armijo & (~curv) & flip

        bracketed = s.bracketed | shrink | pos_slope
        t_hi = jnp.where(
            shrink, t, jnp.where(pos_slope, s.t_lo, s.t_hi)
        )
        t_lo = jnp.where(armijo & (~shrink), t, s.t_lo)
        f_lo = jnp.where(armijo & (~shrink), f_t, s.f_lo)
        # Unbracketed and still descending: expand. On accept keep the
        # probed t (the loop stops; state.t IS the accepted step).
        t_next = jnp.where(
            accept, t, jnp.where(bracketed, t, t * 2.0)
        )
        return _WolfeState(
            t=t_next, f_t=f_t, g_t=g_t,
            t_lo=t_lo, f_lo=f_lo, t_hi=t_hi,
            bracketed=bracketed, it=s.it + 1, done=accept,
        )

    init = _WolfeState(
        t=t0,
        f_t=f0,
        g_t=g0,
        t_lo=jnp.zeros((), dtype),
        f_lo=f0,
        t_hi=jnp.zeros((), dtype),
        bracketed=jnp.asarray(False),
        it=jnp.asarray(0),
        done=jnp.asarray(False),
    )
    s = lax.while_loop(cond, body, init)
    # On exhaustion fall back to the best Armijo point found (t_lo).
    ok = s.done | (s.t_lo > 0)
    t = jnp.where(s.done, s.t, s.t_lo)
    # The state's f_t/g_t are from the LAST probe, which is the accepted
    # point exactly when done; otherwise re-evaluate at the fallback t.
    f_t, g_t = lax.cond(
        s.done, lambda: (s.f_t, s.g_t), lambda: fun(w + t * d)
    )
    return t, f_t, g_t, ok & (f_t < f0)


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    hist: _History
    iteration: Array
    code: Array
    losses: Array


def lbfgs_solve(
    fun: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig | None = None,
    *,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Minimize ``fun`` from ``w0``; jit- and vmap-compatible.

    ``tolerances`` can be supplied to skip the zero-coefficient evaluation
    (e.g. when the caller already computed it, or for exact parity control in
    warm starts).

    Box constraints route to the bound-constrained solver (the reference's
    LBFGSB, a gradient-projection active-set method) — projection after an
    unconstrained step can stall on active-set boundaries.
    """
    config = config or OptimizerConfig()
    if config.box_constraints is not None:
        from photon_tpu.optim.lbfgsb import lbfgsb_solve

        return lbfgsb_solve(fun, w0, config, tolerances=tolerances)
    m = config.num_corrections
    d = w0.shape[-1]
    dtype = w0.dtype

    tol = tolerances if tolerances is not None else absolute_tolerances(
        fun, w0, config.tolerance)

    f0, g0 = fun(w0)
    losses = jnp.full((config.max_iterations + 1,), f0, dtype=dtype)
    init = _State(
        w=w0,
        f=f0,
        g=g0,
        hist=_History(
            s=jnp.zeros((m, d), dtype=dtype),
            y=jnp.zeros((m, d), dtype=dtype),
            rho=jnp.zeros((m,), dtype=dtype),
            count=jnp.asarray(0),
        ),
        iteration=jnp.asarray(0),
        code=jnp.asarray(0, dtype=jnp.int32),
        losses=losses,
    )

    def cond(state: _State):
        return state.code == 0

    def body(state: _State) -> _State:
        direction = _two_loop_direction(state.g, state.hist)
        dderiv = jnp.dot(state.g, direction)
        # Safeguard: if the two-loop direction is not a descent direction
        # (numerical breakdown), fall back to steepest descent.
        bad = dderiv >= 0.0
        direction = jnp.where(bad, -state.g, direction)
        dderiv = jnp.where(bad, -jnp.dot(state.g, state.g), dderiv)

        # First step along an unscaled gradient: temper by 1/|g| (Breeze's
        # first-iteration heuristic); afterwards the two-loop scaling makes
        # t0 = 1 the right initial probe.
        gnorm = _l2norm(state.g)
        t0 = jnp.where(
            state.hist.count == 0,
            jnp.minimum(jnp.asarray(1.0, dtype), 1.0 / jnp.maximum(gnorm, 1e-12)),
            jnp.asarray(1.0, dtype),
        )
        t, f_new, g_new, improved = _wolfe_line_search(
            fun, state.w, state.f, state.g, direction, dderiv, t0,
            config.max_line_search_iterations,
        )
        w_new = state.w + t * direction
        # A failed line search means the objective cannot improve from here.
        accept = improved & (f_new < state.f)
        w_acc = jnp.where(accept, w_new, state.w)
        f_acc = jnp.where(accept, f_new, state.f)
        g_acc = jnp.where(accept, g_new, state.g)
        hist = _push_history(state.hist, w_acc - state.w, g_acc - state.g)
        hist = jax.tree.map(
            lambda new, old: jnp.where(accept, new, old), hist, state.hist
        )

        iteration = state.iteration + jnp.where(accept, 1, 0)
        code = convergence_code(
            iteration=iteration,
            max_iterations=config.max_iterations,
            loss_delta=state.f - f_acc,
            gradient_norm=_l2norm(g_acc),
            tol=tol,
            not_improving=~accept,
        )
        losses = state.losses.at[iteration].set(f_acc)
        return _State(w_acc, f_acc, g_acc, hist, iteration, code, losses)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=_l2norm(final.g),
        iterations=final.iteration,
        convergence_reason=final.code,
        loss_history=final.losses,
    )
