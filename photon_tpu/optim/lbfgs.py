"""L-BFGS with two-loop recursion as a pure ``lax.while_loop`` program.

TPU-native counterpart of the reference's Breeze-backed LBFGS wrapper
(photon-lib optimization/LBFGS.scala:38-154). The reference delegates to
``breeze.optimize.LBFGS`` on the driver JVM and pays a broadcast +
treeAggregate round trip per function evaluation; here the entire solve —
history updates, line search, convergence cascade — is one XLA program, so in
distributed mode the only cross-device traffic is the gradient reduction XLA
inserts inside ``fun``, and in batched (vmap) mode thousands of independent
solves share one fused kernel.

Shapes are static: the (s, y) history lives in fixed ``[m, d]`` ring buffers
(``num_corrections`` = m, default 10 like LBFGS.scala:150), and the line
search is a bounded backtracking-Armijo loop. Box constraints are applied by
projection after each accepted step (LBFGS.scala:56-79 semantics).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    OptResult,
    OptimizerConfig,
    Tolerances,
    ValueAndGrad,
    _l2norm,
    absolute_tolerances,
    convergence_code,
    project_box,
)

Array = jax.Array

# Armijo sufficient-decrease constant (standard c1; Breeze StrongWolfe uses
# the same decrease constant).
_C1 = 1e-4
_BACKTRACK = 0.5
# Curvature-pair acceptance guard: skip history updates when s.y is not
# sufficiently positive (keeps the inverse-Hessian estimate PSD without a
# strong-Wolfe curvature line search).
_CURVATURE_EPS = 1e-10


class _History(NamedTuple):
    s: Array  # [m, d] steps
    y: Array  # [m, d] gradient differences
    rho: Array  # [m] 1/(s.y), 0 marks an empty/skipped slot
    count: Array  # total number of accepted updates (ring position = count % m)


def _two_loop_direction(g: Array, hist: _History) -> Array:
    """Classic two-loop recursion producing d = -H_k g with ring-buffered
    history; empty slots are skipped via their zero rho."""
    m = hist.s.shape[0]
    k = hist.count

    def backward(j, carry):
        q, alphas = carry
        idx = (k - 1 - j) % m
        valid = (j < k) & (hist.rho[idx] != 0.0)
        a = jnp.where(valid, hist.rho[idx] * jnp.dot(hist.s[idx], q), 0.0)
        q = q - a * hist.y[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(
        0, m, backward, (g, jnp.zeros(m, dtype=g.dtype))
    )

    # Initial Hessian scaling gamma = s.y / y.y of the newest valid pair.
    newest = (k - 1) % m
    have_any = k > 0
    y_newest = hist.y[newest]
    yy = jnp.dot(y_newest, y_newest)
    gamma = jnp.where(
        have_any & (hist.rho[newest] != 0.0) & (yy > 0.0),
        1.0 / jnp.maximum(hist.rho[newest] * yy, jnp.finfo(g.dtype).tiny),
        1.0,
    )
    r = gamma * q

    def forward(j, r):
        nvalid = jnp.minimum(k, m)
        u = k - nvalid + j  # oldest-first update number
        idx = u % m
        valid = (j < nvalid) & (hist.rho[idx] != 0.0)
        beta = jnp.where(valid, hist.rho[idx] * jnp.dot(hist.y[idx], r), 0.0)
        return r + (alphas[idx] - beta) * hist.s[idx]

    r = lax.fori_loop(0, m, forward, r)
    return -r


def _push_history(hist: _History, s: Array, y: Array) -> _History:
    """Append an (s, y) pair, skipping low-curvature pairs."""
    sy = jnp.dot(s, y)
    ok = sy > _CURVATURE_EPS * _l2norm(s) * _l2norm(y)
    idx = hist.count % hist.s.shape[0]
    rho_new = jnp.where(ok, 1.0 / jnp.where(ok, sy, 1.0), 0.0)
    return _History(
        s=jnp.where(ok, hist.s.at[idx].set(s), hist.s),
        y=jnp.where(ok, hist.y.at[idx].set(y), hist.y),
        rho=jnp.where(ok, hist.rho.at[idx].set(rho_new), hist.rho),
        count=hist.count + jnp.where(ok, 1, 0),
    )


class _LSResult(NamedTuple):
    t: Array
    f_new: Array
    improved: Array


def _armijo_line_search(
    fun: ValueAndGrad, w: Array, f: Array, d: Array, dderiv: Array, t0: Array,
    max_iters: int,
) -> _LSResult:
    """Backtracking line search on f(w + t d) with the Armijo condition.

    ``dderiv`` is the directional derivative used in the sufficient-decrease
    test (g.d for L-BFGS; the pseudo-gradient version for OWL-QN overrides
    the evaluation function instead).
    """

    def cond(state):
        t, f_new, it, done = state
        return (~done) & (it < max_iters)

    def body(state):
        t, _, it, _ = state
        f_new, _ = fun(w + t * d)
        ok = f_new <= f + _C1 * t * dderiv
        # keep t on success; otherwise shrink for the next probe
        t_next = jnp.where(ok, t, t * _BACKTRACK)
        return t_next, f_new, it + 1, ok

    t, f_new, _, done = lax.while_loop(
        cond, body, (t0, f, jnp.asarray(0), jnp.asarray(False))
    )
    return _LSResult(t=t, f_new=f_new, improved=done & (f_new < f))


class _State(NamedTuple):
    w: Array
    f: Array
    g: Array
    hist: _History
    iteration: Array
    code: Array
    losses: Array


def lbfgs_solve(
    fun: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig | None = None,
    *,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Minimize ``fun`` from ``w0``; jit- and vmap-compatible.

    ``tolerances`` can be supplied to skip the zero-coefficient evaluation
    (e.g. when the caller already computed it, or for exact parity control in
    warm starts).
    """
    config = config or OptimizerConfig()
    m = config.num_corrections
    d = w0.shape[-1]
    dtype = w0.dtype

    tol = tolerances if tolerances is not None else absolute_tolerances(
        fun, w0, config.tolerance)

    f0, g0 = fun(w0)
    losses = jnp.full((config.max_iterations + 1,), f0, dtype=dtype)
    init = _State(
        w=w0,
        f=f0,
        g=g0,
        hist=_History(
            s=jnp.zeros((m, d), dtype=dtype),
            y=jnp.zeros((m, d), dtype=dtype),
            rho=jnp.zeros((m,), dtype=dtype),
            count=jnp.asarray(0),
        ),
        iteration=jnp.asarray(0),
        code=jnp.asarray(0, dtype=jnp.int32),
        losses=losses,
    )

    def cond(state: _State):
        return state.code == 0

    def body(state: _State) -> _State:
        direction = _two_loop_direction(state.g, state.hist)
        dderiv = jnp.dot(state.g, direction)
        # Safeguard: if the two-loop direction is not a descent direction
        # (numerical breakdown), fall back to steepest descent.
        bad = dderiv >= 0.0
        direction = jnp.where(bad, -state.g, direction)
        dderiv = jnp.where(bad, -jnp.dot(state.g, state.g), dderiv)

        # First step along an unscaled gradient: temper by 1/|g| (Breeze's
        # first-iteration heuristic); afterwards the two-loop scaling makes
        # t0 = 1 the right initial probe.
        gnorm = _l2norm(state.g)
        t0 = jnp.where(
            state.hist.count == 0,
            jnp.minimum(jnp.asarray(1.0, dtype), 1.0 / jnp.maximum(gnorm, 1e-12)),
            jnp.asarray(1.0, dtype),
        )
        ls = _armijo_line_search(
            fun, state.w, state.f, direction, dderiv, t0,
            config.max_line_search_iterations,
        )

        w_new = project_box(state.w + ls.t * direction, config.box_constraints)
        f_new, g_new = fun(w_new)
        # A failed line search (or a projection that un-does the decrease)
        # means the objective cannot improve from here.
        accept = ls.improved & (f_new < state.f)
        w_acc = jnp.where(accept, w_new, state.w)
        f_acc = jnp.where(accept, f_new, state.f)
        g_acc = jnp.where(accept, g_new, state.g)
        hist = _push_history(state.hist, w_acc - state.w, g_acc - state.g)
        hist = jax.tree.map(
            lambda new, old: jnp.where(accept, new, old), hist, state.hist
        )

        iteration = state.iteration + jnp.where(accept, 1, 0)
        code = convergence_code(
            iteration=iteration,
            max_iterations=config.max_iterations,
            loss_delta=state.f - f_acc,
            gradient_norm=_l2norm(g_acc),
            tol=tol,
            not_improving=~accept,
        )
        losses = state.losses.at[iteration].set(f_acc)
        return _State(w_acc, f_acc, g_acc, hist, iteration, code, losses)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=_l2norm(final.g),
        iterations=final.iteration,
        convergence_reason=final.code,
        loss_history=final.losses,
    )
