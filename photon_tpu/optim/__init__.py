"""Optimizers: L-BFGS, OWL-QN, TRON as pure lax.while_loop programs.

``solve`` mirrors the reference's OptimizerFactory dispatch
(photon-lib optimization/OptimizerFactory.scala:74): LBFGS vs TRON by
configured type, with OWL-QN substituted automatically whenever an L1 term is
present.
"""

from __future__ import annotations

import jax

from photon_tpu.optim.base import (
    ConvergenceReason,
    HessianVectorProduct,
    OptResult,
    OptimizerConfig,
    OptimizerType,
    Tolerances,
    ValueAndGrad,
    absolute_tolerances,
    convergence_code,
)
from photon_tpu.optim.lbfgs import lbfgs_solve
from photon_tpu.optim.lbfgsb import lbfgsb_solve
from photon_tpu.optim.owlqn import owlqn_solve
from photon_tpu.optim.regularization import (
    RegularizationContext,
    RegularizationType,
    inverse_prior_variances,
    with_gaussian_prior,
    with_gaussian_prior_hvp,
    with_l2,
    with_l2_hvp,
    with_l2_hvp_masked,
    with_l2_masked,
)
from photon_tpu.optim.tron import tron_solve

Array = jax.Array

__all__ = [
    "ConvergenceReason",
    "HessianVectorProduct",
    "OptResult",
    "OptimizerConfig",
    "OptimizerType",
    "RegularizationContext",
    "RegularizationType",
    "Tolerances",
    "ValueAndGrad",
    "lbfgs_solve",
    "lbfgsb_solve",
    "owlqn_solve",
    "solve",
    "tron_solve",
    "inverse_prior_variances",
    "with_gaussian_prior",
    "with_gaussian_prior_hvp",
    "with_l2",
    "with_l2_hvp",
    "with_l2_hvp_masked",
    "with_l2_masked",
]


def solve(
    fun: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig | None = None,
    *,
    l1_weight: float = 0.0,
    l2_weight: float = 0.0,
    intercept_index: int | None = None,
    hvp: HessianVectorProduct | None = None,
    tolerances: Tolerances | None = None,
) -> OptResult:
    """Factory-style entry point: compose regularization onto ``fun`` and
    dispatch to the right solver.

    - L2 is folded into the objective closure (mixin equivalent,
      intercept excluded);
    - a nonzero L1 weight routes to OWL-QN regardless of configured type
      (OptimizerFactory semantics — Breeze OWLQN replaces LBFGS when L1 is
      present; TRON does not support L1 in the reference either);
    - TRON requires an ``hvp``.
    """
    config = config or OptimizerConfig()
    obj = fun if l2_weight == 0.0 else with_l2(fun, l2_weight, intercept_index)

    if l1_weight != 0.0:
        return owlqn_solve(obj, w0, l1_weight, config, tolerances=tolerances)

    if config.optimizer_type == OptimizerType.TRON:
        if hvp is None:
            raise ValueError("TRON requires a Hessian-vector-product closure")
        obj_hvp = (
            hvp if l2_weight == 0.0
            else with_l2_hvp(hvp, l2_weight, intercept_index)
        )
        return tron_solve(obj, obj_hvp, w0, config, tolerances=tolerances)

    return lbfgs_solve(obj, w0, config, tolerances=tolerances)
