"""Cross-cutting utilities: section timing + device profiling hooks."""

from photon_tpu.utils.compile_cache import (
    cache_stats,
    compile_event_count,
    enable_compilation_cache,
)
from photon_tpu.utils.timed import Timed, profile_trace

__all__ = [
    "Timed",
    "cache_stats",
    "compile_event_count",
    "enable_compilation_cache",
    "profile_trace",
]
