"""Cross-cutting utilities: section timing + device profiling hooks."""

from photon_tpu.utils.timed import Timed, profile_trace

__all__ = ["Timed", "profile_trace"]
