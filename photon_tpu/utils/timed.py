"""Section timing + optional device profiling.

.. deprecated::
    ``Timed`` is a compatibility SHIM over the unified telemetry layer
    (``photon_tpu.obs.span`` — see OBSERVABILITY.md): it keeps the
    reference-parity logging contract ("<msg>: begin execution" /
    "<msg>: executed in <t> s", util/Timed.scala:53-80) and the
    ``.seconds`` attribute, but new code should open an ``obs.span``
    directly — spans nest into one tree, carry the host/device split,
    and export through the JSONL/snapshot surfaces. Direct ``Timed`` use
    emits a :class:`DeprecationWarning` (hidden by default; visible
    under ``-W error::DeprecationWarning``).

``profile_trace`` is likewise a deprecated shim: the one profiling
entry point is now ``photon_tpu.obs.trace.profile_session``, which runs
the same ``jax.profiler.trace`` capture INSIDE an obs span bracketed by
``profile.start``/``profile.stop`` instants, so the captured device
profile is correlated with the exported host timeline by construction.
"""

from __future__ import annotations

import contextlib
import logging
import time
import warnings

logger = logging.getLogger("photon_tpu.timed")


class Timed:
    """Context manager: log begin/end + duration of a named section.

    Reference: Timed.measureDuration (util/Timed.scala:53-80) — logs
    "<msg>: begin execution" then "<msg>: executed in <t> s". The elapsed
    time is exposed as ``.seconds`` for programmatic use (the reference's
    OptimizationStatesTracker timing role).

    Deprecated shim: delegates to ``obs.logged_span`` — the ONE
    logged-section helper — so the log format and span naming cannot
    diverge between legacy call sites and migrated ones; this class only
    adds the ``.seconds`` attribute on top.
    """

    def __init__(self, msg: str, log: logging.Logger | None = None):
        warnings.warn(
            "photon_tpu.utils.Timed is deprecated; use "
            "photon_tpu.obs.logged_span (see OBSERVABILITY.md)",
            DeprecationWarning,
            stacklevel=2,
        )
        self.msg = msg
        self.log = log or logger
        self.seconds = 0.0
        self._cm = None

    def __enter__(self) -> "Timed":
        from photon_tpu import obs

        self._cm = obs.logged_span(self.msg, self.log)
        self._cm.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        cm, self._cm = self._cm, None
        cm.__exit__(exc_type, exc, tb)


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Wrap a block in ``jax.profiler.trace`` when a directory is given.

    .. deprecated::
        Shim over :func:`photon_tpu.obs.trace.profile_session` — THE
        profiling entry point, which additionally correlates the
        captured device profile with the obs span timeline. A None
        directory remains a no-op that never touches jax.
    """
    if not trace_dir:
        yield
        return
    warnings.warn(
        "photon_tpu.utils.profile_trace is deprecated; use "
        "photon_tpu.obs.trace.profile_session (see OBSERVABILITY.md)",
        DeprecationWarning,
        stacklevel=3,
    )
    from photon_tpu.obs.trace import profile_session

    with profile_session(trace_dir):
        yield
