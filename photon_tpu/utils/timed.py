"""Section timing + optional device profiling.

TPU-native counterpart of photon-lib util/Timed.scala:33 — the
``Timed("msg"){block}`` wall-clock section logger used pervasively by the
reference's drivers and estimator — plus a ``jax.profiler.trace`` wrapper for
real device traces (the capability the reference delegates to the Spark UI).
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("photon_tpu.timed")


class Timed:
    """Context manager: log begin/end + duration of a named section.

    Reference: Timed.measureDuration (util/Timed.scala:53-80) — logs
    "<msg>: begin execution" then "<msg>: executed in <t> s". The elapsed
    time is exposed as ``.seconds`` for programmatic use (the reference's
    OptimizationStatesTracker timing role).
    """

    def __init__(self, msg: str, log: logging.Logger | None = None):
        self.msg = msg
        self.log = log or logger
        self.seconds = 0.0

    def __enter__(self) -> "Timed":
        self.log.info("%s: begin execution", self.msg)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        self.log.info("%s: executed in %.3f s", self.msg, self.seconds)


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Wrap a block in ``jax.profiler.trace`` when a directory is given.

    Produces a TensorBoard-loadable device trace; a None directory is a
    no-op so call sites can wire it unconditionally.
    """
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
