"""Persistent XLA compilation cache wiring + hit/miss instrumentation.

The reference pays no compilation cost (Spark ships interpreted closures);
the TPU build's analog of that "instant start" is XLA's persistent
compilation cache: compiled executables keyed by HLO hash land in a local
directory, so repeated runs of the same shapes (the CLI on a daily cadence,
the bench, tuner re-entries in fresh processes) skip the compile entirely.

``cache_stats()`` exposes what the cache actually did this process —
hit/miss counts from JAX's monitoring events plus the on-disk entry
count/bytes — so ``bench.py`` can report the hit-rate next to
``warm_cache_e2e_seconds`` (the BENCH_r05 anomaly where the warm rerun was
SLOWER than cold is unexplainable without knowing whether the cache ever
hit).
"""

from __future__ import annotations

import os
import threading

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "photon_tpu_xla"
)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The counters here are written from whatever thread
# happens to compile: `_on_event` fires from JAX's monitoring hooks
# during any compile (including the ingest pipeline's background
# AOT-compile thread), and `aot_compile` itself runs ON that thread —
# concurrent with the training thread's jit fallbacks. Before this
# contract the dict updates were bare `+=` on a module global (torn
# read-modify-write under free threading, lost updates under the GIL's
# ~5ms switch interval); every write now takes the module lock. The
# XLA compile in `aot_compile` runs OUTSIDE the lock (minutes-long on
# real programs — the `blocking-under-lock` rule's worst case).
CONCURRENCY_AUDIT = dict(
    name="compile-cache",
    locks={
        "_lock": ("_stats", "_listener_installed", "_dir_in_effect"),
    },
    thread_entries=("_on_event", "aot_compile"),
    jax_dispatch_ok={
        "aot_compile": "the whole point of the entry: XLA compiles in "
        "C++ with the GIL released on the pipeline's dedicated compile "
        "thread; the Lowered it compiles is thread-private and the "
        "persistent-cache singleton is thread-safe in JAX",
    },
)

_lock = threading.Lock()

# Monitoring event -> counter key. Misses are recorded by
# jax/_src/compilation_cache.py on a failed lookup; hits by
# jax/_src/compiler.py when a compiled executable is served from disk.
_EVENTS = {
    "/jax/compilation_cache/cache_hits": "persistent_hits",
    "/jax/compilation_cache/cache_misses": "persistent_misses",
}

_stats = {
    "persistent_hits": 0,
    "persistent_misses": 0,
    # Ingest pipeline's overlapped warm compiles (data/pipeline.py): how
    # many AOT compiles ran in the background and their total seconds —
    # compile work that e2e wall-clock should NOT see when the overlap
    # holds.
    "aot_compiles": 0,
    "aot_compile_seconds": 0.0,
}
_listener_installed = False
_dir_in_effect: str | None = None


def aot_compile(lowered, *, ledger_key: str | None = None):
    """Compile a ``jax.stages.Lowered`` for the warm-compile stage.

    The compile runs through the SAME persistent-cache wiring as any jit
    compile (the cache singleton keys on HLO hash), so even when the
    resulting executable goes unused — a stale shape prediction — the
    fallback jit path's compile becomes a cache hit instead of a second
    full compile. Counted in ``cache_stats()``.

    A RETRIED site (resilience layer): a transient compile failure — a
    flaky compiler RPC on tunneled backends, the injected
    ``compile.aot`` fault — re-runs ``lowered.compile()`` with backoff;
    deterministic compile errors propagate on the first attempt.

    ``ledger_key`` names this compile in the cost ledger's compile-time
    account (obs/ledger.py) — callers pass their cache key (the serve
    ladder's rung, the fused generation's AOT label); None books under
    ``aot`` when the ledger is armed.
    """
    import time

    from photon_tpu.resilience import retry

    t0 = time.perf_counter()
    compiled = retry.retrying_check(
        "compile.aot", lowered.compile, site="compile_cache.aot_compile"
    )
    seconds = time.perf_counter() - t0
    with _lock:
        _stats["aot_compiles"] += 1
        _stats["aot_compile_seconds"] += seconds
    try:
        from photon_tpu.obs import ledger

        ledger.record_compile(ledger_key or "aot", seconds)
    except Exception:  # pragma: no cover — telemetry must never abort
        pass
    return compiled


def _on_event(event: str, **kwargs) -> None:
    key = _EVENTS.get(event)
    if key is not None:
        with _lock:
            _stats[key] += 1
        # Side-feed the unified telemetry registry (photon_tpu.obs) so
        # cache behavior shows up in the same snapshot/JSONL stream as
        # spans and pipeline stages (outside the module lock — the
        # registry takes its own). Guarded: monitoring events can fire
        # from compile paths during interpreter teardown.
        try:
            from photon_tpu import obs

            if obs.enabled():
                obs.REGISTRY.counter(
                    "compile_cache_events_total",
                    event=key.removeprefix("persistent_"),
                ).inc()
        except Exception:  # pragma: no cover — telemetry must never abort
            pass


def _install_listener() -> None:
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        import jax.monitoring

        # Listeners are append-only in jax (no unregister API); one
        # process-lifetime counter hook is the intended use. Latched
        # under the lock so two racing enable calls cannot register
        # the listener (and double-count every event) twice.
        jax.monitoring.register_event_listener(_on_event)
        _listener_installed = True


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a local directory.

    Resolution order: explicit argument, ``PHOTON_COMPILE_CACHE`` env var,
    ``~/.cache/photon_tpu_xla``. The value ``off`` (env or argument)
    disables wiring. Safe to call multiple times; returns the directory in
    effect (or None when disabled).
    """
    import jax

    global _dir_in_effect

    if cache_dir is None:  # photon: ignore[spmd-host-divergence] -- cache dir is host-local config; changes where artifacts persist, never what is traced
        cache_dir = os.environ.get("PHOTON_COMPILE_CACHE", _DEFAULT_DIR)
    if not cache_dir or cache_dir.lower() == "off":  # photon: ignore[spmd-host-divergence] -- cache dir is host-local config; changes where artifacts persist, never what is traced
        # Genuinely disable: a process that enabled the cache earlier
        # must stop persisting/hitting it, or cache_stats() would report
        # dir=None while the counters keep climbing.
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_cache_singleton()
        with _lock:
            _dir_in_effect = None
        return None
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything that took meaningful compile time; the default
    # threshold (1s) would skip many of the small eager-op programs whose
    # first-compile latency dominates cold starts on remote backends.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    # JAX initializes the cache singleton AT MOST ONCE, on the first
    # compile: if anything jitted before this call (an import-time eager
    # op is enough), the singleton latched "no directory" and every
    # later compile skips the cache silently. Reset so the directory
    # configured above actually takes effect.
    _reset_cache_singleton()
    _install_listener()
    with _lock:
        _dir_in_effect = cache_dir
    return cache_dir


def _reset_cache_singleton() -> None:
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover — internal API may move
        pass


def _dir_stats(cache_dir: str) -> tuple[int, int]:
    entries = 0
    total = 0
    try:
        for de in os.scandir(cache_dir):
            if de.is_file():
                entries += 1
                total += de.stat().st_size
    except OSError:
        pass
    return entries, total


def compile_event_count() -> int:
    """Total persistent-cache requests seen so far (hits + misses).

    A DELTA of this across a window is the runtime zero-recompile
    check the serving path uses: any compile attempted in the window —
    whether the disk cache served it or not — moves the count, so a
    steady-state loop that "adds zero programs" must leave it flat
    (bench.py ``serving_compile_events``, cli/serve.py
    ``compile_events_during_serving``). Only meaningful while the
    persistent cache is enabled (the monitoring listener is installed
    by ``enable_compilation_cache``).
    """
    with _lock:
        return _stats["persistent_hits"] + _stats["persistent_misses"]


def cache_stats() -> dict:
    """Hit/miss counters + on-disk footprint of the persistent cache.

    ``persistent_hits``/``persistent_misses`` count this process's
    compile requests served from / missed in the directory cache (a miss
    is a real compile). ``hit_rate`` is None before any request. The
    ``entries``/``bytes`` pair is the directory scan at call time — a
    cross-process view of what the next cold start will find.
    """
    with _lock:
        snap = dict(_stats)
        cache_dir = _dir_in_effect
    hits = snap["persistent_hits"]
    misses = snap["persistent_misses"]
    total = hits + misses
    # The directory scan stays outside the lock: it is filesystem I/O
    # and must not stall a compile thread's counter update.
    entries, size = _dir_stats(cache_dir) if cache_dir else (0, 0)
    return {
        "dir": cache_dir,
        "persistent_hits": hits,
        "persistent_misses": misses,
        "hit_rate": (hits / total) if total else None,
        "entries": entries,
        "bytes": size,
        "aot_compiles": snap["aot_compiles"],
        "aot_compile_seconds": round(snap["aot_compile_seconds"], 4),
    }
