"""Persistent XLA compilation cache wiring.

The reference pays no compilation cost (Spark ships interpreted closures);
the TPU build's analog of that "instant start" is XLA's persistent
compilation cache: compiled executables keyed by HLO hash land in a local
directory, so repeated runs of the same shapes (the CLI on a daily cadence,
the bench, tuner re-entries in fresh processes) skip the compile entirely.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "photon_tpu_xla"
)


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a local directory.

    Resolution order: explicit argument, ``PHOTON_COMPILE_CACHE`` env var,
    ``~/.cache/photon_tpu_xla``. The value ``off`` (env or argument)
    disables wiring. Safe to call multiple times; returns the directory in
    effect (or None when disabled).
    """
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("PHOTON_COMPILE_CACHE", _DEFAULT_DIR)
    if not cache_dir or cache_dir.lower() == "off":
        return None
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything that took meaningful compile time; the default
    # threshold (1s) would skip many of the small eager-op programs whose
    # first-compile latency dominates cold starts on remote backends.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return cache_dir
