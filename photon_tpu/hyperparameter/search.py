"""Random and Bayesian (GP) hyperparameter search.

TPU-native counterpart of photon-lib hyperparameter/search/RandomSearch.scala:34
(Sobol-sequence quasi-random draws, :46-51) and
GaussianProcessSearch.scala:52 (GP posterior over the evaluation function,
expected-improvement candidate selection, :79-120). Candidates live in the
unit cube [0, 1]^d; the evaluation function owns the mapping to real
hyperparameters (see rescaling / GameEstimatorEvaluationFunction).
"""

from __future__ import annotations

import math

import numpy as np

from photon_tpu.hyperparameter.criteria import ExpectedImprovement
from photon_tpu.hyperparameter.gp import GaussianProcessEstimator


class _SobolGenerator:
    """Quasi-random equidistributed draws in [0, 1]^d.

    The reference uses commons-math SobolSequenceGenerator skipped ahead by
    the seed (RandomSearch.scala:46-51); scipy's generator (a baked-in jax
    dependency) provides the same low-discrepancy sequence.
    """

    def __init__(self, dim: int, seed: int):
        from scipy.stats import qmc

        self._sobol = qmc.Sobol(d=dim, scramble=False)
        skip = seed % 65536
        if skip:
            self._sobol.fast_forward(skip)

    def draw(self, n: int) -> np.ndarray:
        return self._sobol.random(n)


class RandomSearch:
    """Uniform (Sobol) search of the unit cube (RandomSearch.scala:34).

    ``evaluation_function`` follows the EvaluationFunction contract
    (hyperparameter/EvaluationFunction.scala:25): ``apply(candidate) ->
    (value, result)`` where LOWER values are better (the adapter flips signs
    for maximize-metrics), and ``convert_observations(results) ->
    [(vector, value)]``.
    """

    def __init__(
        self,
        num_params: int,
        evaluation_function,
        discrete_params: dict[int, int] | None = None,
        kernel: str = "matern52",
        seed: int = 0,
    ):
        if num_params <= 0:
            raise ValueError("Number of parameters must be positive.")
        self.num_params = num_params
        self.evaluation_function = evaluation_function
        self.discrete_params = dict(discrete_params or {})
        self.kernel = kernel
        self.seed = seed
        self._sobol = _SobolGenerator(num_params, seed)

    # -- public API (find / findWithPriorObservations / findWithPriors) ----

    def find(self, n: int) -> list:
        return self.find_with_prior_observations(n, [])

    def find_with_prior_observations(self, n: int, prior_observations) -> list:
        """RandomSearch.findWithPriorObservations :104-117."""
        if n <= 0:
            raise ValueError("The number of results must be greater than zero.")
        candidate = self._discretize(self.draw_candidates(1)[0])
        _, result = self.evaluation_function(candidate)
        if n == 1:
            return [result]
        observations = self.evaluation_function.convert_observations([result])
        return [result] + self.find_with_priors(
            n - 1, observations, prior_observations
        )

    def find_with_priors(self, n: int, observations, prior_observations) -> list:
        """RandomSearch.findWithPriors :61-95."""
        if n <= 0:
            raise ValueError("The number of results must be greater than zero.")
        if not observations:
            raise ValueError("There must be at least one observation.")
        for point, value in observations[:-1]:
            self._on_observation(np.asarray(point, dtype=float), value)
        for point, value in prior_observations:
            self._on_prior_observation(np.asarray(point, dtype=float), value)

        results = []
        last_candidate, last_value = observations[-1]
        last_candidate = np.asarray(last_candidate, dtype=float)
        for _ in range(n):
            candidate = self._discretize(
                self._next(last_candidate, last_value)
            )
            value, result = self.evaluation_function(candidate)
            results.append(result)
            last_candidate, last_value = candidate, value
        return results

    # -- extension points ---------------------------------------------------

    def _next(self, last_candidate, last_value) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def _on_observation(self, point: np.ndarray, value: float) -> None:
        pass

    def _on_prior_observation(self, point: np.ndarray, value: float) -> None:
        pass

    # -- helpers ------------------------------------------------------------

    def draw_candidates(self, n: int) -> np.ndarray:
        return self._sobol.draw(n)

    def _discretize(self, candidate: np.ndarray) -> np.ndarray:
        return discretize_candidate(candidate, self.discrete_params)


class GaussianProcessSearch(RandomSearch):
    """GP-guided search (GaussianProcessSearch.scala:52).

    Each step fits a GP (slice-sampled kernel hyperparameters) to the
    mean-centered observations plus any prior observations, scores a Sobol
    candidate pool by expected improvement, and evaluates the best candidate.
    Falls back to uniform draws until there are more observations than
    dimensions (under-determined regime).
    """

    def __init__(
        self,
        num_params: int,
        evaluation_function,
        discrete_params: dict[int, int] | None = None,
        kernel: str = "matern52",
        candidate_pool_size: int = 250,
        noisy_target: bool = True,
        seed: int = 0,
    ):
        super().__init__(
            num_params, evaluation_function, discrete_params, kernel, seed
        )
        self.candidate_pool_size = candidate_pool_size
        self.noisy_target = noisy_target
        self._points: list[np.ndarray] = []
        self._values: list[float] = []
        self._best = math.inf
        self._prior_points: list[np.ndarray] = []
        self._prior_values: list[float] = []
        self._prior_best = math.inf
        self.last_model = None

    def _on_observation(self, point, value) -> None:
        self._points.append(np.asarray(point, dtype=float))
        self._values.append(float(value))
        self._best = min(self._best, float(value))

    def _on_prior_observation(self, point, value) -> None:
        self._prior_points.append(np.asarray(point, dtype=float))
        self._prior_values.append(float(value))
        self._prior_best = min(self._prior_best, float(value))

    def _next(self, last_candidate, last_value) -> np.ndarray:
        """GaussianProcessSearch.next :79-120."""
        self._on_observation(last_candidate, last_value)

        if len(self._points) <= self.num_params:
            return super()._next(last_candidate, last_value)

        candidates = self.draw_candidates(self.candidate_pool_size)
        values = np.asarray(self._values)
        current_mean = float(values.mean())
        overall_best = min(self._prior_best, self._best - current_mean)
        transformation = ExpectedImprovement(overall_best)

        points = np.stack(self._points)
        evals = values - current_mean
        if self._prior_points:
            points = np.vstack([points, np.stack(self._prior_points)])
            evals = np.concatenate([evals, np.asarray(self._prior_values)])

        estimator = GaussianProcessEstimator(
            kernel=self.kernel,
            normalize_labels=False,
            noisy_target=self.noisy_target,
            seed=self.seed,
        )
        model = estimator.fit(points, evals)
        self.last_model = model

        predictions = model.predict_transformed(candidates, transformation)
        return self._select_best_candidate(
            candidates, predictions, transformation
        )

    @staticmethod
    def _select_best_candidate(candidates, predictions, transformation):
        """argmax (EI) or argmin (CB) over the pool
        (selectBestCandidate :166-189)."""
        idx = (
            int(np.argmax(predictions))
            if transformation.is_max_opt
            else int(np.argmin(predictions))
        )
        return candidates[idx]


def discretize_candidate(
    candidate: np.ndarray, discrete_params: dict[int, int]
) -> np.ndarray:
    """floor(v*k)/k on discrete dims (discretizeCandidate :168-180)."""
    out = np.array(candidate, dtype=float)
    for index, k in discrete_params.items():
        out[index] = math.floor(out[index] * k) / k
    return out
