"""Shrink the hyperparameter search range around a prior optimum.

TPU-native counterpart of photon-client
hyperparameter/ShrinkSearchRange.scala:147 (getBounds): fit a GP to prior
observations (rescaled into the unit cube), locate the best predicted point
over a Sobol candidate pool, and return a ``radius``-wide box around it in
the CONFIG-RANGE space (i.e. transformed space for LOG/SQRT variables —
exactly what the reference's scaleBackward returns, ready to use as new
config ranges), clamped to the configured ranges — the warm-started
search-space reduction used when retraining on fresh data.
"""

from __future__ import annotations

import numpy as np

from photon_tpu.hyperparameter.gp import GaussianProcessEstimator
from photon_tpu.hyperparameter.rescaling import scale_backward
from photon_tpu.hyperparameter.search import (
    _SobolGenerator,
    discretize_candidate,
)
from photon_tpu.hyperparameter.serialization import (
    HyperparameterConfig,
    prior_from_json,
    rescale_prior_observations,
)


def get_bounds(
    config: HyperparameterConfig,
    prior_json: str,
    prior_default: dict[str, str],
    radius: float,
    candidate_pool_size: int = 1000,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """(lower, upper) bounds in config-range space
    (ShrinkSearchRange.getBounds): for LOG/SQRT variables these are
    transformed-space values, directly usable as new config ranges.

    The best candidate is the Sobol pool point with the LOWEST GP-predicted
    evaluation (the search minimizes); the box [best - radius, best + radius]
    on the unit cube maps back through scaleBackward and clamps to the
    configured ranges.
    """
    priors = prior_from_json(prior_json, prior_default, config.names)
    if not priors:
        raise ValueError("no prior observations to shrink around")
    rescaled = rescale_prior_observations(priors, config)
    points = np.stack([p for p, _ in rescaled])
    evals = np.asarray([v for _, v in rescaled])

    model = GaussianProcessEstimator(kernel="matern52", seed=seed).fit(
        points, evals)
    candidates = _SobolGenerator(len(config.names), seed).draw(
        candidate_pool_size)
    means, _ = model.predict(candidates)
    best = candidates[int(np.argmin(means))]

    discrete_set = set(config.discrete_params)
    upper = scale_backward(
        discretize_candidate(best + radius, config.discrete_params),
        config.ranges, discrete_set,
    )
    lower = scale_backward(
        discretize_candidate(best - radius, config.discrete_params),
        config.ranges, discrete_set,
    )
    for i, r in enumerate(config.ranges):
        upper[i] = min(upper[i], r.end)
        lower[i] = max(lower[i], r.start)
    return lower, upper
