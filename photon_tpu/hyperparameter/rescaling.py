"""Hyperparameter vector rescaling between user space and the unit cube.

TPU-native counterpart of photon-lib hyperparameter/VectorRescaling.scala:150:
forward/backward LOG (base 10) and SQRT transforms on selected indices, and
linear scaling of each dimension into [0, 1] given per-dimension ranges, with
the reference's +1 width adjustment for discrete dimensions. Host-side numpy —
these are tiny vectors manipulated between search iterations.
"""

from __future__ import annotations

import dataclasses

import numpy as np

LOG_TRANSFORM = "LOG"
SQRT_TRANSFORM = "SQRT"


@dataclasses.dataclass(frozen=True)
class DoubleRange:
    """Closed interval (util/DoubleRange.scala)."""

    start: float
    end: float

    def transform(self, fn) -> "DoubleRange":
        return DoubleRange(fn(self.start), fn(self.end))


def transform_forward(vector, transform_map: dict[int, str]) -> np.ndarray:
    out = np.array(vector, dtype=float)
    for index, transform in transform_map.items():
        if transform == LOG_TRANSFORM:
            out[index] = np.log10(out[index])
        elif transform == SQRT_TRANSFORM:
            out[index] = np.sqrt(out[index])
        else:
            raise ValueError(f"Unknown transformation: {transform}")
    return out


def transform_backward(vector, transform_map: dict[int, str]) -> np.ndarray:
    out = np.array(vector, dtype=float)
    for index, transform in transform_map.items():
        if transform == LOG_TRANSFORM:
            out[index] = 10.0 ** out[index]
        elif transform == SQRT_TRANSFORM:
            out[index] = out[index] ** 2
        else:
            raise ValueError(f"Unknown transformation: {transform}")
    return out


def _range_arrays(ranges, discrete_index_set):
    start = np.array([r.start for r in ranges])
    end = np.array([r.end for r in ranges])
    adj = np.array([
        1.0 if i in (discrete_index_set or set()) else 0.0
        for i in range(len(ranges))
    ])
    return start, end, adj


def scale_forward(vector, ranges, discrete_index_set=None) -> np.ndarray:
    """User space -> [0, 1]^d (scaleForward; discrete dims widen by 1)."""
    start, end, adj = _range_arrays(ranges, discrete_index_set)
    return (np.array(vector, dtype=float) - start) / (end - start + adj)


def scale_backward(vector, ranges, discrete_index_set=None) -> np.ndarray:
    """[0, 1]^d -> user space (scaleBackward)."""
    start, end, adj = _range_arrays(ranges, discrete_index_set)
    return np.array(vector, dtype=float) * (end - start + adj) + start


def rescale_priors(priors, ranges, transform_map, discrete_index_set=None):
    """Map prior (candidate, eval) pairs into the unit cube
    (VectorRescaling.rescalePriors)."""
    out = []
    for candidate, value in priors:
        t = transform_forward(candidate, transform_map)
        out.append((scale_forward(t, ranges, discrete_index_set), value))
    return out
