"""Slice sampler for GP kernel hyperparameters.

TPU-native counterpart of photon-lib hyperparameter/SliceSampler.scala:52 —
the classic Neal (2003) step-out / shrink procedure. The control flow is
host-side numpy (slice sampling is inherently sequential and data-dependent);
the log-density callback is typically a jitted jnp function, so the expensive
Cholesky factorizations still run on device.
"""

from __future__ import annotations

import numpy as np


class SliceSampler:
    """Reference: SliceSampler.scala:52 (stepSize 1.0, maxStepsOut 1000)."""

    def __init__(self, step_size: float = 1.0, max_steps_out: int = 1000,
                 rng: np.random.Generator | None = None, seed: int = 0):
        self.step_size = step_size
        self.max_steps_out = max_steps_out
        self.rng = rng if rng is not None else np.random.default_rng(seed)

    def draw(self, x: np.ndarray, logp) -> np.ndarray:
        """One sample along a uniformly random direction (draw :70-76)."""
        direction = self.rng.normal(size=x.shape[0])
        direction = direction / np.linalg.norm(direction)
        return self._draw_along(np.asarray(x, dtype=float), logp, direction)

    def draw_dimension_wise(self, x: np.ndarray, logp) -> np.ndarray:
        """One Gibbs sweep: each axis in shuffled order (drawDimensionWise)."""
        x = np.asarray(x, dtype=float)
        dims = self.rng.permutation(x.shape[0])
        for i in dims:
            direction = np.zeros(x.shape[0])
            direction[i] = 1.0
            x = self._draw_along(x, logp, direction)
        return x

    def _draw_along(self, x, logp, direction) -> np.ndarray:
        y = np.log(self.rng.uniform()) + float(logp(x))
        lower, upper = self._step_out(x, y, logp, direction)
        # Shrink until a point on the slice is found (draw :94-113).
        for _ in range(1000):
            t = self.rng.uniform()
            new_x = lower + t * (upper - lower)
            if float(logp(new_x)) > y:
                return new_x
            if new_x @ direction < x @ direction:
                lower = new_x
            elif new_x @ direction > x @ direction:
                upper = new_x
            else:
                raise RuntimeError("Slice size shrank to zero.")
        raise RuntimeError("slice sampler failed to find an acceptable point")

    def _step_out(self, x, y, logp, direction):
        """Widen the slice until both ends fall below y (stepOut :135-155)."""
        lower = x - direction * self.rng.uniform() * self.step_size
        upper = lower + direction * self.step_size
        steps = 0
        while float(logp(lower)) > y and steps < self.max_steps_out:
            lower = lower - direction * self.step_size
            steps += 1
        steps = 0
        while float(logp(upper)) > y and steps < self.max_steps_out:
            upper = upper + direction * self.step_size
            steps += 1
        return lower, upper
