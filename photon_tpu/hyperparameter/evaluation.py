"""Evaluation-function adapters: hyperparameter vector -> retrain -> metric.

TPU-native counterpart of photon-lib hyperparameter/EvaluationFunction.scala:25
(the search-facing contract) and photon-client
estimators/GameEstimatorEvaluationFunction.scala:40 (the GAME adapter): a
candidate point in the unit cube is scaled back to (log-space) regularization
weights / elastic-net alphas, expanded into a full GAME optimization
configuration, and evaluated by a FULL retrain + validation evaluation.
Lower values are better inside the search; maximize-metrics (AUC) are
sign-flipped on the way in and out.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Protocol

import numpy as np

from photon_tpu.algorithm.problems import GLMOptimizationConfiguration
from photon_tpu.hyperparameter.rescaling import (
    DoubleRange,
    scale_backward,
    scale_forward,
)
from photon_tpu.optim.regularization import RegularizationType

# GameEstimatorEvaluationFunction.scala:242-243.
DEFAULT_REG_WEIGHT_RANGE = DoubleRange(1e-4, 1e4)
DEFAULT_REG_ALPHA_RANGE = DoubleRange(0.0, 1.0)



class EvaluationFunction(Protocol):
    """hyperparameter/EvaluationFunction.scala:25."""

    def __call__(self, candidate: np.ndarray) -> tuple[float, object]: ...

    def convert_observations(
        self, results: list
    ) -> list[tuple[np.ndarray, float]]: ...


@dataclasses.dataclass
class GameEstimatorEvaluationFunction:
    """Adapter: unit-cube candidate -> GAME retrain -> validation metric.

    Reference: GameEstimatorEvaluationFunction.scala:40. The hyperparameter
    vector packs, per coordinate sorted by id: log(lambda) for L1/L2/
    ELASTIC_NET coordinates, plus alpha for ELASTIC_NET (NONE coordinates
    contribute no dimensions) — configurationToVector :151-183 /
    vectorToConfiguration :191-230.
    """

    estimator: object  # GameEstimator
    base_config: dict[str, GLMOptimizationConfiguration]
    data: object  # GameDataset
    validation_data: object  # GameDataset
    is_opt_max: bool
    # Warm-start / incremental-training model, forwarded into every retrain
    # (required when the estimator has incremental_training enabled).
    initial_model: object | None = None

    def __post_init__(self):
        self._coordinate_ids = sorted(self.base_config)
        ranges: list[DoubleRange] = []
        self._weight_range: dict[str, DoubleRange] = {}
        for cid in self._coordinate_ids:
            cfg = self.base_config[cid]
            raw_range = (
                DoubleRange(*cfg.regularization_weight_range)
                if cfg.regularization_weight_range is not None
                else DEFAULT_REG_WEIGHT_RANGE
            )
            if raw_range.start <= 0.0:
                raise ValueError(
                    f"coordinate {cid!r}: regularization weight range must "
                    f"start above 0 (weights are searched in log space), "
                    f"got {raw_range.start}"
                )
            self._weight_range[cid] = raw_range
            reg_range = raw_range.transform(math.log)
            alpha_range = (
                DoubleRange(*cfg.elastic_net_param_range)
                if cfg.elastic_net_param_range is not None
                else DEFAULT_REG_ALPHA_RANGE
            )
            t = cfg.regularization.regularization_type
            if t == RegularizationType.ELASTIC_NET:
                ranges.extend([reg_range, alpha_range])
            elif t in (RegularizationType.L1, RegularizationType.L2):
                ranges.append(reg_range)
        self.ranges = ranges
        self.num_params = len(ranges)

    # -- EvaluationFunction contract ---------------------------------------

    def __call__(self, candidate: np.ndarray) -> tuple[float, object]:
        scaled = scale_backward(candidate, self.ranges)
        config = self.vector_to_configuration(scaled)
        result = self.estimator.fit(
            self.data, self.validation_data, [config],
            initial_model=self.initial_model,
        )[0]
        direction = -1.0 if self.is_opt_max else 1.0
        return direction * result.evaluation.primary_evaluation, result

    def convert_observations(self, results) -> list[tuple[np.ndarray, float]]:
        out = []
        for result in results:
            vec = self.vectorize_params(result)
            scaled = scale_forward(vec, self.ranges)
            direction = -1.0 if self.is_opt_max else 1.0
            out.append((scaled, direction * self.get_evaluation_value(result)))
        return out

    def vectorize_params(self, result) -> np.ndarray:
        return self.configuration_to_vector(result.config)

    @staticmethod
    def get_evaluation_value(result) -> float:
        if result.evaluation is None:
            raise ValueError(
                "Can't extract evaluation value from a GAME result with no "
                "evaluations"
            )
        return result.evaluation.primary_evaluation

    # -- config <-> vector --------------------------------------------------

    def configuration_to_vector(
        self, configuration: dict[str, GLMOptimizationConfiguration]
    ) -> np.ndarray:
        if set(configuration) != set(self.base_config):
            raise ValueError(
                "Configuration coordinates mismatch; "
                f"{sorted(configuration)} != {self._coordinate_ids}"
            )
        values: list[float] = []
        for cid in self._coordinate_ids:
            cfg = configuration[cid]
            t = cfg.regularization.regularization_type
            # A grid config trained with lambda=0 must still vectorize — the
            # reference's math.log(0) yields -Infinity and poisons the GP, so
            # zero maps to the coordinate's configured range start (a fixed
            # 1e-12 floor would land far outside the unit cube and distort
            # the GP posterior near the boundary). Positive out-of-range
            # weights pass through unclamped: their true (out-of-cube)
            # location is finite and more honest to the GP than a relocated
            # boundary observation.
            w = cfg.regularization_weight
            if w <= 0.0:
                w = self._weight_range[cid].start
            if t == RegularizationType.ELASTIC_NET:
                alpha = (
                    1.0 if cfg.regularization.alpha is None
                    else cfg.regularization.alpha
                )
                values.extend([math.log(w), alpha])
            elif t in (RegularizationType.L1, RegularizationType.L2):
                values.append(math.log(w))
        return np.asarray(values)

    def vector_to_configuration(
        self, hyperparameters: np.ndarray
    ) -> dict[str, GLMOptimizationConfiguration]:
        if len(hyperparameters) != self.num_params:
            raise ValueError(
                f"Configuration dimension mismatch; {self.num_params} != "
                f"{len(hyperparameters)}"
            )
        queue = list(np.asarray(hyperparameters, dtype=float))
        out: dict[str, GLMOptimizationConfiguration] = {}
        for cid in self._coordinate_ids:
            cfg = self.base_config[cid]
            t = cfg.regularization.regularization_type
            if t == RegularizationType.ELASTIC_NET:
                weight = math.exp(queue.pop(0))
                alpha = min(max(queue.pop(0), 0.0), 1.0)
                out[cid] = dataclasses.replace(
                    cfg,
                    regularization=dataclasses.replace(
                        cfg.regularization, alpha=alpha
                    ),
                    regularization_weight=weight,
                )
            elif t in (RegularizationType.L1, RegularizationType.L2):
                out[cid] = cfg.with_regularization_weight(
                    math.exp(queue.pop(0))
                )
            else:
                out[cid] = cfg
        return out
