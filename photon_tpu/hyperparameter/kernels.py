"""Stationary covariance kernels for GP hyperparameter search — pure jnp.

TPU-native counterpart of the reference's kernel classes
(photon-lib hyperparameter/estimators/kernels/StationaryKernel.scala:189-loc,
Matern52.scala:44, RBF.scala:34, Kernel.scala). The Scala classes carry their
parameters as object state and loop over rows to build the Gram matrix; here a
kernel is a (name, theta) pair and every operation is a vectorized, jittable
function of ``theta = [amplitude, noise, length_scale...]``:

- ``gram(name, theta, x)``: K = amplitude * f(d2) + noise * I
  (StationaryKernel.apply one-matrix form, :61-70).
- ``cross(name, theta, x1, x2)``: amplitude * f(d2), no noise (:76-87).
- ``log_likelihood(name, theta, x, y)``: GPML Algorithm 2.1 marginal
  likelihood via Cholesky, plus the reference's priors — lognormal on
  amplitude, horseshoe on noise, tophat [0, 2] on each length scale
  (StationaryKernel.logLikelihood :110-152).

Rows may be padding: a ``valid`` mask turns padded rows into unit-diagonal /
zero-coupling entries so one jitted likelihood serves a growing observation
set without recompilation (observations are padded up to a bucket size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

Array = jax.Array

# Priors (StationaryKernel.scala): lognormal amplitude scale, horseshoe
# noise scale, tophat max for length scales.
AMPLITUDE_SCALE = 1.0
NOISE_SCALE = 0.1
LENGTH_SCALE_MAX = 2.0

DEFAULT_NOISE = 1e-4

KERNEL_NAMES = ("matern52", "rbf")


def _from_sq_dists(name: str, d2: Array) -> Array:
    """Covariance from squared scaled distances (fromPairwiseDistances)."""
    if name == "matern52":
        f = jnp.sqrt(5.0 * d2)
        return (1.0 + f + (5.0 / 3.0) * d2) * jnp.exp(-f)
    if name == "rbf":
        return jnp.exp(-0.5 * d2)
    raise ValueError(f"unknown kernel {name!r}")


def split_theta(theta: Array) -> tuple[Array, Array, Array]:
    """theta -> (amplitude, noise, length_scale[d or 1])."""
    return theta[0], theta[1], theta[2:]


def make_theta(amplitude, noise, length_scale) -> jnp.ndarray:
    return jnp.concatenate([
        jnp.asarray([amplitude, noise], dtype=jnp.result_type(float)),
        jnp.atleast_1d(jnp.asarray(length_scale, dtype=jnp.result_type(float))),
    ])


def initial_theta(y: Array, num_length_scales: int) -> jnp.ndarray:
    """Matern52.getInitialKernel: amplitude = stddev(y), defaults elsewhere.

    The reference keeps a single shared length scale; we carry one per
    hyperparameter dimension (ARD), initialized to 1.0.
    """
    amp = jnp.std(y)
    amp = jnp.where(amp > 0, amp, 1.0)
    return make_theta(amp, DEFAULT_NOISE, jnp.ones(num_length_scales))


def _sq_dists(x1: Array, x2: Array) -> Array:
    """Pairwise squared Euclidean distances [n1, n2] (pairwiseDistances)."""
    d2 = (
        jnp.sum(x1 * x1, axis=1)[:, None]
        - 2.0 * x1 @ x2.T
        + jnp.sum(x2 * x2, axis=1)[None, :]
    )
    return jnp.maximum(d2, 0.0)


def _scaled(x: Array, length_scale: Array) -> Array:
    # A length-1 scale broadcasts across all dims (expandDimensions).
    return x / length_scale


@functools.partial(jax.jit, static_argnums=0)
def gram(name: str, theta: Array, x: Array, valid: Array | None = None) -> Array:
    """K(x, x) with noise on the diagonal; padded rows become identity."""
    amplitude, noise, ls = split_theta(theta)
    xs = _scaled(x, ls)
    k = amplitude * _from_sq_dists(name, _sq_dists(xs, xs))
    k = k + noise * jnp.eye(x.shape[0], dtype=x.dtype)
    if valid is not None:
        pair = valid[:, None] * valid[None, :]
        eye = jnp.eye(x.shape[0], dtype=x.dtype)
        k = jnp.where(pair > 0, k, eye)
    return k


@functools.partial(jax.jit, static_argnums=0)
def cross(name: str, theta: Array, x1: Array, x2: Array,
          valid2: Array | None = None) -> Array:
    """K(x1, x2) without noise; padded x2 rows contribute zero coupling."""
    amplitude, _, ls = split_theta(theta)
    k = amplitude * _from_sq_dists(
        name, _sq_dists(_scaled(x1, ls), _scaled(x2, ls))
    )
    if valid2 is not None:
        k = k * valid2[None, :]
    return k


@functools.partial(jax.jit, static_argnums=0)
def log_likelihood(
    name: str, theta: Array, x: Array, y: Array, valid: Array
) -> Array:
    """GP marginal log likelihood + hyperprior terms; -inf out of bounds.

    Reference: StationaryKernel.logLikelihood :110-152 — bounds checks
    (nonneg params, tophat length-scale max), GPML 2.1 line 7 via Cholesky,
    lognormal amplitude prior, horseshoe noise prior; any numerical failure
    (non-PD K) yields -inf.
    """
    amplitude, noise, ls = split_theta(theta)
    n_real = jnp.sum(valid)
    k = gram(name, theta, x, valid)
    chol = jnp.linalg.cholesky(k)
    ym = y * valid
    alpha = jax.scipy.linalg.cho_solve((chol, True), ym)
    # Padded rows have unit diagonal: their log-det contribution is 0 and
    # alpha entries are y*0 = 0.
    lik = (
        -0.5 * jnp.dot(ym, alpha)
        - jnp.sum(jnp.log(jnp.diagonal(chol)) * valid)
        - 0.5 * n_real * jnp.log(2.0 * jnp.pi)
    )
    # Lognormal amplitude prior + horseshoe noise prior.
    lik = lik - 0.5 * jnp.log(jnp.sqrt(amplitude / AMPLITUDE_SCALE)) ** 2
    lik = lik + jnp.where(
        noise > 0,
        jnp.log(jnp.log1p((NOISE_SCALE / noise) ** 2)),
        0.0,
    )
    in_bounds = (
        (amplitude > 0)
        & (noise >= 0)
        & jnp.all(ls > 0)
        & jnp.all(ls <= LENGTH_SCALE_MAX)
    )
    return jnp.where(
        in_bounds & jnp.isfinite(lik), lik, -jnp.inf
    )


def log_likelihood_np(name: str, theta, x, y) -> float:
    """Host-side scalar twin of ``log_likelihood`` for the slice sampler.

    Slice sampling's step-out walk evaluates the likelihood hundreds of
    times sequentially at tiny n; per-call device dispatch would dominate
    by orders of magnitude (the reference's Breeze calls are in-process for
    the same reason). Same math, numpy; tested equal to the jnp version.
    """
    import numpy as np

    theta = np.asarray(theta, dtype=float)
    amplitude, noise, ls = theta[0], theta[1], theta[2:]
    if (
        amplitude <= 0
        or noise < 0
        or (ls <= 0).any()
        or (ls > LENGTH_SCALE_MAX).any()
    ):
        return -np.inf
    xs = np.asarray(x, dtype=float) / ls
    d2 = (
        (xs * xs).sum(1)[:, None]
        - 2.0 * xs @ xs.T
        + (xs * xs).sum(1)[None, :]
    )
    d2 = np.maximum(d2, 0.0)
    if name == "matern52":
        f = np.sqrt(5.0 * d2)
        k = (1.0 + f + (5.0 / 3.0) * d2) * np.exp(-f)
    elif name == "rbf":
        k = np.exp(-0.5 * d2)
    else:
        raise ValueError(f"unknown kernel {name!r}")
    k = amplitude * k + noise * np.eye(xs.shape[0])
    try:
        chol = np.linalg.cholesky(k)
    except np.linalg.LinAlgError:
        return -np.inf
    y = np.asarray(y, dtype=float)
    alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
    lik = (
        -0.5 * float(y @ alpha)
        - float(np.log(np.diagonal(chol)).sum())
        - 0.5 * xs.shape[0] * np.log(2.0 * np.pi)
    )
    lik -= 0.5 * np.log(np.sqrt(amplitude / AMPLITUDE_SCALE)) ** 2
    if noise > 0:
        lik += np.log(np.log1p((NOISE_SCALE / noise) ** 2))
    return lik if np.isfinite(lik) else -np.inf
