"""Hyperparameter tuning: Sobol random search + GP Bayesian search.

TPU-native counterpart of photon-lib hyperparameter/* (search, estimators,
kernels, criteria, slice sampler, rescaling — SURVEY §1 layer 9) and the
photon-api tuner dispatch. See the individual modules for file:line parity
citations.
"""

from photon_tpu.hyperparameter.criteria import (
    ConfidenceBound,
    ExpectedImprovement,
)
from photon_tpu.hyperparameter.evaluation import (
    DEFAULT_REG_ALPHA_RANGE,
    DEFAULT_REG_WEIGHT_RANGE,
    GameEstimatorEvaluationFunction,
)
from photon_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_tpu.hyperparameter.rescaling import (
    DoubleRange,
    scale_backward,
    scale_forward,
    transform_backward,
    transform_forward,
)
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
)
from photon_tpu.hyperparameter.serialization import (
    HyperparameterConfig,
    config_from_json,
    prior_from_json,
    rescale_prior_observations,
)
from photon_tpu.hyperparameter.shrink import get_bounds
from photon_tpu.hyperparameter.slice_sampler import SliceSampler
from photon_tpu.hyperparameter.tuner import HyperparameterTuningMode, search

__all__ = [
    "ConfidenceBound",
    "ExpectedImprovement",
    "DEFAULT_REG_ALPHA_RANGE",
    "DEFAULT_REG_WEIGHT_RANGE",
    "GameEstimatorEvaluationFunction",
    "GaussianProcessEstimator",
    "GaussianProcessModel",
    "DoubleRange",
    "scale_backward",
    "scale_forward",
    "transform_backward",
    "transform_forward",
    "GaussianProcessSearch",
    "RandomSearch",
    "HyperparameterConfig",
    "config_from_json",
    "prior_from_json",
    "rescale_prior_observations",
    "get_bounds",
    "SliceSampler",
    "HyperparameterTuningMode",
    "search",
]
