"""Hyperparameter config / prior-observation JSON (de)serialization.

TPU-native counterpart of photon-lib
hyperparameter/HyperparameterSerialization.scala:136 and
HyperparameterConfig.scala: the JSON vocabulary that names tunable
hyperparameters, their ranges, discretization, and LOG/SQRT transforms, plus
prior observations from past datasets (the ``records`` list consumed by
``findWithPriors``).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from photon_tpu.hyperparameter.rescaling import (
    DoubleRange,
    rescale_priors,
)
from photon_tpu.hyperparameter.tuner import HyperparameterTuningMode


@dataclasses.dataclass(frozen=True)
class HyperparameterConfig:
    """Reference: HyperparameterConfig.scala — tuning mode + per-parameter
    names / ranges / discrete cardinalities / transforms."""

    tuning_mode: HyperparameterTuningMode
    names: list[str]
    ranges: list[DoubleRange]
    discrete_params: dict[int, int]
    transform_map: dict[int, str]


def config_from_json(json_config: str) -> HyperparameterConfig:
    """Parse the tuner config document (configFromJson :58-120).

    Expected shape::

        {"tuning_mode": "BAYESIAN",
         "variables": {"global.regularizer": {
             "type": "CONTINUOUS", "min": -4, "max": 4,
             "transform": "LOG"}}}

    DISCRETE variables widen their range by 1 on the unit-cube side (the
    reference's discreteParam handling in VectorRescaling).
    """
    raw = json.loads(json_config)
    mode_name = str(raw.get("tuning_mode", "NONE")).upper()
    try:
        mode = HyperparameterTuningMode(mode_name)
    except ValueError:
        raise ValueError(
            f"unknown tuning_mode {mode_name!r}; expected one of "
            f"{[m.value for m in HyperparameterTuningMode]}") from None
    variables = raw["variables"]
    names = sorted(variables)
    ranges: list[DoubleRange] = []
    discrete: dict[int, int] = {}
    transforms: dict[int, str] = {}
    for i, name in enumerate(names):
        spec = variables[name]
        lo, hi = float(spec["min"]), float(spec["max"])
        ranges.append(DoubleRange(lo, hi))
        if str(spec.get("type", "CONTINUOUS")).upper() == "DISCRETE":
            discrete[i] = int(hi - lo) + 1
        if spec.get("transform") is not None:
            transforms[i] = str(spec["transform"]).upper()
    return HyperparameterConfig(
        tuning_mode=mode,
        names=names,
        ranges=ranges,
        discrete_params=discrete,
        transform_map=transforms,
    )


def prior_from_json(
    prior_json: str,
    prior_default: dict[str, str],
    hyperparameter_list: list[str],
) -> list[tuple[np.ndarray, float]]:
    """Parse prior observations (priorFromJson :33-56): a ``records`` list of
    string maps, each carrying ``evaluationValue`` plus per-parameter values
    (absent parameters fall back to ``prior_default``)."""
    raw = json.loads(prior_json)
    out: list[tuple[np.ndarray, float]] = []
    for rec in raw["records"]:
        value = float(rec["evaluationValue"])
        vec = np.array([
            float(rec[name] if name in rec else prior_default[name])
            for name in hyperparameter_list
        ])
        out.append((vec, value))
    return out


def rescale_prior_observations(
    priors: list[tuple[np.ndarray, float]],
    config: HyperparameterConfig,
) -> list[tuple[np.ndarray, float]]:
    """Transform + scale prior observations into the unit cube
    (VectorRescaling.rescalePriors with the config's transform map)."""
    return rescale_priors(
        priors, config.ranges, config.transform_map,
        set(config.discrete_params),
    )
