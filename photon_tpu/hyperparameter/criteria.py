"""Acquisition criteria (prediction transformations) for Bayesian search.

TPU-native counterpart of photon-lib hyperparameter/criteria/
ExpectedImprovement.scala:58 and ConfidenceBound.scala:48, plus the
PredictionTransformation contract (estimators/PredictionTransformation.scala).
Each criterion is a callable (means, variances) -> scores, pure jnp so it can
run inside the vmapped posterior-sample average.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

Array = jax.Array

_INV_SQRT_2 = 0.7071067811865476
_INV_SQRT_2PI = 0.3989422804014327


def _norm_cdf(z: Array) -> Array:
    return 0.5 * (1.0 + jax.lax.erf(z * _INV_SQRT_2))


def _norm_pdf(z: Array) -> Array:
    return _INV_SQRT_2PI * jnp.exp(-0.5 * z * z)


@dataclasses.dataclass(frozen=True)
class ExpectedImprovement:
    """EI against the best (lowest) observed evaluation; maximized.

    Reference: ExpectedImprovement.scala:58 — gamma = -(mean - best)/std,
    EI = std * (gamma * Phi(gamma) + phi(gamma)) (PBO eqs. 1-2). The search
    minimizes the evaluation value, so EI is maximized.
    """

    best_evaluation: float
    is_max_opt: bool = True

    def __call__(self, means: Array, variances: Array) -> Array:
        std = jnp.sqrt(variances)
        gamma = -(means - self.best_evaluation) / std
        return std * (gamma * _norm_cdf(gamma) + _norm_pdf(gamma))


@dataclasses.dataclass(frozen=True)
class ConfidenceBound:
    """Lower confidence bound mean - k*std; minimized.

    Reference: ConfidenceBound.scala:48 (explorationFactor default 2.0,
    PBO eq. 3)."""

    exploration_factor: float = 2.0
    is_max_opt: bool = False

    def __call__(self, means: Array, variances: Array) -> Array:
        return means - self.exploration_factor * jnp.sqrt(variances)
