"""Hyperparameter tuner entry point.

TPU-native counterpart of photon-api hyperparameter/tuner/ — the
HyperparameterTuner contract (HyperparameterTuner.scala), the
NONE/RANDOM/BAYESIAN mode switch, and the AtlasTuner dispatch
(AtlasTuner.scala:27). The reference resolves tuner classes reflectively
(HyperparameterTunerFactory.scala:19); here it's a plain function.
"""

from __future__ import annotations

import enum

from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
)


class HyperparameterTuningMode(enum.Enum):
    """HyperparameterTuningMode in the reference CLI."""

    NONE = "NONE"
    RANDOM = "RANDOM"
    BAYESIAN = "BAYESIAN"


def search(
    n: int,
    dimension: int,
    mode: HyperparameterTuningMode | str,
    evaluation_function,
    observations,
    prior_observations=(),
    discrete_params: dict[int, int] | None = None,
    seed: int = 0,
) -> list:
    """Run n tuning iterations; returns the evaluated results.

    Reference: AtlasTuner.search :27-45 — BAYESIAN builds a
    GaussianProcessSearch, RANDOM a RandomSearch, both seeded with the
    already-evaluated observations (the lambda-grid models).
    """
    mode = HyperparameterTuningMode(
        mode.upper() if isinstance(mode, str) else mode
    )
    if mode == HyperparameterTuningMode.NONE or n <= 0:
        return []
    if mode == HyperparameterTuningMode.BAYESIAN:
        searcher = GaussianProcessSearch(
            dimension, evaluation_function,
            discrete_params=discrete_params, seed=seed,
        )
    else:
        searcher = RandomSearch(
            dimension, evaluation_function,
            discrete_params=discrete_params, seed=seed,
        )
    return searcher.find_with_priors(
        n, list(observations), list(prior_observations)
    )
