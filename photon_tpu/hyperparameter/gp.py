"""Gaussian-process regression for Bayesian hyperparameter search.

TPU-native counterpart of photon-lib
hyperparameter/estimators/GaussianProcessEstimator.scala:36 (slice-sampled
kernel hyperparameters, burn-in + posterior samples) and
GaussianProcessModel.scala:118 (GPML Algorithm 2.1 predictions via Cholesky).

Design notes vs the reference:
- The reference keeps a list of Kernel objects (one per posterior sample) and
  loops; here the posterior samples live in one ``[S, p]`` theta matrix and
  the Cholesky factorizations / predictions are ``vmap``-ped over S.
- Observations are padded to a bucket size with a validity mask so the jitted
  likelihood and predict functions serve a growing observation set without
  recompiling every iteration (the search adds one point per step).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.hyperparameter import kernels
from photon_tpu.hyperparameter.slice_sampler import SliceSampler

Array = jax.Array


@functools.cache
def _gp_device():
    """The GP runs on the host CPU backend when one is registered.

    Slice sampling makes hundreds of sequential tiny (n <= ~100) Cholesky
    calls; on an accelerator behind a network tunnel each call pays a
    round trip that dwarfs the compute. The main training path is unaffected
    — only the tuner's GP is pinned here.
    """
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _put(x):
    dev = _gp_device()
    arr = jnp.asarray(x)
    return arr if dev is None else jax.device_put(arr, dev)


def _pad_to_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _Precomputed:
    chols: Array  # [S, n, n]
    alphas: Array  # [S, n]


@dataclasses.dataclass(frozen=True)
class GaussianProcessModel:
    """Posterior GP over the evaluation function (GaussianProcessModel.scala).

    ``thetas`` holds one kernel-hyperparameter sample per row; predictions
    average over samples (the reference's mean over its kernels list).
    """

    kernel_name: str
    x_train: Array  # [n_pad, d]
    y_train: Array  # [n_pad] (already mean-shifted by y_mean)
    y_mean: float
    valid: Array  # [n_pad]
    thetas: Array  # [S, p]
    _pre: _Precomputed

    @property
    def feature_dimension(self) -> int:
        return int(self.x_train.shape[1])

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(means, variances) at query points, averaged over theta samples
        (GaussianProcessModel.predict :58-66)."""
        xq = _put(x)
        means, variances = _predict_all(
            self.kernel_name, self.thetas, self._pre.chols, self._pre.alphas,
            self.x_train, self.valid, xq,
        )
        return (
            np.asarray(jnp.mean(means, axis=0) + self.y_mean),
            np.asarray(jnp.mean(variances, axis=0)),
        )

    def predict_transformed(self, x: np.ndarray, transformation) -> np.ndarray:
        """Mean over samples of transformation(mean_s, var_s)
        (predictTransformed :72-84); the transformation sees *shifted* means,
        matching the reference (yPred + yMean happens per kernel there; the
        EI criterion receives the same shifted values either way because the
        best-eval it compares against is shifted identically)."""
        xq = _put(x)
        means, variances = _predict_all(
            self.kernel_name, self.thetas, self._pre.chols, self._pre.alphas,
            self.x_train, self.valid, xq,
        )
        vals = jax.vmap(transformation)(means + self.y_mean, variances)
        return np.asarray(jnp.mean(vals, axis=0))


def _predict_one(name, theta, chol, alpha, x_train, valid, xq):
    """GPML Alg. 2.1 lines 4-6 for one theta sample
    (GaussianProcessModel.predictWithKernel :92-110)."""
    ktrans = kernels.cross(name, theta, x_train, xq, None)  # [n, m]
    ktrans = ktrans * valid[:, None]
    y_pred = ktrans.T @ alpha
    v = jax.scipy.linalg.solve_triangular(chol, ktrans, lower=True)
    amplitude, noise, _ = kernels.split_theta(theta)
    kx_diag = amplitude + noise  # one-matrix apply: f(0)=1 plus noise
    y_var = jnp.maximum(kx_diag - jnp.sum(v * v, axis=0), 1e-12)
    return y_pred, y_var


def _make_precompute(name: str):
    @jax.jit
    def pre(thetas, x, y, valid):
        def one(theta):
            k = kernels.gram(name, theta, x, valid)
            chol = jnp.linalg.cholesky(k)
            alpha = jax.scipy.linalg.cho_solve((chol, True), y * valid)
            return chol, alpha

        chols, alphas = jax.vmap(one)(thetas)
        return _Precomputed(chols=chols, alphas=alphas)

    return pre


_PRECOMPUTE = {n: _make_precompute(n) for n in kernels.KERNEL_NAMES}


def _make_predict(name: str):
    @jax.jit
    def predict(thetas, chols, alphas, x_train, valid, xq):
        return jax.vmap(
            lambda t, c, a: _predict_one(name, t, c, a, x_train, valid, xq)
        )(thetas, chols, alphas)

    return predict


_PREDICT = {n: _make_predict(n) for n in kernels.KERNEL_NAMES}


def _predict_all(name, thetas, chols, alphas, x_train, valid, xq):
    return _PREDICT[name](thetas, chols, alphas, x_train, valid, xq)


class GaussianProcessEstimator:
    """Slice-sample kernel hyperparameters, return a posterior-averaged model.

    Reference: GaussianProcessEstimator.scala:36 — burn-in
    (monteCarloNumBurnInSamples=100) then monteCarloNumSamples=10 posterior
    draws; amplitude/noise sampled jointly (or amplitude alone with fixed
    noise when ``noisy_target`` is False), length scales dimension-wise
    (sampleNext :94-137).
    """

    def __init__(
        self,
        kernel: str = "matern52",
        normalize_labels: bool = False,
        noisy_target: bool = False,
        num_burn_in_samples: int = 100,
        num_samples: int = 10,
        seed: int = 0,
    ):
        if kernel not in kernels.KERNEL_NAMES:
            raise ValueError(f"unknown kernel {kernel!r}")
        self.kernel = kernel
        self.normalize_labels = normalize_labels
        self.noisy_target = noisy_target
        self.num_burn_in_samples = num_burn_in_samples
        self.num_samples = num_samples
        self.seed = seed

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.asarray(x, dtype=float)
        y = np.asarray(y, dtype=float)
        if x.ndim != 2 or x.shape[0] == 0:
            raise ValueError("empty input")
        if x.shape[0] != y.shape[0]:
            raise ValueError("x and y must have the same number of rows")
        y_mean = float(np.mean(y)) if self.normalize_labels else 0.0
        y = y - y_mean

        n, d = x.shape
        n_pad = _pad_to_bucket(n)
        x_pad = np.zeros((n_pad, d))
        x_pad[:n] = x
        y_pad = np.zeros(n_pad)
        y_pad[:n] = y
        valid = np.zeros(n_pad)
        valid[:n] = 1.0

        xj = _put(x_pad)
        yj = _put(y_pad)
        vj = _put(valid)

        # The sampler's logp runs host-side: step-out makes O(100) tiny
        # sequential likelihood calls per draw (see log_likelihood_np).
        def logp(theta_np: np.ndarray) -> float:
            return kernels.log_likelihood_np(self.kernel, theta_np, x, y)

        theta = np.asarray(kernels.initial_theta(jnp.asarray(y), d))
        sampler = SliceSampler(rng=np.random.default_rng(self.seed))
        for _ in range(self.num_burn_in_samples):
            theta = self._sample_next(theta, logp, sampler)
        samples = []
        for _ in range(self.num_samples):
            theta = self._sample_next(theta, logp, sampler)
            samples.append(theta.copy())

        thetas = _put(np.stack(samples))
        pre = _PRECOMPUTE[self.kernel](thetas, xj, yj, vj)
        return GaussianProcessModel(
            kernel_name=self.kernel,
            x_train=xj,
            y_train=yj,
            y_mean=y_mean,
            valid=vj,
            thetas=thetas,
            _pre=pre,
        )

    def _sample_next(self, theta, logp, sampler) -> np.ndarray:
        """One sweep: amplitude(+noise), then length scales
        (GaussianProcessEstimator.sampleNext :94-137)."""
        amp_noise = theta[:2]
        ls = theta[2:]

        if self.noisy_target:
            amp_noise = sampler.draw(
                amp_noise,
                lambda an: logp(np.concatenate([an, ls])),
            )
        else:
            amp = sampler.draw(
                amp_noise[:1],
                lambda a: logp(np.concatenate(
                    [a, [kernels.DEFAULT_NOISE], ls])),
            )
            amp_noise = np.concatenate([amp, [kernels.DEFAULT_NOISE]])

        ls = sampler.draw_dimension_wise(
            ls,
            lambda l: logp(np.concatenate([amp_noise, l])),
        )
        return np.concatenate([amp_noise, ls])
