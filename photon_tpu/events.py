"""Event system: typed training events + a listener registry.

TPU-native counterpart of the reference's event bus (photon-client
event/EventEmitter.scala:24 — a trait holding a listener list with
``sendEvent`` fan-out — and the ``Event`` case classes in
event/Event.scala:65). Upstream only the legacy driver wires it; here the
GAME path emits directly from ``CoordinateDescent`` and ``GameEstimator``,
so callers can observe training progress (per-coordinate diagnostics,
per-config results) without polling or log scraping.

Listeners are plain callables ``listener(event) -> None``; exceptions
propagate (a listener that raises aborts training, matching the reference's
synchronous ``foreach`` fan-out).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class PhotonEvent:
    """Base event type (event/Event.scala:65)."""


@dataclasses.dataclass(frozen=True)
class CoordinateUpdateEvent(PhotonEvent):
    """One coordinate update finished (the per-iteration log record of
    CoordinateDescent.descend, CoordinateDescent.scala:322-333).

    Wraps the history record so the event surface cannot drift from it.
    """

    record: Any  # CoordinateUpdateRecord

    @property
    def iteration(self) -> int:
        return self.record.iteration

    @property
    def coordinate_id(self) -> str:
        return self.record.coordinate_id

    @property
    def seconds(self) -> float | None:
        # None on the fused whole-fit path (one device program: no
        # per-coordinate dispatch time exists; see CoordinateUpdateRecord).
        return self.record.seconds

    @property
    def diagnostics(self):
        return self.record.diagnostics

    @property
    def evaluation(self):
        return self.record.evaluation


@dataclasses.dataclass(frozen=True)
class FitEndEvent(PhotonEvent):
    """One optimization configuration's coordinate-descent run finished
    (the per-config result of GameEstimator.fit :458)."""

    config_index: int
    result: Any  # GameFitResult


Listener = Callable[[PhotonEvent], None]


class EventEmitter:
    """Listener registry with synchronous fan-out (EventEmitter.scala:24)."""

    def __init__(self, listeners=None):
        self._listeners: list[Listener] = list(listeners or ())

    def add_listener(self, listener: Listener) -> None:
        self._listeners.append(listener)

    def remove_listener(self, listener: Listener) -> None:
        self._listeners.remove(listener)

    def clear_listeners(self) -> None:
        self._listeners.clear()

    def send_event(self, event: PhotonEvent) -> None:
        for listener in self._listeners:
            listener(event)
