"""Event system: typed training events + a listener registry.

TPU-native counterpart of the reference's event bus (photon-client
event/EventEmitter.scala:24 — a trait holding a listener list with
``sendEvent`` fan-out — and the ``Event`` case classes in
event/Event.scala:65). Upstream only the legacy driver wires it; here the
GAME path emits directly from ``CoordinateDescent`` and ``GameEstimator``,
so callers can observe training progress (per-coordinate diagnostics,
per-config results) without polling or log scraping.

Listeners are plain callables ``listener(event) -> None``. By default
exceptions propagate (a listener that raises aborts training, matching the
reference's synchronous ``foreach`` fan-out); construct the emitter with
``safe_listeners=True`` — or pass ``isolate=True`` to a single
``send_event`` call — to log-and-continue instead, so one broken observer
(a telemetry sink, a progress bar) cannot abort a multi-hour fit.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable

logger = logging.getLogger(__name__)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). Telemetry sinks register/unregister listeners from
# whatever thread owns them while the training thread fans out events,
# and a listener may mutate the registry from INSIDE the fan-out (a
# one-shot progress listener removing itself). The listener list is
# lock-guarded and `send_event` iterates a SNAPSHOT taken under the
# lock: every listener registered when the emit began receives the
# event exactly once, regardless of concurrent (or reentrant) mutation,
# and the listener calls themselves run outside the lock so a reentrant
# add/remove cannot deadlock.
CONCURRENCY_AUDIT = dict(
    name="event-bus",
    locks={"EventEmitter._lock": ("EventEmitter._listeners",)},
    thread_entries=(),
    jax_dispatch_ok={},
)


@dataclasses.dataclass(frozen=True)
class PhotonEvent:
    """Base event type (event/Event.scala:65)."""


@dataclasses.dataclass(frozen=True)
class CoordinateUpdateEvent(PhotonEvent):
    """One coordinate update finished (the per-iteration log record of
    CoordinateDescent.descend, CoordinateDescent.scala:322-333).

    Wraps the history record so the event surface cannot drift from it.
    """

    record: Any  # CoordinateUpdateRecord

    @property
    def iteration(self) -> int:
        return self.record.iteration

    @property
    def coordinate_id(self) -> str:
        return self.record.coordinate_id

    @property
    def seconds(self) -> float | None:
        # None on the fused whole-fit path with telemetry off (one device
        # program: no per-coordinate dispatch time exists); an attributed
        # share of the fit's measured wall with telemetry on. See the
        # CoordinateUpdateRecord contract.
        return self.record.seconds

    @property
    def diagnostics(self):
        return self.record.diagnostics

    @property
    def evaluation(self):
        return self.record.evaluation


@dataclasses.dataclass(frozen=True)
class CoordinateRollbackEvent(PhotonEvent):
    """A coordinate update produced non-finite loss/weights and was
    ROLLED BACK to the previous iterate (the CD loop's non-finite
    guard, resilience layer): the model the run carries forward is the
    pre-update one, and the wrapped record's ``rolled_back`` flag is
    set. The poisoned update's diagnostics ride along for debugging."""

    record: Any  # CoordinateUpdateRecord (rolled_back=True)

    @property
    def iteration(self) -> int:
        return self.record.iteration

    @property
    def coordinate_id(self) -> str:
        return self.record.coordinate_id


@dataclasses.dataclass(frozen=True)
class FitEndEvent(PhotonEvent):
    """One optimization configuration's coordinate-descent run finished
    (the per-config result of GameEstimator.fit :458)."""

    config_index: int
    result: Any  # GameFitResult


Listener = Callable[[PhotonEvent], None]


class EventEmitter:
    """Listener registry with synchronous fan-out (EventEmitter.scala:24).

    ``safe_listeners`` selects the default fault-isolation mode:
    ``False`` (the reference's semantics) lets a raising listener abort
    the caller; ``True`` logs the exception and continues with the
    remaining listeners. ``send_event(..., isolate=...)`` overrides the
    default per call. Fan-out stays synchronous in both modes — events
    arrive on the training thread, in order.
    """

    def __init__(self, listeners=None, *, safe_listeners: bool = False):
        self._lock = threading.Lock()
        self._listeners: list[Listener] = list(listeners or ())
        self.safe_listeners = safe_listeners

    def add_listener(self, listener: Listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener: Listener) -> None:
        with self._lock:
            self._listeners.remove(listener)

    def clear_listeners(self) -> None:
        with self._lock:
            self._listeners.clear()

    def send_event(
        self, event: PhotonEvent, *, isolate: bool | None = None
    ) -> None:
        if isolate is None:
            isolate = self.safe_listeners
        # Snapshot under the lock; fan out OUTSIDE it. A listener that
        # mutates the registry mid-emit (removing itself, adding a
        # sibling) must neither skip the next listener (the classic
        # mutate-during-iteration bug) nor deadlock on a reentrant
        # add/remove. Listeners added during the fan-out see the NEXT
        # event; listeners present at emit start all see this one.
        with self._lock:
            listeners = tuple(self._listeners)
        if not isolate:
            for listener in listeners:
                listener(event)
            return
        for listener in listeners:
            try:
                listener(event)
            except Exception:  # noqa: BLE001 — isolation is the contract
                logger.exception(
                    "event listener %r raised on %r; continuing "
                    "(isolated fan-out)", listener, type(event).__name__,
                )
