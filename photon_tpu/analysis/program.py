"""Tier-2 semantic auditor: jaxpr/HLO program contracts for photon_tpu.

Where the tier-1 rules (``rules.py``) read SOURCE TEXT, this tier audits
the PROGRAMS the package actually builds: the public jitted entry points
are traced under abstract shapes (``jax.jit(...).trace`` / ``.lower()`` —
no device execution, so the whole pass runs on CPU CI) and the resulting
jaxprs / lowered HLO are checked against contracts DECLARED NEXT TO THE
CODE they constrain (each audited module carries a ``PROGRAM_AUDIT``
declaration; this module owns the tracing machinery).

Checks (rule ids):

- ``program-dispatch-census``: the number of distinct traced programs
  across a contract's declared config grid must stay within the declared
  bound — a config family that should re-enter one executable (the λ-grid
  warm-start ladder) must not mint new programs.
- ``program-recompile-key``: per config family, the trace signature either
  MUST be stable (``stable_under``) or MUST change (``recompiles_on`` —
  a declared static specialization that stops specializing means the
  declaration went stale). The report names which argument perturbs the
  key.
- ``program-host-boundary``: no callback primitives inside hot-loop
  jaxprs — a ``pure_callback``/``io_callback``/``debug_callback`` in a
  fit program is a host round trip per dispatch, the jaxpr-level twin of
  tier-1's ``host-sync-in-jit``.
- ``program-f64-cast``: no ``convert_element_type`` TO float64 anywhere
  in an audited jaxpr (tier-1's ``float64-literal``, after tracing).
- ``program-sharding``: mesh entry points must carry the declared
  ``NamedSharding`` axis on every hot-loop operand, replicate exactly the
  operands declared replicated, and lower to HLO whose collectives are a
  subset of the declared set (an unplanned all-gather is a silent
  cross-device transfer per dispatch).
- ``program-contract``: registry integrity — a contract whose builder
  raises is a finding, never a silent skip.

Findings reuse :class:`photon_tpu.analysis.core.Finding` (path is the
contract name) so the text/JSON reporters and the suppression audit work
unchanged. Suppressions are PER CONTRACT, declared in the contract's
``suppress`` mapping with a written reason.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import importlib
import os
import re
import sys
from typing import Any, Callable, Iterable, Iterator

from photon_tpu.analysis.core import Finding

SEMANTIC_RULES: dict[str, str] = {
    "program-dispatch-census": (
        "distinct compiled programs across a config grid exceed the "
        "contract's bound"
    ),
    "program-recompile-key": (
        "a config family perturbs (or stops perturbing) a compile-cache "
        "key against its declaration"
    ),
    "program-host-boundary": (
        "callback primitive inside a hot-loop jaxpr (host round trip "
        "per dispatch)"
    ),
    "program-f64-cast": (
        "convert_element_type to float64 inside an audited jaxpr"
    ),
    "program-sharding": (
        "mesh operand lost its NamedSharding axis, or lowered HLO "
        "carries undeclared collectives"
    ),
    "program-contract": "contract declaration or builder integrity error",
}

# Modules that declare program contracts (each exports PROGRAM_AUDIT —
# one declaration dict or a list of them). The declarations are plain
# data so importing the audited modules stays free of analysis imports.
DECLARING_MODULES = (
    "photon_tpu.algorithm.fused_fit",
    "photon_tpu.data.pipeline",
    "photon_tpu.data.stream",
    "photon_tpu.estimators.game_estimator",
    "photon_tpu.obs",
    "photon_tpu.ops.newton_kernel",
    "photon_tpu.ops.segment_reduce",
    "photon_tpu.ops.serve_kernel",
    "photon_tpu.parallel.mesh",
    "photon_tpu.pilot",
    "photon_tpu.resilience",
    "photon_tpu.serve",
)

_CALLBACK_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "outside_call",
        "host_callback_call",
    }
)

# Cross-device transfer ops: owned since PR 20 by the tier-6 SPMD
# census (analysis/spmd.py) — one list, one census, so the tier-2
# sharding audit and the --spmd collective-order audit cannot drift.
from photon_tpu.analysis.spmd import COLLECTIVE_OPS as _COLLECTIVE_OPS


# --------------------------------------------------------------------------
# data model
# --------------------------------------------------------------------------


# Function reprs inside higher-order primitive params (custom_jvp's
# jvp_jaxpr_thunk and friends) embed id() addresses in the jaxpr text.
# They vary per trace — across simulated hosts and across re-traces of
# one config — without any semantic divergence, so both the tier-2
# recompile-key proxy and the tier-6 cross-host proof scrub them.
_JAXPR_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


@dataclasses.dataclass
class TracedProgram:
    """One traced entry point: its jaxpr (for the boundary walk), the
    jaxpr text hash (the recompile-key proxy: two configs tracing to
    different jaxprs get different compiled programs), and optionally the
    Lowered for HLO/cost checks."""

    name: str
    text: str
    jaxpr: Any | None = None  # ClosedJaxpr; None for key-only programs
    lowered: Any | None = None

    def __post_init__(self) -> None:
        self.text = _JAXPR_ADDR_RE.sub(" at 0x", self.text)

    @property
    def signature(self) -> str:
        return hashlib.sha1(self.text.encode("utf-8")).hexdigest()[:16]


@dataclasses.dataclass
class ContractTrace:
    """Everything a contract's builder hands the checks.

    ``variants`` maps a config-family name to one signature-dict per
    generated config (program name -> signature); ``opshardings`` /
    ``replicated`` / ``collectives`` feed the sharding audit (None when
    the builder ran single-device); ``notes`` surface in the report.
    """

    programs: dict[str, TracedProgram]
    variants: dict[str, list[dict[str, str]]] = dataclasses.field(
        default_factory=dict
    )
    opshardings: dict[str, str] | None = None
    collectives: list[str] | None = None
    notes: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    name: str
    entry: str  # human-readable entry-point path (report/docs)
    build: Callable[[], ContractTrace]
    max_programs: int | None = None
    stable_under: tuple[str, ...] = ()
    recompiles_on: tuple[str, ...] = ()
    hot_loop: bool = False
    sharded_operands: tuple[str, ...] = ()
    replicated_operands: tuple[str, ...] = ()
    axis: str | None = None
    allowed_collectives: tuple[str, ...] = ()
    suppress: dict[str, str] = dataclasses.field(default_factory=dict)


def _finding(contract: ProgramContract, rule: str, message: str) -> Finding:
    return Finding(
        rule=rule, path=f"<{contract.name}>", line=0, col=0, message=message
    )


# --------------------------------------------------------------------------
# jaxpr utilities
# --------------------------------------------------------------------------


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every equation of a (Closed)Jaxpr, recursing into sub-jaxprs held
    in eqn params (scan/while/cond bodies, pjit calls, custom calls)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for sub in _param_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _param_jaxprs(params: dict) -> Iterator[Any]:
    for v in params.values():
        for cand in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                if hasattr(getattr(cand, "jaxpr", cand), "eqns"):
                    yield cand


def trace_program(name: str, fn: Any, *args: Any, **kwargs: Any) -> TracedProgram:
    """Trace ``jax.jit(fn)`` (or an already-jitted fn) abstractly.

    ``args`` may mix concrete arrays and ``jax.ShapeDtypeStruct`` leaves;
    nothing executes. The Lowered is captured for cost/HLO analysis.
    """
    import jax

    jitted = fn if hasattr(fn, "trace") else jax.jit(fn)
    traced = jitted.trace(*args, **kwargs)
    return TracedProgram(
        name=name,
        text=str(traced.jaxpr),
        jaxpr=traced.jaxpr,
        lowered=traced.lower(),
    )


# --------------------------------------------------------------------------
# checks
# --------------------------------------------------------------------------


def check_dispatch_census(
    contract: ProgramContract, trace: ContractTrace
) -> Iterator[Finding]:
    if contract.max_programs is None:
        return
    sigs: dict[str, str] = {
        p.signature: f"{name} (base)" for name, p in trace.programs.items()
    }
    for fam in contract.stable_under:
        for i, cfg in enumerate(trace.variants.get(fam, [])):
            for name, sig in cfg.items():
                sigs.setdefault(sig, f"{name} ({fam}[{i}])")
    if len(sigs) > contract.max_programs:
        yield _finding(
            contract,
            "program-dispatch-census",
            f"{len(sigs)} distinct compiled programs across the declared "
            f"config grid, contract allows {contract.max_programs}: "
            + ", ".join(sorted(sigs.values())),
        )


def check_recompile_key(
    contract: ProgramContract, trace: ContractTrace
) -> Iterator[Finding]:
    base = {name: p.signature for name, p in trace.programs.items()}
    for fam in contract.stable_under:
        if not trace.variants.get(fam):
            # Same integrity rule as recompiles_on below: a declared
            # family with no generated variants is an UNCHECKED
            # stability guarantee, not a passing one.
            yield _finding(
                contract,
                "program-contract",
                f"declared stable family '{fam}' generated no "
                "variants — the stability guarantee is unchecked",
            )
            continue
        for i, cfg in enumerate(trace.variants.get(fam, [])):
            moved = sorted(
                name
                for name, sig in cfg.items()
                if name in base and sig != base[name]
            )
            if moved:
                yield _finding(
                    contract,
                    "program-recompile-key",
                    f"config family '{fam}' (variant {i}) perturbs the "
                    f"compile key of {', '.join(moved)} — these configs "
                    "must re-enter the same executable",
                )
    for fam in contract.recompiles_on:
        variants = trace.variants.get(fam, [])
        if not variants:
            yield _finding(
                contract,
                "program-contract",
                f"declared recompile family '{fam}' generated no "
                "variants — the declaration is unchecked",
            )
            continue
        if all(
            all(sig == base.get(name) for name, sig in cfg.items())
            for cfg in variants
        ):
            yield _finding(
                contract,
                "program-recompile-key",
                f"declared recompile trigger '{fam}' no longer perturbs "
                "any program key — the static specialization it documents "
                "is gone; tighten the contract declaration",
            )


def check_host_boundary(
    contract: ProgramContract, trace: ContractTrace
) -> Iterator[Finding]:
    import numpy as np

    f64 = np.dtype("float64")
    for name, prog in trace.programs.items():
        if prog.jaxpr is None:
            continue
        seen_cb: set[str] = set()
        seen_f64 = False
        for eqn in iter_eqns(prog.jaxpr):
            pname = eqn.primitive.name
            if contract.hot_loop and pname in _CALLBACK_PRIMITIVES:
                if pname not in seen_cb:
                    seen_cb.add(pname)
                    yield _finding(
                        contract,
                        "program-host-boundary",
                        f"program '{name}' carries host-callback "
                        f"primitive '{pname}' in its hot-loop jaxpr — "
                        "one host round trip per dispatch",
                    )
            if not seen_f64 and pname == "convert_element_type":
                new = eqn.params.get("new_dtype")
                if new is not None and np.dtype(new) == f64:
                    seen_f64 = True
                    yield _finding(
                        contract,
                        "program-f64-cast",
                        f"program '{name}' converts to float64 in its "
                        "traced jaxpr (2x HBM + off the TPU fast path)",
                    )


def check_sharding(
    contract: ProgramContract, trace: ContractTrace
) -> Iterator[Finding]:
    if not (contract.sharded_operands or contract.replicated_operands):
        return
    if trace.opshardings is None:
        # Builder ran single-device; the note in the report says so.
        return
    for op in contract.sharded_operands:
        spec = trace.opshardings.get(op)
        if spec is None:
            yield _finding(
                contract,
                "program-sharding",
                f"operand '{op}' missing from the sharding trace",
            )
        elif contract.axis and f"'{contract.axis}'" not in spec:
            yield _finding(
                contract,
                "program-sharding",
                f"operand '{op}' lost the '{contract.axis}' mesh axis "
                f"(sharding is {spec}) — unplanned replication",
            )
    for op in contract.replicated_operands:
        spec = trace.opshardings.get(op)
        if spec is None:
            yield _finding(
                contract,
                "program-sharding",
                f"operand '{op}' missing from the sharding trace",
            )
        elif contract.axis and f"'{contract.axis}'" in spec:
            yield _finding(
                contract,
                "program-sharding",
                f"operand '{op}' is declared replicated but carries the "
                f"'{contract.axis}' axis ({spec})",
            )
    undeclared = sorted(
        set(trace.collectives or ()) - set(contract.allowed_collectives)
    )
    if undeclared:
        yield _finding(
            contract,
            "program-sharding",
            "lowered HLO carries undeclared cross-device transfer op(s): "
            + ", ".join(undeclared)
            + f" (declared: {', '.join(contract.allowed_collectives) or 'none'})",
        )


CHECKS = (
    check_dispatch_census,
    check_recompile_key,
    check_host_boundary,
    check_sharding,
)


def run_checks(
    contract: ProgramContract, trace: ContractTrace
) -> list[Finding]:
    """All checks over one contract's trace, suppressions applied."""
    findings: list[Finding] = []
    for check in CHECKS:
        for f in check(contract, trace):
            reason = contract.suppress.get(f.rule)
            if reason is not None:
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=reason
                )
            findings.append(f)
    return findings


# --------------------------------------------------------------------------
# collective HLO census (shared by the mesh builder and tests)
# --------------------------------------------------------------------------


def hlo_collectives(compiled: Any) -> list[str]:
    """Collective op names present in a compiled executable's HLO text.

    Delegates to the tier-6 census (``spmd.collective_census``) — the
    single source of truth the ``--spmd`` collective-order audit also
    gates on, so the two tiers see the same ops by construction.
    """
    from photon_tpu.analysis import spmd

    return spmd.collective_census(compiled)


# --------------------------------------------------------------------------
# shared tiny workload (abstract-trace fixtures; CPU-cheap)
# --------------------------------------------------------------------------


def _l2_config(weight: float, optimizer=None, variance=None):
    from photon_tpu import optim
    from photon_tpu.algorithm.problems import GLMOptimizationConfiguration

    kw: dict[str, Any] = dict(
        regularization=optim.RegularizationContext(
            optim.RegularizationType.L2
        ),
        regularization_weight=weight,
    )
    if optimizer is not None:
        kw["optimizer"] = optimizer
    if variance is not None:
        kw["variance_computation"] = variance
    return GLMOptimizationConfiguration(**kw)


def _tiny_glmix(num_iterations: int = 2, n: int = 96, e: int = 7):
    """A miniature single-device GLMix estimator + dataset: one dense
    fixed effect and one random effect, logistic task — the smallest
    structure that exercises every fused-fit program family."""
    import numpy as np

    from photon_tpu.data.dataset import DenseFeatures
    from photon_tpu.data.game_data import make_game_dataset
    from photon_tpu.data.random_effect import RandomEffectDataConfiguration
    from photon_tpu.estimators.game_estimator import (
        FixedEffectCoordinateConfiguration,
        GameEstimator,
        RandomEffectCoordinateConfiguration,
    )
    from photon_tpu.types import TaskType

    d, du = 5, 4
    rng = np.random.default_rng(20260803)
    x = rng.normal(size=(n, d)).astype(np.float32)
    x[:, -1] = 1.0
    xu = rng.normal(size=(n, du)).astype(np.float32)
    xu[:, -1] = 1.0
    users = rng.integers(0, e, size=n)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    data = make_game_dataset(
        y,
        {"global": DenseFeatures(x), "userShard": DenseFeatures(xu)},
        id_tags={"userId": users},
    )
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "global": FixedEffectCoordinateConfiguration(
                "global", _l2_config(0.01)
            ),
            "per-user": RandomEffectCoordinateConfiguration(
                RandomEffectDataConfiguration("userId", "userShard"),
                _l2_config(0.5),
            ),
        },
        intercept_indices={"global": d - 1, "userShard": du - 1},
        num_iterations=num_iterations,
        mesh="off",
    )
    return est, data


def _zero_initial_models(coords: dict) -> dict:
    """Warm-start models with the right structure (values never matter —
    tracing sees only avals — but has_init flips the statics)."""
    import jax.numpy as jnp

    from photon_tpu.algorithm.coordinate import FixedEffectCoordinate
    from photon_tpu.models.game import FixedEffectModel, RandomEffectModel
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel

    out = {}
    for cid, coord in coords.items():
        inner = getattr(coord, "inner", coord)
        if isinstance(inner, FixedEffectCoordinate):
            glm = GeneralizedLinearModel(
                Coefficients(
                    means=jnp.zeros(
                        inner.batch.num_features, inner.batch.labels.dtype
                    )
                ),
                inner.problem.task,
            )
            out[cid] = FixedEffectModel(glm, coord.feature_shard_id)
        else:
            ds = inner.dataset
            out[cid] = RandomEffectModel(
                coefficients=jnp.zeros(
                    (ds.num_entities, ds.max_sub_dim), ds.dtype
                ),
                random_effect_type=ds.config.random_effect_type,
                feature_shard_id=ds.config.feature_shard_id,
                task=inner.task,
                proj_all=ds.proj_all,
                entity_keys=ds.entity_keys,
            )
    return out


# --------------------------------------------------------------------------
# contract builders (named by the PROGRAM_AUDIT declarations)
# --------------------------------------------------------------------------


def build_fused_fit() -> ContractTrace:
    """Trace the three programs of one fused-fit generation and the config
    families of the λ-grid / optimizer-swap discipline."""
    from photon_tpu import optim
    from photon_tpu.algorithm.fused_fit import FusedFit

    est, data = _tiny_glmix()
    datasets, _ = est.prepare(data)
    n = data.num_samples

    def fused_for(opt_configs: dict, iters: int = 2,
                  precision: str = "float32"):
        coords = est._build_coordinates(
            datasets, opt_configs, {}, logical_rows=n
        )
        return FusedFit(
            coords, est.update_sequence, iters, set(),
            precision=precision,
        ), coords

    def fit_trace(
        fused: FusedFit, coords: dict, initial_models=None, lower=True
    ):
        # FusedFit.trace is the SAME operand assembly run() uses — the
        # audited jaxpr is the production program by construction.
        traced = fused.trace(coords, initial_models)
        return TracedProgram(
            name="fit",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower() if lower else None,
        )

    fused, coords = fused_for({})
    mat = trace_program(
        "materialize", fused._mat_jit, fused._mat_operands(coords)
    )
    fit_cold = fit_trace(fused, coords)
    warm = _zero_initial_models(coords)
    fit_warm = dataclasses.replace(
        fit_trace(fused, coords, warm), name="fit_warm"
    )

    variants: dict[str, list[dict[str, str]]] = {
        "lambda_grid": [],
        "optimizer_swap": [],
        "iteration_count": [],
    }
    for w in (0.003, 3.0):
        f2, c2 = fused_for(
            {"global": _l2_config(w), "per-user": _l2_config(w)}
        )
        variants["lambda_grid"].append(
            {
                "fit": fit_trace(f2, c2, lower=False).signature,
                "fit_warm": fit_trace(
                    f2, c2, _zero_initial_models(c2), lower=False
                ).signature,
            }
        )
    f3, c3 = fused_for(
        {
            "global": _l2_config(
                0.01, optimizer=optim.OptimizerConfig.tron()
            )
        }
    )
    variants["optimizer_swap"].append(
        {"fit": fit_trace(f3, c3, lower=False).signature}
    )
    f4, c4 = fused_for({}, iters=3)
    variants["iteration_count"].append(
        {"fit": fit_trace(f4, c4, lower=False).signature}
    )
    # Mixed precision is a DECLARED recompile: bf16 slab/score storage
    # changes the traced dtypes (ops/precision.py), so the bfloat16
    # program must differ from the f32 base — and a silent no-op here
    # (the mixed path quietly tracing f32) fails the contract.
    f5, c5 = fused_for({}, precision="bfloat16")
    variants["precision"] = [
        {"fit": fit_trace(f5, c5, lower=False).signature}
    ]

    return ContractTrace(
        programs={
            "materialize": mat,
            "fit": fit_cold,
            "fit_warm": fit_warm,
        },
        variants=variants,
        notes=[
            "a fused fit is 2 dispatches (materialize once per dataset "
            "generation + the whole-fit program); the warm-start entry is "
            "a third distinct executable of the same generation",
        ],
    )


def build_fused_cache_keys() -> ContractTrace:
    """The estimator's static-key discipline, checked on keys alone: a
    λ grid maps to ONE fused-cache entry, an optimizer swap to a second,
    and a realistic mixed grid stays within the LRU bound."""
    from photon_tpu import optim
    from photon_tpu.algorithm.fused_fit import fused_static_key
    from photon_tpu.estimators.game_estimator import _FUSED_CACHE_SIZE

    est, data = _tiny_glmix()
    datasets, _ = est.prepare(data)
    n = data.num_samples

    def key_for(opt_configs: dict, precision: str = "float32") -> str:
        coords = est._build_coordinates(
            datasets, opt_configs, {}, logical_rows=n
        )
        return str(
            fused_static_key(
                coords,
                est.update_sequence,
                est.num_iterations,
                est.locked_coordinates,
                precision,
            )
        )

    base = TracedProgram(name="fused_static_key", text=key_for({}))
    lam = [
        {"fused_static_key": TracedProgram("k", key_for(
            {"global": _l2_config(w), "per-user": _l2_config(w)}
        )).signature}
        for w in (1e-4, 0.01, 1.0, 100.0)
    ]
    swap = [
        {"fused_static_key": TracedProgram("k", key_for(
            {"global": _l2_config(
                0.01, optimizer=optim.OptimizerConfig.tron()
            )}
        )).signature}
    ]
    prec = [
        {"fused_static_key": TracedProgram(
            "k", key_for({}, precision="bfloat16")).signature}
    ]
    mixed = {sig["fused_static_key"] for sig in lam + swap + prec} | {
        base.signature
    }
    notes = [
        f"mixed λ×optimizer grid occupies {len(mixed)} of "
        f"{_FUSED_CACHE_SIZE} fused-cache slots",
    ]
    trace = ContractTrace(
        programs={"fused_static_key": base},
        variants={
            "lambda_grid": lam, "optimizer_swap": swap,
            "precision": prec,
        },
        notes=notes,
    )
    if len(mixed) > _FUSED_CACHE_SIZE:
        trace.notes.append(
            "mixed grid exceeds the fused-cache LRU capacity — "
            "alternating configs will rebuild whole-fit traces"
        )
    return trace


def build_unfused_update() -> ContractTrace:
    """The unfused coordinate update (_run_impl under jit): λ and warm
    starts are traced operands — ONE executable per static config."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from photon_tpu import optim
    from photon_tpu.algorithm.problems import (
        VarianceComputationType,
        _run_jit,
    )
    from photon_tpu.data.dataset import make_dense_batch
    from photon_tpu.ops.normalization import NormalizationContext
    from photon_tpu.types import TaskType

    n, d = 64, 5
    rng = np.random.default_rng(0)
    batch = make_dense_batch(
        rng.normal(size=(n, d)).astype(np.float32),
        (rng.uniform(size=n) < 0.5).astype(np.float32),
    )
    norm = NormalizationContext()

    def tr(l2: float, opt_config=None, w0=None) -> TracedProgram:
        dtype = batch.labels.dtype
        return trace_program(
            "coordinate_update",
            _run_jit,
            batch,
            (jnp.zeros(d, dtype) if w0 is None else w0),
            jnp.asarray(0.0, dtype),
            jnp.asarray(l2, dtype),
            norm,
            None,
            jnp.asarray(1.0, dtype),
            task=TaskType.LOGISTIC_REGRESSION,
            opt_config=opt_config or optim.OptimizerConfig(),
            use_owlqn=False,
            intercept_index=d - 1,
            variance_computation=VarianceComputationType.NONE,
        )

    base = tr(0.01)
    warm = jax.numpy.ones(d, batch.labels.dtype)
    return ContractTrace(
        programs={"coordinate_update": base},
        variants={
            "lambda_grid": [
                {"coordinate_update": tr(w).signature} for w in (1e-3, 10.0)
            ],
            "warm_start": [
                {"coordinate_update": tr(0.01, w0=warm).signature}
            ],
            "optimizer_swap": [
                {
                    "coordinate_update": tr(
                        0.01, opt_config=optim.OptimizerConfig.tron()
                    ).signature
                }
            ],
        },
    )


def build_newton_kernel() -> ContractTrace:
    """The Pallas Newton-step wrapper, traced through the interpreter
    path on non-TPU backends (Mosaic lowering is TPU-only)."""
    import jax

    from photon_tpu.ops.newton_kernel import (
        LANES,
        interpret_required,
        newton_step_lanes,
    )
    from photon_tpu.types import TaskType

    s, r, bp = 4, 6, LANES
    f32 = "float32"

    def sds(*shape):
        return jax.ShapeDtypeStruct(shape, f32)

    def tr(name: str, *, s=s, r=r, trials=16) -> TracedProgram:
        return trace_program(
            name,
            newton_step_lanes,
            sds(s, r, bp), sds(s, bp), sds(r, bp), sds(r, bp), sds(r, bp),
            sds(s, bp), sds(s, bp), sds(s, bp), sds(1, bp),
            r=r, s=s,
            task=TaskType.LOGISTIC_REGRESSION,
            trials=trials,
            interpret=interpret_required(),
        )

    base = tr("newton_step")
    return ContractTrace(
        programs={"newton_step": base},
        variants={
            "bucket_shape": [{"newton_step": tr("n", r=r + 2).signature}],
            "line_search_trials": [
                {"newton_step": tr("n", trials=8).signature}
            ],
        },
    )


def build_segment_reduce() -> ContractTrace:
    """The Pallas segment-reduce wrapper, traced through the interpreter
    path on non-TPU backends (Mosaic lowering is TPU-only). Values, ids
    and the prefetched starts are traced operands; only the static
    reduce shape (elements, segments, k_tiles) keys a new executable."""
    import functools
    import os

    import jax
    import numpy as np

    from photon_tpu.ops import segment_reduce as sr

    def tr(name: str, *, m: int, n: int, mult: int = 1) -> TracedProgram:
        fn = functools.partial(
            sr.sorted_segment_sum,
            num_segments=n,
            multiplicity=mult,
            interpret=sr.interpret_required(),
        )
        return trace_program(
            name,
            fn,
            jax.ShapeDtypeStruct((m,), np.float32),
            jax.ShapeDtypeStruct((m,), np.int32),
        )

    # The kernel path must be what gets traced here regardless of the
    # host's backend: force it for the audit (env restored after).
    prev = os.environ.get("PHOTON_SEGMENT_KERNEL")
    os.environ["PHOTON_SEGMENT_KERNEL"] = "force"
    try:
        base = tr("segment_sum", m=4096, n=2048)
        variants = {
            "reduce_shape": [
                {"segment_sum": tr("v", m=8192, n=2048).signature},
                {"segment_sum": tr("v", m=4096, n=2048,
                                   mult=4).signature},
            ],
        }
    finally:
        if prev is None:
            os.environ.pop("PHOTON_SEGMENT_KERNEL", None)
        else:
            os.environ["PHOTON_SEGMENT_KERNEL"] = prev
    return ContractTrace(
        programs={"segment_sum": base},
        variants=variants,
    )


def build_serve_kernel() -> ContractTrace:
    """The fused serve-score kernel's one-program contract.

    The same tiny GLMix fixture as ``build_serving`` is loaded into
    serving tables with ``PHOTON_SERVE_KERNEL=force`` (env restored
    after), so ``ScorePrograms.trace`` lowers the fused pallas_call
    instead of the per-coordinate jit chain — through the interpreter
    path on non-TPU hosts (Mosaic lowering is TPU-only). One rung is
    ONE program: tables, features and the scalar-prefetched codes are
    traced operands. The declared recompile families prove the two
    static specializations still specialize: a different ``rung``
    (grid size) and a different ``model_structure`` (feature width)
    must each perturb the compile key.
    """
    import os

    import numpy as np

    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(20260806)

    def model_for(d: int, e: int = 7, s: int = 3, du: int = 6):
        prng = np.random.default_rng(1234)
        proj = np.sort(
            np.stack([
                prng.permutation(du)[:s] for _ in range(e)
            ]), axis=1,
        ).astype(np.int64)
        return GameModel({
            "global": FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(means=jnp.asarray(
                        rng.normal(size=d).astype(np.float32)
                    )),
                    TaskType.LOGISTIC_REGRESSION,
                ),
                "features",
            ),
            "per-user": RandomEffectModel(
                coefficients=jnp.asarray(
                    rng.normal(size=(e, s)).astype(np.float32)
                ),
                random_effect_type="userId",
                feature_shard_id="userShard",
                task=TaskType.LOGISTIC_REGRESSION,
                proj_all=proj,
                entity_keys=tuple(str(i) for i in range(e)),
            ),
        })

    def rung_program(d: int, rung: int, *, name: str) -> TracedProgram:
        ladder = ShapeLadder((rung,))
        tables = CoefficientTables.from_game_model(model_for(d))
        programs = ScorePrograms(
            tables, ladder=ladder, compile_now=False
        )
        if not programs.use_kernel:
            raise RuntimeError(
                "PHOTON_SERVE_KERNEL=force did not engage the fused "
                "kernel — the serve-kernel contract audits nothing"
            )
        traced = programs.trace(rung)
        return TracedProgram(
            name=name,
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )

    # The kernel path must be what gets traced here regardless of the
    # host's backend: force it for the audit (env restored after).
    prev = os.environ.get("PHOTON_SERVE_KERNEL")
    os.environ["PHOTON_SERVE_KERNEL"] = "force"
    try:
        base = rung_program(5, 8, name="serve_kernel_b8")
        variants = {
            "rung": [
                {"serve_kernel_b8": rung_program(
                    5, r, name="v").signature}
                for r in (1, 64)
            ],
            "model_structure": [
                {"serve_kernel_b8": rung_program(
                    9, 8, name="v").signature},
            ],
        }
    finally:
        if prev is None:  # photon: ignore[spmd-host-divergence] -- env save/restore of the audit fixture's kernel flag; host-local tooling, not fleet code
            os.environ.pop("PHOTON_SERVE_KERNEL", None)
        else:
            os.environ["PHOTON_SERVE_KERNEL"] = prev
    return ContractTrace(
        programs={"serve_kernel_b8": base},
        variants=variants,
        notes=[
            "fused pallas_call traced through the interpret path; "
            "tables/features/codes are traced operands — a values-only "
            "reload re-enters the same executable (build_serving's "
            "model_reload family covers the jit fallback)",
        ],
    )


def build_mesh_sharding() -> ContractTrace:
    """Mesh entry points: the data-parallel GLM objective over a sharded
    batch, plus the random-effect dataset placement rules — checked from
    the placed arrays' NamedShardings and the compiled HLO's collectives.
    Includes the reasoned report of why the fused path rejects meshes."""
    import jax
    import numpy as np

    from photon_tpu.algorithm.fused_fit import fuse_ineligibility_reasons
    from photon_tpu.data.dataset import make_dense_batch
    from photon_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    from photon_tpu.ops import losses as losses_mod
    from photon_tpu.ops import glm as glm_ops
    from photon_tpu.ops.normalization import NormalizationContext
    from photon_tpu.parallel.mesh import (
        make_mesh,
        replicated,
        shard_batch,
        shard_random_effect_dataset,
    )
    from photon_tpu.types import TaskType

    if len(jax.devices()) < 2:
        return ContractTrace(
            programs={},
            notes=[
                "sharding audit SKIPPED: single visible device (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8, as "
                "CI does, to exercise it)",
            ],
        )

    mesh = make_mesh()
    n_dev = len(mesh.devices.reshape(-1))
    n, d = 8 * n_dev, 5
    rng = np.random.default_rng(1)
    batch = shard_batch(
        make_dense_batch(
            rng.normal(size=(n, d)).astype(np.float32),
            (rng.uniform(size=n) < 0.5).astype(np.float32),
        ),
        mesh,
    )
    loss = losses_mod.get_loss(TaskType.LOGISTIC_REGRESSION)

    def objective(b, w):
        return glm_ops.make_value_and_grad(b, loss, NormalizationContext())(w)

    w = jax.device_put(
        jax.numpy.zeros(d, batch.labels.dtype), replicated(mesh)
    )
    prog = trace_program("sharded_objective", objective, batch, w)
    collectives = hlo_collectives(prog.lowered.compile())

    opshardings = {
        "features": str(batch.features.x.sharding.spec),
        "labels": str(batch.labels.sharding.spec),
        "offsets": str(batch.offsets.sharding.spec),
        "weights": str(batch.weights.sharding.spec),
    }

    # Random-effect placement rules: plan arrays entity-sharded, shared
    # raw leaves replicated (mesh.shard_random_effect_dataset contract).
    est, data = _tiny_glmix(n=16 * n_dev, e=2 * n_dev)
    re_ds = build_random_effect_dataset(
        data,
        RandomEffectDataConfiguration("userId", "userShard"),
        intercept_index=3,
    )
    re_ds = shard_random_effect_dataset(re_ds, mesh)
    b0 = re_ds.blocks[0]
    opshardings["re_entity_codes"] = str(b0.entity_codes.sharding.spec)
    opshardings["re_row_ids"] = str(b0.row_ids.sharding.spec)
    raw = re_ds.raw
    raw_leaf = getattr(raw, "x", None)
    if raw_leaf is None:
        raw_leaf = raw.values
    opshardings["re_raw"] = str(raw_leaf.sharding.spec)

    # Why the fused whole-fit path refuses this mesh today — the reasoned
    # report the ROADMAP's multi-device fusion work starts from.
    datasets, _ = est.prepare(data)
    coords = est._build_coordinates(datasets, {}, {}, data.num_samples)
    reasons = fuse_ineligibility_reasons(coords, mesh=mesh)
    notes = [f"mesh fusion blocked: {r}" for r in reasons] or [
        "fuse_ineligibility_reasons reports no blockers — revisit the "
        "estimator's mesh gate"
    ]
    return ContractTrace(
        programs={"sharded_objective": prog},
        opshardings=opshardings,
        collectives=collectives,
        notes=notes,
    )


def build_ingest_pipeline() -> ContractTrace:
    """The ingest pipeline's overlapped AOT warm-compile entry.

    Two properties, both checked against the PRODUCTION fused generation:

    - **census unchanged**: the programs the background warm compile
      traces from shape-PREDICTED skeleton datasets
      (``GameEstimator._warm_compile`` over
      ``skeleton_random_effect_dataset``) must have EXACTLY the
      signatures of the production materialize/fit programs — the warm
      compile mints zero new executables, it pre-pays existing ones. A
      drifted skeleton (wrong predicted bucket shapes, wrong statics)
      shows up as extra programs in the census and as an
      ``aot_warm_compile`` stability violation.
    - **no host sync in the overlap window**: the traced fit jaxpr (which
      signature-equality proves is also the warm-compiled one) carries no
      callback primitive (``hot_loop`` host-boundary check).

    Runs with ``PHOTON_TPU_SERIAL_INGEST=1`` so the build itself is
    deterministic and the warm compile is invoked synchronously.
    """
    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        mat = trace_program(
            "materialize", fused._mat_jit, fused._mat_operands(coords)
        )
        traced = fused.trace(coords)
        fit = TracedProgram(
            name="fit",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )
        art = est._warm_compile(data)
    variants: dict[str, list[dict[str, str]]] = {"aot_warm_compile": []}
    notes = []
    if art is not None:
        variants["aot_warm_compile"].append({
            "materialize": TracedProgram(
                "materialize", art["mat_text"]).signature,
            "fit": TracedProgram("fit", art["fit_text"]).signature,
        })
        notes.append(
            "warm compile traced from predicted shapes; signature "
            "equality with the production programs proves the compiled "
            "executables are the ones the first fit dispatches"
        )
    # else: the empty declared-stable family trips the program-contract
    # integrity finding — prediction silently declining on the canonical
    # fixture is a contract violation, not a skip.
    return ContractTrace(
        programs={"materialize": mat, "fit": fit},
        variants=variants,
        notes=notes,
    )


def build_telemetry() -> ContractTrace:
    """The telemetry layer's audited zero-overhead guarantee.

    The instrumented entry points — the fused materialize + whole-fit
    programs that every obs span wraps and every convergence trace rides
    — are traced twice, with telemetry DISABLED (base) and ENABLED
    (the ``telemetry_toggle`` variant family). The checks then prove:

    - **zero dispatches added**: the census across both states stays at
      the fused generation's own 2 programs — enabling telemetry mints
      no executable (convergence metrics are unconditional outputs of
      the existing fit program, never a side program or a split);
    - **zero host callbacks**: the hot-loop host-boundary walk over the
      (shared) jaxpr finds no callback primitive — spans and the async
      convergence fetch live entirely OUTSIDE the trace;
    - **identical recompile keys**: ``stable_under=telemetry_toggle`` —
      the enabled-state signatures must be byte-identical to the
      disabled-state ones, so flipping telemetry can never trigger a
      recompile in production.
    """
    from photon_tpu import obs

    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        was_enabled = obs.enabled()
        obs.disable()
        try:
            mat_off = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_off = fused.trace(coords)
            fit_off = TracedProgram(
                name="fit",
                text=str(traced_off.jaxpr),
                jaxpr=traced_off.jaxpr,
                lowered=traced_off.lower(),
            )
            obs.enable()
            mat_on = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_on = fused.trace(coords)
            fit_on = TracedProgram(
                name="fit", text=str(traced_on.jaxpr)
            )
        finally:
            obs.TRACER.enabled = was_enabled
    return ContractTrace(
        programs={"materialize": mat_off, "fit": fit_off},
        variants={
            "telemetry_toggle": [
                {
                    "materialize": mat_on.signature,
                    "fit": fit_on.signature,
                }
            ]
        },
        notes=[
            "telemetry on vs off traced the same materialize/fit "
            "jaxprs: the enable flag is host-side only (convergence "
            "metrics are unconditional program outputs; spans never "
            "enter a trace)",
        ],
    )


def build_trace() -> ContractTrace:
    """The timeline layer's audited zero-overhead guarantee.

    ``build_telemetry`` proves the span/metric/convergence surfaces add
    nothing to the traced programs; this contract raises the same bar
    for the TRACE layer on top of them (``obs/trace.py`` +
    ``obs/flight.py``): the fused materialize + whole-fit programs are
    traced with everything OFF (base) and then with the layer fully
    ARMED — telemetry enabled, a flight recorder installed (its
    excepthook + crash-listener chained, its counter baseline taken),
    and the event ring actively receiving instants, counter samples,
    and request records between the two traces. The
    ``trace_toggle`` variant must be byte-identical to the base:
    events are host-ring bookkeeping on the perf_counter clock, never
    a traced operand, a callback, or a program split.
    """
    import tempfile

    from photon_tpu import obs
    from photon_tpu.obs import flight
    from photon_tpu.obs import trace as obs_trace

    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        was_enabled = obs.enabled()
        obs.disable()
        try:
            mat_off = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_off = fused.trace(coords)
            fit_off = TracedProgram(
                name="fit",
                text=str(traced_off.jaxpr),
                jaxpr=traced_off.jaxpr,
                lowered=traced_off.lower(),
            )
            # Arm the whole layer (install enables telemetry) and keep
            # the ring HOT while the armed trace is taken.
            tmpdir = tempfile.mkdtemp(prefix="photon-trace-audit-")
            flight.install(tmpdir, signals=False)
            try:
                obs_trace.instant("audit.armed", cat="audit")
                obs_trace.counter("audit_gauge", 1.0)
                obs_trace.request({
                    "id": 0, "outcome": "served",
                    "submit_ts": 0.0, "done_ts": 0.0,
                })
                mat_on = trace_program(
                    "materialize", fused._mat_jit,
                    fused._mat_operands(coords),
                )
                traced_on = fused.trace(coords)
                fit_on = TracedProgram(
                    name="fit", text=str(traced_on.jaxpr)
                )
            finally:
                flight.uninstall()
                # The audit fed the PROCESS-GLOBAL ring (a phantom
                # served request, audit instants) purely to arm the
                # traced state — clean up behind it, or a later
                # in-process consumer (request_summary, the exporters)
                # sees audit debris on its timeline.
                obs_trace.reset()
                import shutil

                shutil.rmtree(tmpdir, ignore_errors=True)
        finally:
            obs.TRACER.enabled = was_enabled
    return ContractTrace(
        programs={"materialize": mat_off, "fit": fit_off},
        variants={
            "trace_toggle": [
                {
                    "materialize": mat_on.signature,
                    "fit": fit_on.signature,
                }
            ]
        },
        notes=[
            "flight recorder installed + event ring receiving "
            "instants/counters/request records traced the same "
            "materialize/fit jaxprs as the all-off base: the timeline "
            "layer is host bookkeeping only",
        ],
    )


def build_fleet() -> ContractTrace:
    """The distributed-observability layer's audited zero-overhead
    guarantee (``obs/fleet.py``).

    The fused materialize + whole-fit programs are traced with fleet
    shipping fully ARMED — telemetry enabled, the host-identity block
    stamped, the clock-alignment handshake marked (``mark_init``), and
    a whole bundle COMMITTED to disk (spans JSONL + metrics + ledger
    rows through ``ship_bundle``) between the two traces. The
    ``fleet_toggle`` variant must be byte-identical to the all-off
    base with ZERO added programs: identity is a cached host dict,
    clock samples are paired ``time()`` reads, and a bundle ship is
    ring snapshots + atomic file writes — never a traced operand, a
    host callback in the hot loop, or a cross-host exchange inside a
    program. Zero added collectives is checked explicitly: the armed
    lowered HLO must carry exactly the collective census of the base
    (both empty on the single-device fixture).
    """
    import shutil
    import tempfile

    from photon_tpu import obs
    from photon_tpu.obs import fleet
    from photon_tpu.obs import trace as obs_trace

    def _collective_census(lowered) -> list[str]:
        if lowered is None:
            return []
        try:
            text = lowered.as_text()
        except Exception:  # noqa: BLE001 — backend without HLO text
            return []
        from photon_tpu.analysis import spmd

        return spmd.collective_census(text)

    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        was_enabled = obs.enabled()
        obs.disable()
        try:
            mat_off = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_off = fused.trace(coords)
            fit_off = TracedProgram(
                name="fit",
                text=str(traced_off.jaxpr),
                jaxpr=traced_off.jaxpr,
                lowered=traced_off.lower(),
            )
            base_census = _collective_census(fit_off.lowered)
            # Arm the whole fleet layer and COMMIT a real bundle while
            # the armed trace is taken.
            obs.enable()
            tmpdir = tempfile.mkdtemp(prefix="photon-fleet-audit-")
            try:
                fleet.set_run_id("fleet-audit")
                fleet.mark_init()
                with obs.span("fleet_audit_span"):
                    pass
                obs_trace.instant("fleet.audit", cat="audit")
                fleet.ship_bundle(tmpdir)
                mat_on = trace_program(
                    "materialize", fused._mat_jit,
                    fused._mat_operands(coords),
                )
                traced_on = fused.trace(coords)
                fit_on = TracedProgram(
                    name="fit",
                    text=str(traced_on.jaxpr),
                    lowered=traced_on.lower(),
                )
                armed_census = _collective_census(fit_on.lowered)
            finally:
                fleet.reset()
                obs_trace.reset()
                obs.TRACER.reset()
                shutil.rmtree(tmpdir, ignore_errors=True)
        finally:
            obs.TRACER.enabled = was_enabled
    if armed_census != base_census:
        raise RuntimeError(
            "fleet-armed fit program changed its collective census: "
            f"base {base_census} vs armed {armed_census}"
        )
    return ContractTrace(
        programs={"materialize": mat_off, "fit": fit_off},
        variants={
            "fleet_toggle": [
                {
                    "materialize": mat_on.signature,
                    "fit": fit_on.signature,
                }
            ]
        },
        collectives=base_census,
        notes=[
            "fleet armed (identity stamped, clock handshake marked, "
            "bundle committed to disk) traced the same materialize/fit "
            "jaxprs as the all-off base; collective census identical "
            f"armed vs off ({len(base_census)} ops)",
        ],
    )


def build_ledger() -> ContractTrace:
    """The cost ledger's audited zero-overhead guarantee.

    The fused materialize + whole-fit programs are traced with the
    ledger OFF (base) and then FULLY ARMED — enabled, a program in the
    census, dispatch/compile/resident records landing through every
    recording helper between the two traces. The ``ledger_toggle``
    variant must be byte-identical to the base with ZERO added
    programs: attribution rows are host dicts under a host lock, the
    static-cost join is a lazy thunk priced at report time, and a
    ledger-DISABLED run registers nothing at all (the census stays
    empty — the profile-smoke CI job asserts that end too).
    """
    from photon_tpu import obs
    from photon_tpu.obs import ledger

    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        was_enabled = obs.enabled()
        was_ledger = ledger.enabled()
        obs.disable()
        ledger.disable()
        try:
            mat_off = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_off = fused.trace(coords)
            fit_off = TracedProgram(
                name="fit",
                text=str(traced_off.jaxpr),
                jaxpr=traced_off.jaxpr,
                lowered=traced_off.lower(),
            )
            # Arm the whole layer and keep the accumulators HOT while
            # the armed trace is taken: census, dispatch rows (with
            # per-coordinate parts + host-gap), compile ledger, and
            # the resident account all receive records.
            obs.enable()
            ledger.enable()
            try:
                ledger.register_program(
                    "audit/program", phase="audit",
                    cost={"flops": 1.0, "hbm_bytes": 1.0},
                )
                ledger.record_dispatch(
                    "audit/program", 1e-3, phase="audit",
                    start=0.0, end=1e-3,
                    parts={"audit-coord": 1e-3},
                )
                ledger.record_unattributed(1e-4)
                ledger.record_compile("audit/key", 1e-2)
                ledger.set_resident("audit/table", 128.0)
                mat_on = trace_program(
                    "materialize", fused._mat_jit,
                    fused._mat_operands(coords),
                )
                traced_on = fused.trace(coords)
                fit_on = TracedProgram(
                    name="fit", text=str(traced_on.jaxpr)
                )
            finally:
                # Audit debris must not leak into a later in-process
                # consumer's ledger (a bench attribution window, a
                # pilot cycle report).
                ledger.reset()
        finally:
            obs.TRACER.enabled = was_enabled
            if was_ledger:
                ledger.enable()
            else:
                ledger.disable()
    return ContractTrace(
        programs={"materialize": mat_off, "fit": fit_off},
        variants={
            "ledger_toggle": [
                {
                    "materialize": mat_on.signature,
                    "fit": fit_on.signature,
                }
            ]
        },
        notes=[
            "ledger armed (census + dispatch rows + compile ledger + "
            "resident account all fed) traced the same materialize/fit "
            "jaxprs as the all-off base: attribution is host "
            "bookkeeping, pricing is lazy at report time",
        ],
    )


def build_health() -> ContractTrace:
    """The model/data-health layer's audited zero-dispatch guarantee.

    The fused materialize + whole-fit programs are traced with health
    OFF (base) and then FULLY ARMED — enabled, a training DataSketch
    fed and registered, the serve tap folding sampled batches, a
    numerics sentinel parked AND materialized (the report scan), and a
    gate decision recorded — between the two traces. The
    ``health_toggle`` variant must be byte-identical to the base with
    ZERO added programs: sketches are host numpy under a host lock,
    the sentinel parks a reference to an array the fit ALREADY outputs
    (the convergence block), and PSI/ECE/movement scoring happens at
    report time, never inside (or as) a traced program.
    """
    import numpy as np

    from photon_tpu.obs import health

    with _serial_ingest_env():
        est, data = _tiny_glmix()
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        was_health = health.enabled()
        health.disable()
        try:
            mat_off = trace_program(
                "materialize", fused._mat_jit, fused._mat_operands(coords)
            )
            traced_off = fused.trace(coords)
            fit_off = TracedProgram(
                name="fit",
                text=str(traced_off.jaxpr),
                jaxpr=traced_off.jaxpr,
                lowered=traced_off.lower(),
            )
            # Arm the whole layer and keep every surface HOT while the
            # armed trace is taken: train sketch, serve tap, parked +
            # scanned sentinel, recorded gate decision.
            health.enable()
            try:
                sketch = health.DataSketch()
                sketch.update_window(
                    np.asarray([0.0, 1.0, 1.0]),
                    np.zeros(3),
                    np.ones(3),
                    {"audit": (
                        np.asarray([[0, 1], [1, 0], [0, 1]]),
                        np.asarray([[0.5, 1.0], [2.0, 0.0], [1.5, 0.5]]),
                    )},
                    {"audit": 4},
                )
                health.set_train_sketch(sketch)
                health.set_serve_sample_every(1)
                health.observe_serve_batch(
                    [{"audit": np.zeros(4, dtype=np.float32)}],
                    np.asarray([0.25]),
                )
                health.sentinel_watch(
                    ("audit-coord",),
                    np.asarray([[[1.0, np.nan, 0.0, 0.0, 0.0]]]),
                )
                report = health.numerics_report()
                health.record_gate({
                    "reasons": [], "nonfinite": report["nonfinite_total"],
                })
                mat_on = trace_program(
                    "materialize", fused._mat_jit,
                    fused._mat_operands(coords),
                )
                traced_on = fused.trace(coords)
                fit_on = TracedProgram(
                    name="fit", text=str(traced_on.jaxpr)
                )
            finally:
                # Audit debris (the fake sentinel, the sampled batch)
                # must not leak into a later in-process consumer's
                # health surfaces (a pilot gate, a bench drift run).
                health.reset()
        finally:
            if was_health:
                health.enable()
            else:
                health.disable()
    return ContractTrace(
        programs={"materialize": mat_off, "fit": fit_off},
        variants={
            "health_toggle": [
                {
                    "materialize": mat_on.signature,
                    "fit": fit_on.signature,
                }
            ]
        },
        notes=[
            "health armed (train sketch + serve tap + parked/scanned "
            "numerics sentinel + recorded gate) traced the same "
            "materialize/fit jaxprs as the all-off base: sketching and "
            "scoring are host bookkeeping, the sentinel reads an "
            "output the program already computes",
        ],
    )


def build_monitor() -> ContractTrace:
    """The live-monitoring layer's audited zero-overhead guarantee.

    The serving score program (the request hot path the exporter
    observes) is traced with everything OFF (base), then with the
    monitor layer FULLY ARMED AND UNDER LOAD: a ``MonitorServer`` up on
    an ephemeral port with the window-histogram/SLO/hotness collectors
    registered, a feeder thread pumping observations into the window
    ring, the sketch, and the SLO tracker the whole time, and real
    HTTP scrapes of ``/metrics`` + ``/healthz`` + ``/readyz`` issued
    before, during, and after the armed trace. The ``monitor_scrape``
    variant must be byte-identical to the base with zero added
    programs — a scrape is host bookkeeping and socket I/O, never a
    traced operand or a callback — and every scraped ``/metrics`` body
    must validate as Prometheus text exposition
    (``monitor.validate_exposition``).
    """
    import threading
    import urllib.request

    import numpy as np

    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.obs import monitor
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    d, e, s, du = 4, 5, 2, 4
    rng = np.random.default_rng(20260803)
    proj = np.stack([
        np.sort(rng.permutation(du)[:s]) for _ in range(e)
    ]).astype(np.int64)
    model = GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(means=jnp.asarray(
                    rng.normal(size=d).astype(np.float32)
                )),
                TaskType.LOGISTIC_REGRESSION,
            ),
            "features",
        ),
        "per-user": RandomEffectModel(
            coefficients=jnp.asarray(
                rng.normal(size=(e, s)).astype(np.float32)
            ),
            random_effect_type="userId",
            feature_shard_id="userShard",
            task=TaskType.LOGISTIC_REGRESSION,
            proj_all=proj,
            entity_keys=tuple(str(i) for i in range(e)),
        ),
    })
    tables = CoefficientTables.from_game_model(model)
    programs = ScorePrograms(
        tables, ladder=ShapeLadder((8,)), compile_now=False
    )

    def trace_once() -> TracedProgram:
        traced = programs.trace(8)
        return TracedProgram(
            name="score_b8",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )

    base = trace_once()

    hist = monitor.RollingHistogram(window_s=0.5, num_windows=4)
    sketch = monitor.SpaceSavingSketch(8)
    slo = monitor.SloTracker(
        monitor.SloPolicy(short_window_s=0.5, long_window_s=2.0)
    )

    def collect():
        return (
            [hist.prometheus_family(
                "audit_latency_window_seconds", "audit window ring")]
            + slo.prometheus_families()
        )

    stop = threading.Event()

    def feeder():
        import time

        i = 0
        while not stop.is_set():
            hist.observe(0.001 * (1 + i % 7))
            sketch.observe(f"entity-{i % 11}")
            slo.observe_request(0.002)
            slo.observe_lookups(4, 1)
            i += 1
            # Keep the surfaces hot without pegging a CI core: the
            # audit needs concurrent writers, not maximum write rate.
            time.sleep(0.0005)

    notes: list[str] = []
    srv = monitor.MonitorServer(0, readiness=lambda: (True, {}),
                                collectors=[collect]).start()
    thread = threading.Thread(target=feeder, daemon=True)  # photon: ignore[concurrency-contract] -- audit-fixture load generator, joined before the builder returns; the shared surfaces it feeds carry their own obs-monitor contract
    thread.start()

    def scrape() -> None:
        for path in ("/metrics", "/healthz", "/readyz"):
            body = urllib.request.urlopen(
                srv.url + path, timeout=5
            ).read().decode("utf-8")
            if path == "/metrics":
                monitor.validate_exposition(body)

    # A second scraper loops CONCURRENTLY with the armed trace below —
    # "during" is exercised for real, not just claimed. Its failures
    # are collected and re-raised as a builder error (-> a
    # program-contract finding), never swallowed.
    scrape_errors: list[BaseException] = []
    during_scrapes = [0]

    def scraper():
        while not stop.is_set():
            try:
                scrape()
                during_scrapes[0] += 1
            except BaseException as exc:  # noqa: BLE001 — surfaced below
                scrape_errors.append(exc)
                return

    scraper_thread = threading.Thread(target=scraper, daemon=True)  # photon: ignore[concurrency-contract] -- audit-fixture scraper, joined before the builder returns; see the feeder waiver above
    try:
        scrape()
        scraper_thread.start()
        armed = TracedProgram(
            name="score_b8", text=str(programs.trace(8).jaxpr)
        )
        stop.set()
        scraper_thread.join(timeout=10.0)
        scrape()
        if scrape_errors:
            raise scrape_errors[0]
        notes.append(
            f"exporter scraped before, DURING ({during_scrapes[0]} "
            "concurrent scrape round(s)), and after the armed trace; "
            "every /metrics body validated as text exposition; the "
            "window ring, hotness sketch, and SLO tracker were fed "
            "from a second thread throughout"
        )
    finally:
        stop.set()
        thread.join(timeout=5.0)
        if scraper_thread.is_alive():  # pragma: no cover — start() raced
            scraper_thread.join(timeout=5.0)
        srv.stop()
    return ContractTrace(
        programs={"score_b8": base},
        variants={"monitor_scrape": [{"score_b8": armed.signature}]},
        notes=notes,
    )


def build_serving() -> ContractTrace:
    """The serving score ladder's zero-recompile contract.

    A small GLMix model (one dense fixed effect + one random effect with
    a non-trivial projector) is loaded into serving tables and its
    ladder program traced at every rung — those are the base programs
    (census bound = rung count). Two variant families then prove the
    steady state is CLOSED:

    - ``request_batch``: every request count from 1 to the top rung,
      padded through the PRODUCTION pad rule (``ShapeLadder.rung_for``),
      must trace to the signature of its rung's base program — a pad
      rule that leaked an unpadded (or wrongly padded) shape would mint
      a new program here and fail both the census and the stability
      check.
    - ``model_reload``: the tables refreshed in place with different
      coefficient VALUES (same shapes) must trace every rung to a
      byte-identical signature — coefficients are traced operands, so a
      model reload can never trigger a recompile in a serving process.

    The fit programs' ``hot_loop`` host-boundary walk applies too: no
    callback primitive may live in the request hot path.
    """
    import numpy as np

    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    d, e, s, du = 5, 7, 3, 6
    rng = np.random.default_rng(20260803)

    def model_for(scale: float) -> GameModel:
        # Fixed-seed projector: the reload variant below must be a
        # VALUES-ONLY refresh (reload's in-place condition).
        prng = np.random.default_rng(1234)
        proj = np.sort(
            np.stack([
                prng.permutation(du)[:s] for _ in range(e)
            ]), axis=1,
        ).astype(np.int64)
        return GameModel({
            "global": FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(means=jnp.asarray(
                        scale * rng.normal(size=d).astype(np.float32)
                    )),
                    TaskType.LOGISTIC_REGRESSION,
                ),
                "features",
            ),
            "per-user": RandomEffectModel(
                coefficients=jnp.asarray(
                    scale * rng.normal(size=(e, s)).astype(np.float32)
                ),
                random_effect_type="userId",
                feature_shard_id="userShard",
                task=TaskType.LOGISTIC_REGRESSION,
                proj_all=proj,
                entity_keys=tuple(str(i) for i in range(e)),
            ),
        })

    ladder = ShapeLadder((1, 8, 64))
    tables = CoefficientTables.from_game_model(model_for(1.0))
    programs = ScorePrograms(tables, ladder=ladder, compile_now=False)

    def rung_program(progs: ScorePrograms, batch: int) -> TracedProgram:
        traced = progs.trace(batch)
        return TracedProgram(
            name=f"score_b{batch}",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )

    base = {
        f"score_b{r}": rung_program(programs, r) for r in ladder.rungs
    }

    variants: dict[str, list[dict[str, str]]] = {
        "request_batch": [],
        "model_reload": [],
    }
    # One fresh trace per DISTINCT shape the pad rule produces (a
    # broken rung_for surfaces as a new shape here — traced at n, its
    # signature both breaks the census bound and misses the base
    # programs); re-tracing identical rungs per request count would add
    # gate wall-clock for zero signal.
    rung_sigs: dict[int, str] = {}
    for n in range(1, ladder.max_batch + 1):
        rung = ladder.rung_for(n)
        if rung not in rung_sigs:
            rung_sigs[rung] = TracedProgram(
                name="v", text=str(programs.trace(rung).jaxpr)
            ).signature
        variants["request_batch"].append(
            {f"score_b{rung}": rung_sigs[rung]}
        )
    tables.reload(model_for(2.5))
    variants["model_reload"].append({
        name: TracedProgram(
            name="v", text=str(programs.trace(r).jaxpr)
        ).signature
        for r, name in zip(ladder.rungs, base)
    })
    return ContractTrace(
        programs=base,
        variants=variants,
        notes=[
            f"ladder {ladder.rungs}: every request count 1.."
            f"{ladder.max_batch} pads into the {len(ladder.rungs)} "
            "compiled rungs; an in-place model reload re-traces to "
            "byte-identical programs (tables are traced operands)",
        ],
    )


def build_resilience() -> ContractTrace:
    """The resilience layer's zero-program-footprint contract.

    ``call_with_retry`` and ``faults.check`` are HOST machinery wrapped
    around already-built executables — they must never alter what gets
    traced. Proof by construction: one serving score program (a tiny
    GLMix structure, single rung) is the base; the SAME trace is then
    taken (a) from inside a ``call_with_retry`` wrapper and (b) with a
    full-coverage armed ``FaultPlan`` whose triggers can never fire
    (``nth`` beyond any call count) — both must be byte-identical to
    the base signature. The ``hot_loop`` walk additionally proves no
    callback primitive entered the jaxpr (a retry layer implemented as
    an in-trace ``pure_callback`` would fail here, which is the point).
    """
    import numpy as np

    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.resilience import FaultPlan, call_with_retry, faults
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    d, e, s, du = 4, 5, 2, 4
    rng = np.random.default_rng(20260803)
    proj = np.stack([
        np.sort(rng.permutation(du)[:s]) for _ in range(e)
    ]).astype(np.int64)
    model = GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(means=jnp.asarray(
                    rng.normal(size=d).astype(np.float32)
                )),
                TaskType.LOGISTIC_REGRESSION,
            ),
            "features",
        ),
        "per-user": RandomEffectModel(
            coefficients=jnp.asarray(
                rng.normal(size=(e, s)).astype(np.float32)
            ),
            random_effect_type="userId",
            feature_shard_id="userShard",
            task=TaskType.LOGISTIC_REGRESSION,
            proj_all=proj,
            entity_keys=tuple(str(i) for i in range(e)),
        ),
    })
    tables = CoefficientTables.from_game_model(model)
    programs = ScorePrograms(
        tables, ladder=ShapeLadder((8,)), compile_now=False
    )

    def trace_once() -> TracedProgram:
        traced = programs.trace(8)
        return TracedProgram(
            name="score_b8",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )

    base = trace_once()
    wrapped = call_with_retry(trace_once, site="audit.resilience")
    # Full coverage, unreachable triggers: arming must be invisible to
    # tracing (the hooks are host-side, outside any trace).
    plan = FaultPlan(
        [dict(point=p, nth=10**9) for p in faults.INJECTION_POINTS],
        seed=0,
    )
    with faults.injected(plan):
        armed = trace_once()
    return ContractTrace(
        programs={"score_b8": base},
        variants={
            "retry_wrap": [{"score_b8": wrapped.signature}],
            "fault_plan_armed": [{"score_b8": armed.signature}],
        },
        notes=[
            "retry wrapper + armed FaultPlan trace byte-identical "
            "programs: the resilience layer is host-level only",
        ],
    )


def build_streaming_ingest() -> ContractTrace:
    """The streaming ingest's zero-program-perturbation contract.

    The SAME logical data is ingested two ways — the in-memory
    ``read_training_examples`` path (base) and ``StreamingIngest`` over
    a sharded on-disk copy with a multi-shard window plan (the
    ``streamed_ingest`` variant family) — and the fused materialize +
    whole-fit programs are traced from each. The checks prove the
    streamed dataset dispatches BYTE-IDENTICAL programs: identical
    census (zero added programs), identical recompile keys, and a
    callback-free hot loop. Windowed assembly, quarantine accounting,
    spill/cursor machinery are host/IO-level only, provably.
    """
    import shutil
    import tempfile

    import numpy as np

    from photon_tpu.data.random_effect import RandomEffectDataConfiguration
    from photon_tpu.data.stream import StreamingIngest
    from photon_tpu.estimators.game_estimator import (
        FixedEffectCoordinateConfiguration,
        GameEstimator,
        RandomEffectCoordinateConfiguration,
    )
    from photon_tpu.io.avro_data import (
        read_training_examples,
        write_training_examples,
    )
    from photon_tpu.types import DELIMITER, TaskType

    def make_estimator():
        return GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {
                "global": FixedEffectCoordinateConfiguration(
                    "features", _l2_config(0.01)),
                "per-user": RandomEffectCoordinateConfiguration(
                    RandomEffectDataConfiguration("userId", "features"),
                    _l2_config(0.5),
                ),
            },
            num_iterations=2,
            mesh="off",
        )

    def trace_pair(est, data):
        datasets, _ = est.prepare(data)
        coords = est._build_coordinates(
            datasets, {}, {}, data.num_samples
        )
        fused = est._fused_for(coords, datasets)
        mat = trace_program(
            "materialize", fused._mat_jit, fused._mat_operands(coords)
        )
        traced = fused.trace(coords)
        fit = TracedProgram(
            name="fit",
            text=str(traced.jaxpr),
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
        )
        return mat, fit

    tmp = tempfile.mkdtemp(prefix="photon_stream_audit")
    try:
        with _serial_ingest_env():
            rng = np.random.default_rng(20260803)
            n_per, shards_n, d, e = 32, 3, 4, 7
            base = 0
            for si in range(shards_n):
                y = (rng.uniform(size=n_per) < 0.5).astype(float)
                rows = [
                    [(f"f{j}{DELIMITER}t", float(rng.normal()))
                     for j in range(d)]
                    for _ in range(n_per)
                ]
                meta = [
                    {"userId": f"u{rng.integers(0, e)}"}
                    for _ in range(n_per)
                ]
                write_training_examples(
                    os.path.join(tmp, f"part-{si:05d}.avro"),
                    y, rows, metadata=meta,
                    uids=np.arange(base, base + n_per),
                )
                base += n_per
            in_mem, imap = read_training_examples(tmp)
            mat_base, fit_base = trace_pair(make_estimator(), in_mem)
            streamed, stats = StreamingIngest(
                tmp,
                work_dir=os.path.join(tmp, "work"),
                index_maps={"features": imap},
                id_tag_names=["userId"],
                window_shards=2,
            ).run()
            mat_s, fit_s = trace_pair(make_estimator(), streamed)
        notes = [
            "streamed windows vs in-memory ingest traced the same "
            "materialize/fit jaxprs: the streaming layer (manifest, "
            "windows, spills, cursor) is host/IO machinery only",
            f"clean streamed run ingested_fraction="
            f"{stats['ingested_fraction']}, quarantined="
            f"{stats['shards_quarantined']}",
        ]
        if stats["ingested_fraction"] != 1.0:
            notes.append(
                "AUDIT FIXTURE ANOMALY: the clean streamed run did not "
                "ingest everything")
        return ContractTrace(
            programs={"materialize": mat_base, "fit": fit_base},
            variants={
                "streamed_ingest": [
                    {
                        "materialize": mat_s.signature,
                        "fit": fit_s.signature,
                    }
                ]
            },
            notes=notes,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def build_evaluators() -> ContractTrace:
    """Evaluation + scoring entry points: shape-specialized (a row-count
    change recompiles, by design), value-stable, no host callbacks."""
    import jax

    from photon_tpu.evaluation.evaluators import auc_roc, rmse
    from photon_tpu.models.glm import Coefficients

    def sds(*shape):
        return jax.ShapeDtypeStruct(shape, "float32")

    def tr_eval(name, fn, n) -> TracedProgram:
        return trace_program(name, fn, sds(n), sds(n))

    def score(w, x):
        from photon_tpu.data.dataset import DenseFeatures

        return Coefficients(means=w).compute_score(DenseFeatures(x))

    base_auc = tr_eval("auc", auc_roc, 256)
    base_rmse = tr_eval("rmse", rmse, 256)
    scoring = trace_program("fixed_effect_score", score, sds(5), sds(256, 5))
    return ContractTrace(
        programs={
            "auc": base_auc,
            "rmse": base_rmse,
            "fixed_effect_score": scoring,
        },
        variants={
            "row_count": [
                {
                    "auc": tr_eval("auc", auc_roc, 512).signature,
                    "rmse": tr_eval("rmse", rmse, 512).signature,
                }
            ],
        },
    )


def build_pilot() -> ContractTrace:
    """The pilot's zero-recompile promotion contract.

    A promotion cycle's serving-side effect is exactly one call into
    the reload path (``MicroBatchQueue.reload_model`` →
    ``CoefficientTables.rebuild_from``, which short-circuits a
    values-only delta to the in-place reference swap). Proof: a live
    ladder's rungs are traced as the base programs; then TWO
    consecutive day-over-day promotions — refreshed coefficient VALUES
    on the same structure, the pinned-vocabulary steady state the pilot
    maintains — drive that same swap, and every post-promotion trace
    must be byte-identical to its rung's base program. The census bound
    is the rung count: a control loop that minted even one program per
    promotion would fail the round it shipped. The ``hot_loop`` walk
    applies too: supervision must add no callback to the request path.
    """
    import numpy as np

    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables
    from photon_tpu.types import TaskType

    d, e, s, du = 5, 6, 3, 5
    rng = np.random.default_rng(20260804)

    def day_model(scale: float) -> GameModel:
        # Fixed projector/vocabulary across "days" — the pinned-vocab
        # steady state every pilot promotion relies on.
        prng = np.random.default_rng(99)
        proj = np.sort(
            np.stack([prng.permutation(du)[:s] for _ in range(e)]),
            axis=1,
        ).astype(np.int64)
        return GameModel({
            "global": FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(means=jnp.asarray(
                        scale * rng.normal(size=d).astype(np.float32)
                    )),
                    TaskType.LOGISTIC_REGRESSION,
                ),
                "features",
            ),
            "per-user": RandomEffectModel(
                coefficients=jnp.asarray(
                    scale * rng.normal(size=(e, s)).astype(np.float32)
                ),
                random_effect_type="userId",
                feature_shard_id="userShard",
                task=TaskType.LOGISTIC_REGRESSION,
                proj_all=proj,
                entity_keys=tuple(str(i) for i in range(e)),
            ),
        })

    ladder = ShapeLadder((1, 8))
    tables = CoefficientTables.from_game_model(day_model(1.0))
    programs = ScorePrograms(tables, ladder=ladder, compile_now=False)

    def trace_rungs() -> dict[str, TracedProgram]:
        out = {}
        for r in ladder.rungs:
            traced = programs.trace(r)
            out[f"score_b{r}"] = TracedProgram(
                name=f"score_b{r}",
                text=str(traced.jaxpr),
                jaxpr=traced.jaxpr,
                lowered=traced.lower(),
            )
        return out

    base = trace_rungs()
    variants: dict[str, list[dict[str, str]]] = {"promotion_cycle": []}
    for scale in (1.7, 0.6):  # two consecutive "days"
        # The pilot's PROMOTE serving swap: rebuild_from short-circuits
        # the values-only delta to the in-place reference swap (the
        # exact call chain under MicroBatchQueue.reload_model). Were
        # the refresh NOT values-only, the re-trace below would mint
        # new signatures and fail the stability check — which is the
        # finding this contract exists to catch.
        tables.rebuild_from(day_model(scale), programs=None)
        variants["promotion_cycle"].append({
            name: prog.signature
            for name, prog in trace_rungs().items()
        })
    return ContractTrace(
        programs=base,
        variants=variants,
        notes=[
            f"2 consecutive values-only promotions over ladder "
            f"{ladder.rungs}: every post-promotion trace is "
            "byte-identical to its rung's base program — the control "
            "loop adds zero serving programs",
        ],
    )


_BUILDERS: dict[str, Callable[[], ContractTrace]] = {
    "build_fused_fit": build_fused_fit,
    "build_fused_cache_keys": build_fused_cache_keys,
    "build_unfused_update": build_unfused_update,
    "build_newton_kernel": build_newton_kernel,
    "build_segment_reduce": build_segment_reduce,
    "build_serve_kernel": build_serve_kernel,
    "build_mesh_sharding": build_mesh_sharding,
    "build_ingest_pipeline": build_ingest_pipeline,
    "build_telemetry": build_telemetry,
    "build_trace": build_trace,
    "build_fleet": build_fleet,
    "build_health": build_health,
    "build_ledger": build_ledger,
    "build_monitor": build_monitor,
    "build_pilot": build_pilot,
    "build_serving": build_serving,
    "build_resilience": build_resilience,
    "build_streaming_ingest": build_streaming_ingest,
    "build_evaluators": build_evaluators,
}

# Contracts owned by the analysis tier itself (no better home module).
_LOCAL_AUDITS = (
    dict(
        name="evaluation-scoring",
        entry="evaluation.evaluators.auc_roc / rmse; "
        "models.glm.Coefficients.compute_score",
        builder="build_evaluators",
        max_programs=3,
        recompiles_on=("row_count",),
        hot_loop=True,
    ),
)


def contract_from_declaration(spec: dict) -> ProgramContract:
    builder = spec.get("builder")
    if builder not in _BUILDERS:
        raise ValueError(
            f"PROGRAM_AUDIT declaration {spec.get('name')!r} names unknown "
            f"builder {builder!r}"
        )
    return ProgramContract(
        name=spec["name"],
        entry=spec["entry"],
        build=_BUILDERS[builder],
        max_programs=spec.get("max_programs"),
        stable_under=tuple(spec.get("stable_under", ())),
        recompiles_on=tuple(spec.get("recompiles_on", ())),
        hot_loop=bool(spec.get("hot_loop", False)),
        sharded_operands=tuple(spec.get("sharded_operands", ())),
        replicated_operands=tuple(spec.get("replicated_operands", ())),
        axis=spec.get("axis"),
        allowed_collectives=tuple(spec.get("allowed_collectives", ())),
        suppress=dict(spec.get("suppress", {})),
    )


def collect_contracts() -> list[ProgramContract]:
    """The repo's declared contract registry (module hooks + local)."""
    specs: list[dict] = []
    for modname in DECLARING_MODULES:
        mod = importlib.import_module(modname)
        decl = getattr(mod, "PROGRAM_AUDIT", None)
        if decl is None:
            raise ValueError(
                f"{modname} is a declaring module but exports no "
                "PROGRAM_AUDIT"
            )
        specs.extend(decl if isinstance(decl, (list, tuple)) else [decl])
    specs.extend(_LOCAL_AUDITS)
    return [contract_from_declaration(s) for s in specs]


# --------------------------------------------------------------------------
# the audit driver
# --------------------------------------------------------------------------


@contextlib.contextmanager
def _serial_ingest_env():
    saved = os.environ.get("PHOTON_TPU_SERIAL_INGEST")
    os.environ["PHOTON_TPU_SERIAL_INGEST"] = "1"
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop("PHOTON_TPU_SERIAL_INGEST", None)
        else:
            os.environ["PHOTON_TPU_SERIAL_INGEST"] = saved


def _ensure_virtual_devices() -> None:
    """Give the sharding audit a multi-device CPU platform when possible.

    Only effective before jax initializes; harmless on real accelerators
    (the flag only affects the host platform)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def audit(
    contracts: Iterable[ProgramContract] | None = None,
    *,
    with_cost: bool = True,
    chip: str | None = None,
) -> tuple[list[Finding], dict]:
    """Run every contract; returns (findings, report).

    The registry builds run under ``disable_x64`` so the audited traces
    match the production (f32) configuration even when the host process
    enabled x64 (the test harness does).
    """
    _ensure_virtual_devices()
    from jax.experimental import disable_x64

    from photon_tpu.analysis import costmodel

    if chip is None:
        chip = costmodel.DEFAULT_CHIP
    findings: list[Finding] = []
    report: dict[str, Any] = {"contracts": {}}
    # Serial ingest for the whole audit: contract builds must be
    # deterministic, and the estimator fixtures would otherwise spawn
    # background warm compiles nobody consumes (the ingest-pipeline
    # contract invokes the warm compile explicitly, synchronously).
    with disable_x64(), _serial_ingest_env():
        resolved = (
            collect_contracts() if contracts is None else list(contracts)
        )
        for contract in resolved:
            entry: dict[str, Any] = {
                "entry": contract.entry,
                "programs": {},
                "notes": [],
            }
            report["contracts"][contract.name] = entry
            try:
                trace = contract.build()
            except Exception as exc:  # noqa: BLE001 — any builder crash is a finding
                findings.append(
                    _finding(
                        contract,
                        "program-contract",
                        f"contract builder failed: {exc!r}",
                    )
                )
                continue
            entry["notes"] = list(trace.notes)
            for name, prog in trace.programs.items():
                pentry: dict[str, Any] = {"signature": prog.signature}
                if with_cost and prog.lowered is not None:
                    try:
                        pentry["cost"] = costmodel.program_report(
                            prog.lowered, chip
                        )
                    except Exception as exc:  # noqa: BLE001
                        pentry["cost_error"] = repr(exc)
                entry["programs"][name] = pentry
            if trace.opshardings is not None:
                entry["opshardings"] = dict(trace.opshardings)
                entry["collectives"] = list(trace.collectives or ())
            findings.extend(run_checks(contract, trace))
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings, report
