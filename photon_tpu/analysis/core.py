"""Framework core: findings, the rule registry, suppressions, drivers.

The analyzer is a plain ``ast`` pass — no imports of the analyzed code, no
JAX at analysis time — so it runs in milliseconds over the whole package
and can gate CI on machines with no accelerator. Rules register themselves
via :func:`rule`; each receives a parsed :class:`ModuleContext` and yields
:class:`Finding`s. Suppressions are per-line comments::

    x = bad_thing()  # photon: ignore[rule-id] -- why this is fine here

A reason after ``--`` (or ``:``) is strongly encouraged; ``ignore[*]``
silences every rule on the line. Suppressed findings are retained (with
``suppressed=True``) so reporters can audit them.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Iterable, Iterator

_SUPPRESS_RE = re.compile(
    r"#\s*photon:\s*ignore\[([^\]]*)\]\s*(?:(?:--|:)\s*(?P<reason>.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: a rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: str | None = None

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col + 1}: "
            f"[{self.rule}] {self.message}{tag}"
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: frozenset[str]  # {"*"} means every rule
    reason: str | None

    def covers(self, rule_id: str) -> bool:
        return "*" in self.rules or rule_id in self.rules


class ModuleContext:
    """One parsed source file plus the shared per-file indexes rules need.

    ``parents`` maps every AST node to its parent; ``imports`` maps local
    alias -> canonical dotted module path (``np`` -> ``numpy``,
    ``lax`` -> ``jax.lax``). ``resolve`` expands an attribute/name chain to
    its canonical dotted path, or None when the root isn't an import.
    """

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.imports = _collect_imports(tree)
        self.suppressions = _collect_suppressions(source)
        self._resolve_cache: dict[ast.AST, str | None] = {}

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted path for a Name/Attribute chain, else None."""
        if node in self._resolve_cache:
            return self._resolve_cache[node]
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        out = None
        if isinstance(cur, ast.Name):
            root = self.imports.get(cur.id)
            if root is not None:
                out = ".".join([root, *reversed(parts)])
        self._resolve_cache[node] = out
        return out

    def parent_chain(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        for anc in self.parent_chain(node):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return anc
        return None


def _collect_imports(tree: ast.Module) -> dict[str, str]:
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                table[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for alias in node.names:
                table[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    return table


def _collect_suppressions(source: str) -> dict[int, Suppression]:
    """Suppressions from COMMENT tokens only — a ``photon: ignore``
    sequence inside a string literal must not silence findings."""
    out: dict[int, Suppression] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [
            (tok.start[0], tok.string)
            for tok in tokens
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # unparseable source is reported as syntax-error
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        reason = m.group("reason")
        out[lineno] = Suppression(
            rules=rules or frozenset({"*"}),
            reason=reason.strip() if reason else None,
        )
    return out


# --------------------------------------------------------------------------
# rule registry
# --------------------------------------------------------------------------

RuleFn = Callable[[ModuleContext], Iterable[Finding]]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    fn: RuleFn


_REGISTRY: dict[str, Rule] = {}


def rule(rule_id: str, summary: str) -> Callable[[RuleFn], RuleFn]:
    """Register ``fn`` as the implementation of ``rule_id``."""

    def deco(fn: RuleFn) -> RuleFn:
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id: {rule_id}")
        _REGISTRY[rule_id] = Rule(id=rule_id, summary=summary, fn=fn)
        return fn

    return deco


def registered_rules() -> dict[str, Rule]:
    from photon_tpu.analysis import rules as _rules  # noqa: F401  (registers)

    return dict(_REGISTRY)


# --------------------------------------------------------------------------
# drivers
# --------------------------------------------------------------------------


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Iterable[str] | None = None,
) -> list[Finding]:
    """All findings for one source blob, suppressions applied (not dropped)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                message=f"file does not parse: {exc.msg}",
            )
        ]
    ctx = ModuleContext(path, source, tree)
    active = registered_rules()
    if select is not None:
        wanted = set(select)
        unknown = wanted - set(active)
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        active = {k: v for k, v in active.items() if k in wanted}
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for r in active.values():
        for f in r.fn(ctx):
            # A nested def can be reached twice (as its own jit scope and
            # through the enclosing scope's walk): identical findings
            # collapse to one.
            key = (f.rule, f.line, f.col, f.message)
            if key in seen:
                continue
            seen.add(key)
            sup = ctx.suppressions.get(f.line)
            if sup is not None and sup.covers(f.rule):
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=sup.reason
                )
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(
    path: str | Path, select: Iterable[str] | None = None
) -> list[Finding]:
    p = Path(path)
    return analyze_source(
        p.read_text(encoding="utf-8"), path=str(p), select=select
    )


def iter_python_files(paths: Iterable[str | Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def analyze_paths(
    paths: Iterable[str | Path], select: Iterable[str] | None = None
) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(analyze_file(f, select=select))
    return findings
