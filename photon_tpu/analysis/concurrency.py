"""Tier-3 semantic auditor: host-concurrency contracts for photon_tpu.

Tier 1 reads source text and tier 2 reads traced programs; this tier
audits the THREADED HOST RUNTIME that PRs 3 and 4 introduced — the
ingest plan/chunk pools and the background AOT-compile thread
(``data/pipeline.py``), the per-estimator priming pool
(``estimators/game_estimator.py``), and the lock-guarded telemetry and
event state (``obs/``, ``events.py``, ``utils/compile_cache.py``). The
runtime hammer tests are weak race detectors on a 2-core CI box; this
pass is the static complement, in the spirit of Eraser's lockset
algorithm: every module declares a ``CONCURRENCY_AUDIT`` contract naming
its locks, the state each lock guards, and its thread-entry points, and
the auditor checks the discipline purely from the AST — no imports of
the audited code, no execution, no JAX.

Rules (the registry is ``CONCURRENCY_RULES``):

- ``unlocked-shared-write`` — a write (assignment, augmented assignment,
  or mutating method call, including through a local alias) to state the
  contract declares lock-guarded, outside a ``with <lock>:`` scope.
  ``__init__`` bodies and module top level are exempt (pre-publication).
- ``blocking-under-lock`` — a blocking operation while a lock is held:
  ``jax.block_until_ready`` / ``jax.device_put`` / ``np.asarray`` (a
  potential device fetch), ``Future.result()``, ``open()``,
  ``time.sleep``, executor ``shutdown``, a no-arg ``.join()``, or an XLA
  ``.compile()``. Everything queued behind the lock inherits the wait.
- ``lock-order-hazard`` — two locks acquired in inconsistent nesting
  order in different places in the module (the classic AB/BA deadlock).
- ``dropped-future`` — an ``executor.submit(...)`` whose Future is
  discarded (bare statement) or bound to a name that is never used: the
  thunk's exception can never be observed.
- ``thread-hygiene`` — a ``ThreadPoolExecutor`` without a bounded
  ``max_workers``, an executor that is neither context-managed nor ever
  ``shutdown`` in the module, or a non-daemon ``threading.Thread`` the
  module never joins.
- ``jax-dispatch-off-thread`` — a jit/trace/compile entry (``jax.jit``,
  ``.trace``/``.lower``/``.compile``, ``aot_compile``,
  ``jax.block_until_ready``, ``jax.device_put``) inside a callable the
  module hands to an executor or thread, unless the contract's
  ``jax_dispatch_ok`` declares that entry safe with a written reason.
- ``concurrency-contract`` — contract integrity: modules that create
  locks/threads/executors must declare a contract; declared locks,
  guarded state, thread entries, and ``jax_dispatch_ok`` names must all
  still exist (stale declarations are findings); locks created but not
  declared are findings; ``jax_dispatch_ok`` entries need a reason.

Contract schema (plain data next to the code it constrains, mirroring
``PROGRAM_AUDIT``; parsed from the AST, never imported)::

    CONCURRENCY_AUDIT = dict(
        name="obs-metrics",
        locks={
            # lock -> the state it guards. "Class._attr" for instance
            # state, a bare name for module globals.
            "MetricsRegistry._lock": (
                "MetricsRegistry._counters",
                "MetricsRegistry._gauges",
                "MetricsRegistry._histograms",
            ),
        },
        thread_entries=("_Counter.inc",),  # runs on non-main threads
        jax_dispatch_ok={},                # entry -> why it is safe
    )

Suppressions are the tier-1 per-line mechanism unchanged
(``# photon: ignore[rule] -- reason``); findings reuse
:class:`photon_tpu.analysis.core.Finding`, so the text/JSON reporters
work as-is. Known limits (documented, fixture-tested where they bite):
lock identity is by terminal attribute name within one module — sound
because ``concurrency-contract`` FLAGS ambiguous terminals (two locks
both named ``_lock``) instead of silently mismatching; the write check
is intraprocedural; and a thunk reaching a pool through a variable
(``pool.submit(t) for t in thunks``) is not traced to its definition.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Iterator

from photon_tpu.analysis.core import (
    Finding,
    ModuleContext,
    iter_python_files,
)

CONCURRENCY_RULES: dict[str, str] = {
    "unlocked-shared-write": (
        "write to contract-declared lock-guarded state outside a "
        "`with <lock>` scope"
    ),
    "blocking-under-lock": (
        "blocking call (device sync/transfer, Future.result, file I/O, "
        "sleep, shutdown, compile) while holding a lock"
    ),
    "lock-order-hazard": (
        "two locks acquired in inconsistent nesting order across the "
        "module (AB/BA deadlock shape)"
    ),
    "dropped-future": (
        "executor.submit(...) whose Future is discarded — the thunk's "
        "exception can never be observed"
    ),
    "thread-hygiene": (
        "unbounded or never-shut-down executor, or a non-daemon thread "
        "the module never joins"
    ),
    "jax-dispatch-off-thread": (
        "jit/trace/compile entry inside a submitted thunk without a "
        "declared jax_dispatch_ok reason"
    ),
    "concurrency-contract": (
        "CONCURRENCY_AUDIT missing or stale (declared lock/state/entry "
        "no longer exists, or created lock undeclared)"
    ),
}

_LOCK_FACTORIES = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "threading.Semaphore",
        "threading.BoundedSemaphore",
    }
)
_EXECUTOR_FACTORIES = frozenset(
    {
        "concurrent.futures.ThreadPoolExecutor",
        "concurrent.futures.thread.ThreadPoolExecutor",
        "concurrent.futures.ProcessPoolExecutor",
    }
)
_THREAD_FACTORIES = frozenset({"threading.Thread", "threading.Timer"})

# Mutating container methods: a call through a guarded name (or an alias
# of one) counts as a write for the lockset check.
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "insert",
        "remove",
        "pop",
        "popleft",
        "popitem",
        "clear",
        "update",
        "setdefault",
        "add",
        "discard",
        "move_to_end",
    }
)

_BLOCKING_PATHS = {
    "jax.block_until_ready": "jax.block_until_ready (device sync)",
    "jax.device_put": "jax.device_put (host->device transfer)",
    "jax.device_get": "jax.device_get (device->host transfer)",
    "numpy.asarray": "np.asarray (device fetch if the value lives on "
    "device, large copy otherwise)",
    "numpy.array": "np.array (device fetch if the value lives on "
    "device, large copy otherwise)",
    "time.sleep": "time.sleep",
    "concurrent.futures.wait": "concurrent.futures.wait",
}
# Attribute calls that block regardless of what object they hang off
# (matched when the dotted path does not resolve to an import).
_BLOCKING_ATTRS = {
    "result": "Future.result() (blocks until the thunk finishes)",
    "block_until_ready": "block_until_ready (device sync)",
    "shutdown": "executor shutdown (waits for queued work by default)",
    "compile": "XLA compile (seconds of wall-clock)",
}

_JAX_ENTRY_PATHS = frozenset(
    {
        "jax.jit",
        "jax.pjit",
        "jax.eval_shape",
        "jax.vmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.block_until_ready",
        "jax.device_put",
    }
)
_JAX_ENTRY_ATTRS = frozenset({"trace", "lower", "compile"})


# --------------------------------------------------------------------------
# contract parsing (pure AST — the audited module is never imported)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ConcurrencyContract:
    """One module's declared concurrency model."""

    name: str
    locks: dict[str, tuple[str, ...]]  # lock -> guarded state names
    thread_entries: tuple[str, ...] = ()
    jax_dispatch_ok: dict[str, str] = dataclasses.field(
        default_factory=dict
    )
    line: int = 0

    def guarded(self) -> dict[str, str]:
        """Terminal guarded-state name -> terminal lock name."""
        out: dict[str, str] = {}
        for lock, states in self.locks.items():
            for s in states:
                out[_terminal(s)] = _terminal(lock)
        return out


def _terminal(name: str) -> str:
    return name.split(".")[-1]


class _ContractError(ValueError):
    pass


def _literal(node: ast.AST):
    """Evaluate the restricted literal forms a contract may use:
    constants, dict/list/tuple/set displays, and ``dict(...)`` calls."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Dict):
        return {
            _literal(k): _literal(v)
            for k, v in zip(node.keys, node.values)
        }
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        out = [_literal(e) for e in node.elts]
        return set(out) if isinstance(node, ast.Set) else tuple(out)
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "dict"
        and not node.args
    ):
        return {kw.arg: _literal(kw.value) for kw in node.keywords}
    raise _ContractError(
        f"unsupported expression in CONCURRENCY_AUDIT at line "
        f"{getattr(node, 'lineno', '?')}: {ast.dump(node)[:60]}"
    )


def parse_contract(
    tree: ast.Module,
) -> tuple[ConcurrencyContract | None, str | None]:
    """The module's CONCURRENCY_AUDIT declaration, or (None, error)."""
    for node in tree.body:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
            if isinstance(node, ast.AnnAssign) and node.value
            else []
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "CONCURRENCY_AUDIT"
            for t in targets
        ):
            continue
        try:
            raw = _literal(node.value)
            if not isinstance(raw, dict):
                raise _ContractError("CONCURRENCY_AUDIT must be a dict")
            name = raw.get("name")
            if not isinstance(name, str) or not name:
                raise _ContractError("contract needs a non-empty `name`")
            locks = {
                str(k): tuple(str(s) for s in v)
                for k, v in dict(raw.get("locks") or {}).items()
            }
            return (
                ConcurrencyContract(
                    name=name,
                    locks=locks,
                    thread_entries=tuple(
                        str(t) for t in raw.get("thread_entries") or ()
                    ),
                    jax_dispatch_ok={
                        str(k): str(v)
                        for k, v in dict(
                            raw.get("jax_dispatch_ok") or {}
                        ).items()
                    },
                    line=node.lineno,
                ),
                None,
            )
        except _ContractError as exc:
            return None, str(exc)
    return None, None


# --------------------------------------------------------------------------
# module model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ModuleModel:
    """Everything the rules need, extracted in one walk."""

    ctx: ModuleContext
    contract: ConcurrencyContract | None
    contract_error: str | None
    # qualified lock name ("Class._lock" / "_lock") -> creation node
    lock_defs: dict[str, ast.AST]
    executor_calls: list[ast.Call]
    thread_calls: list[ast.Call]
    submit_calls: list[ast.Call]
    # every def/lambda in the module by terminal name (methods included)
    defs: dict[str, ast.AST]
    has_shutdown_call: bool
    has_join_call: bool

    @property
    def lock_terminals(self) -> frozenset[str]:
        names = {_terminal(n) for n in self.lock_defs}
        if self.contract:
            names.update(_terminal(n) for n in self.contract.locks)
        return frozenset(names)


def _enclosing_class(ctx: ModuleContext, node: ast.AST) -> str | None:
    for anc in ctx.parent_chain(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
        if isinstance(anc, ast.Module):
            return None
    return None


def build_model(ctx: ModuleContext) -> ModuleModel:
    contract, err = parse_contract(ctx.tree)
    lock_defs: dict[str, ast.AST] = {}
    executor_calls: list[ast.Call] = []
    thread_calls: list[ast.Call] = []
    submit_calls: list[ast.Call] = []
    defs: dict[str, ast.AST] = {}
    has_shutdown = has_join = False
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.Call):
            path = ctx.resolve(node.func)
            if path in _LOCK_FACTORIES:
                parent = ctx.parents.get(node)
                target = None
                if isinstance(parent, (ast.Assign, ast.AnnAssign)):
                    tgts = (
                        parent.targets
                        if isinstance(parent, ast.Assign)
                        else [parent.target]
                    )
                    for t in tgts:
                        if isinstance(t, ast.Attribute):
                            cls = _enclosing_class(ctx, node)
                            target = (
                                f"{cls}.{t.attr}" if cls else t.attr
                            )
                        elif isinstance(t, ast.Name):
                            cls = _enclosing_class(ctx, node)
                            target = (
                                f"{cls}.{t.id}" if cls else t.id
                            )
                lock_defs[target or f"<anonymous@{node.lineno}>"] = node
            elif path in _EXECUTOR_FACTORIES:
                executor_calls.append(node)
            elif path in _THREAD_FACTORIES:
                thread_calls.append(node)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "submit"
                and ctx.resolve(node.func) is None
            ):
                submit_calls.append(node)
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "shutdown":
                    has_shutdown = True
                elif node.func.attr == "join" and not node.args:
                    has_join = True
    return ModuleModel(
        ctx=ctx,
        contract=contract,
        contract_error=err,
        lock_defs=lock_defs,
        executor_calls=executor_calls,
        thread_calls=thread_calls,
        submit_calls=submit_calls,
        defs=defs,
        has_shutdown_call=has_shutdown,
        has_join_call=has_join,
    )


# --------------------------------------------------------------------------
# lock-scope helpers
# --------------------------------------------------------------------------


def _lock_expr_terminal(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def held_locks(model: ModuleModel, node: ast.AST) -> list[str]:
    """Terminal names of module locks held at ``node`` (lexically:
    the ``with`` statements on the ancestor chain whose context
    expression names a known lock), outermost first."""
    held: list[str] = []
    for anc in model.ctx.parent_chain(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                t = _lock_expr_terminal(item.context_expr)
                if t is not None and t in model.lock_terminals:
                    held.append(t)
    held.reverse()
    return held


def _finding(
    ctx: ModuleContext, rule_id: str, node: ast.AST, message: str
) -> Finding:
    return Finding(
        rule=rule_id,
        path=ctx.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
    )


# --------------------------------------------------------------------------
# rule: unlocked-shared-write
# --------------------------------------------------------------------------


def _guarded_aliases(
    model: ModuleModel, func: ast.AST, guarded: dict[str, str]
) -> dict[str, str]:
    """Local names assigned directly from a guarded attribute/global
    inside ``func`` — writes through them count as writes to the state."""
    aliases: dict[str, str] = {}
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        src = node.value
        name = None
        if isinstance(src, ast.Attribute):
            name = src.attr
        elif isinstance(src, ast.Name):
            name = src.id
        if name in guarded:
            aliases[tgt.id] = name
    return aliases


def _write_targets(node: ast.AST) -> Iterator[ast.AST]:
    """The target expressions a statement writes to (flattening tuple
    unpacking), or the base of a mutating method call."""
    if isinstance(node, ast.Assign):
        stack = list(node.targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            else:
                yield t
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        yield node.target
    elif (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _MUTATORS
    ):
        yield node.func.value


def _written_name(target: ast.AST) -> str | None:
    """Terminal state name a write target refers to: ``x._attr``,
    bare ``name``, or a subscript on either."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


def check_unlocked_shared_write(model: ModuleModel) -> Iterator[Finding]:
    if model.contract is None or not model.contract.locks:
        return
    guarded = model.contract.guarded()
    ctx = model.ctx
    alias_cache: dict[ast.AST, dict[str, str]] = {}
    for node in ast.walk(ctx.tree):
        for target in _write_targets(node):
            name = _written_name(target)
            if name is None:
                continue
            func = ctx.enclosing_function(node)
            if func is None:
                continue  # import-time initialization, pre-threads
            if getattr(func, "name", "") == "__init__":
                continue  # the object is not yet published
            state = None
            if name in guarded and (
                isinstance(
                    target.value
                    if isinstance(target, ast.Subscript)
                    else target,
                    ast.Attribute,
                )
                or _is_module_global(guarded, name, model)
            ):
                state = name
            else:
                if func not in alias_cache:
                    alias_cache[func] = _guarded_aliases(
                        model, func, guarded
                    )
                state = alias_cache[func].get(name)
                if state is not None and not isinstance(
                    target, (ast.Subscript,)
                ) and not (
                    isinstance(node, ast.Call)
                ):
                    # Rebinding the alias itself is not a shared write.
                    state = None
            if state is None:
                continue
            want = guarded[state]
            if want in held_locks(model, node):
                continue
            yield _finding(
                ctx,
                "unlocked-shared-write",
                node,
                f"write to `{state}` (declared guarded by `{want}` in "
                f"CONCURRENCY_AUDIT) outside a `with {want}` scope",
            )


def _is_module_global(
    guarded: dict[str, str], name: str, model: ModuleModel
) -> bool:
    """True when the contract declares ``name`` as a bare module-level
    global (no class qualifier) — a bare Name write then counts."""
    if model.contract is None:
        return False
    for states in model.contract.locks.values():
        for s in states:
            if s == name and "." not in s:
                return True
    return False


# --------------------------------------------------------------------------
# rule: blocking-under-lock
# --------------------------------------------------------------------------


def check_blocking_under_lock(model: ModuleModel) -> Iterator[Finding]:
    ctx = model.ctx
    if not model.lock_terminals:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        held = held_locks(model, node)
        if not held:
            continue
        path = ctx.resolve(node.func)
        why = None
        if path in _BLOCKING_PATHS:
            why = _BLOCKING_PATHS[path]
        elif isinstance(node.func, ast.Name) and node.func.id == "open":
            why = "open() (file I/O)"
        elif isinstance(node.func, ast.Attribute) and path is None:
            attr = node.func.attr
            if attr == "join" and node.args:
                pass  # str.join(iterable) — not a thread join
            elif attr in _BLOCKING_ATTRS:
                why = _BLOCKING_ATTRS[attr]
        if why is None:
            continue
        yield _finding(
            ctx,
            "blocking-under-lock",
            node,
            f"{why} while holding `{held[-1]}`: every thread queued on "
            "the lock inherits this wait; move the blocking call "
            "outside the critical section",
        )


# --------------------------------------------------------------------------
# rule: lock-order-hazard
# --------------------------------------------------------------------------


def check_lock_order(model: ModuleModel) -> Iterator[Finding]:
    ctx = model.ctx
    if len(model.lock_terminals) < 2:
        return
    # (outer, inner) -> first acquisition site exhibiting that order
    orders: dict[tuple[str, str], ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        inner = [
            t
            for item in node.items
            if (t := _lock_expr_terminal(item.context_expr)) is not None
            and t in model.lock_terminals
        ]
        if not inner:
            continue
        outer = held_locks(model, node)
        for o in outer:
            for i in inner:
                if o != i:
                    orders.setdefault((o, i), node)
    reported: set[frozenset[str]] = set()
    for (o, i), node in sorted(
        orders.items(), key=lambda kv: kv[1].lineno
    ):
        if (i, o) in orders and frozenset((o, i)) not in reported:
            reported.add(frozenset((o, i)))
            other = orders[(i, o)]
            yield _finding(
                ctx,
                "lock-order-hazard",
                node,
                f"locks `{o}` and `{i}` are acquired in both orders "
                f"(here `{o}`->`{i}`; line {other.lineno} takes "
                f"`{i}`->`{o}`): two threads taking opposite orders "
                "deadlock; pick one global order",
            )


# --------------------------------------------------------------------------
# rule: dropped-future
# --------------------------------------------------------------------------


def check_dropped_future(model: ModuleModel) -> Iterator[Finding]:
    ctx = model.ctx
    for call in model.submit_calls:
        parent = ctx.parents.get(call)
        if isinstance(parent, ast.Expr):
            yield _finding(
                ctx,
                "dropped-future",
                call,
                "submit(...) as a bare statement: the Future (and any "
                "exception the thunk raises) is dropped on the floor; "
                "keep it and consume .result()",
            )
            continue
        if (
            isinstance(parent, ast.Assign)
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)
        ):
            name = parent.targets[0].id
            func = ctx.enclosing_function(call) or ctx.tree
            used = any(
                isinstance(n, ast.Name)
                and n.id == name
                and isinstance(n.ctx, ast.Load)
                for n in ast.walk(func)
            )
            if not used:
                yield _finding(
                    ctx,
                    "dropped-future",
                    call,
                    f"Future bound to `{name}` is never consumed: the "
                    "thunk's exception can never be observed; call "
                    ".result() (or .exception()) on every submitted "
                    "Future",
                )


# --------------------------------------------------------------------------
# rule: thread-hygiene
# --------------------------------------------------------------------------


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def check_thread_hygiene(model: ModuleModel) -> Iterator[Finding]:
    ctx = model.ctx
    for call in model.executor_calls:
        if _kw(call, "max_workers") is None and not call.args:
            yield _finding(
                ctx,
                "thread-hygiene",
                call,
                "executor without a bounded max_workers: the default "
                "scales with the host's cores and oversubscribes the "
                "2-core CI box; pass an explicit bound",
            )
        parent = ctx.parents.get(call)
        managed = isinstance(parent, ast.withitem)
        if not managed and not model.has_shutdown_call:
            yield _finding(
                ctx,
                "thread-hygiene",
                call,
                "executor is neither context-managed nor ever shut "
                "down in this module: worker threads (and queued "
                "thunks) outlive every error path; use `with` or "
                "guarantee shutdown() in a finally",
            )
    for call in model.thread_calls:
        daemon = _kw(call, "daemon")
        is_daemon = (
            isinstance(daemon, ast.Constant) and daemon.value is True
        )
        if not is_daemon and not model.has_join_call:
            yield _finding(
                ctx,
                "thread-hygiene",
                call,
                "non-daemon Thread that this module never joins: the "
                "process cannot exit while it runs and its exceptions "
                "vanish; join it or mark daemon=True deliberately",
            )


# --------------------------------------------------------------------------
# rule: jax-dispatch-off-thread
# --------------------------------------------------------------------------


def _submitted_callables(
    model: ModuleModel,
) -> Iterator[tuple[str, ast.AST, ast.AST]]:
    """(name, body-node, submit-site) for every callable the module
    hands to an executor/thread that the AST can link to a definition."""
    ctx = model.ctx
    seen: set[ast.AST] = set()

    def emit(name: str, node: ast.AST, site: ast.AST):
        if node not in seen:
            seen.add(node)
            yield (name, node, site)

    for call in model.submit_calls:
        if not call.args:
            continue
        target = call.args[0]
        if isinstance(target, ast.Lambda):
            yield from emit("<lambda>", target, call)
        elif isinstance(target, ast.Name):
            fn = model.defs.get(target.id)
            if fn is not None:
                yield from emit(target.id, fn, call)
        elif isinstance(target, ast.Attribute):
            if ctx.resolve(target) is None:  # not an imported callable
                fn = model.defs.get(target.attr)
                if fn is not None:
                    yield from emit(target.attr, fn, call)
    for call in model.thread_calls:
        target = _kw(call, "target")
        if isinstance(target, ast.Lambda):
            yield from emit("<lambda>", target, call)
        elif isinstance(target, ast.Name):
            fn = model.defs.get(target.id)
            if fn is not None:
                yield from emit(target.id, fn, call)
    if model.contract:
        for entry in model.contract.thread_entries:
            fn = model.defs.get(_terminal(entry))
            if fn is not None:
                yield from emit(_terminal(entry), fn, fn)


def check_jax_dispatch_off_thread(
    model: ModuleModel,
) -> Iterator[Finding]:
    ctx = model.ctx
    waived = (
        {_terminal(k) for k in model.contract.jax_dispatch_ok}
        if model.contract
        else set()
    )
    for name, body, _site in _submitted_callables(model):
        if name in waived:
            continue
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            path = ctx.resolve(node.func)
            hit = None
            if path in _JAX_ENTRY_PATHS:
                hit = path
            elif path is not None and path.endswith("aot_compile"):
                hit = path
            elif (
                path is None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _JAX_ENTRY_ATTRS
            ):
                hit = f".{node.func.attr}()"
            if hit is None:
                continue
            yield _finding(
                ctx,
                "jax-dispatch-off-thread",
                node,
                f"`{hit}` inside thread-entry `{name}`: jit/trace "
                "entry off the main thread can interleave trace "
                "contexts and rendezvous; declare it safe in "
                "CONCURRENCY_AUDIT jax_dispatch_ok with a reason, or "
                "move the dispatch to the caller",
            )


# --------------------------------------------------------------------------
# rule: concurrency-contract (integrity + staleness)
# --------------------------------------------------------------------------


def _module_mentions(model: ModuleModel, terminal: str) -> bool:
    """Whether ``terminal`` appears as an attribute or bare name
    anywhere in the module (the existence proxy for declared state)."""
    for node in ast.walk(model.ctx.tree):
        if isinstance(node, ast.Attribute) and node.attr == terminal:
            return True
        if isinstance(node, ast.Name) and node.id == terminal:
            return True
    return False


def check_contract(model: ModuleModel) -> Iterator[Finding]:
    ctx = model.ctx
    if model.contract_error:
        yield _finding(
            ctx,
            "concurrency-contract",
            ctx.tree,
            f"CONCURRENCY_AUDIT does not parse: {model.contract_error}",
        )
        return
    machinery = (
        list(model.lock_defs.values())
        + model.executor_calls
        + model.thread_calls
    )
    if model.contract is None:
        if machinery:
            first = min(machinery, key=lambda n: n.lineno)
            yield _finding(
                ctx,
                "concurrency-contract",
                first,
                "module creates locks/threads/executors but declares "
                "no CONCURRENCY_AUDIT contract: name the locks, the "
                "state each guards, and the thread entries",
            )
        return
    c = model.contract
    anchor = _ContractAnchor(ctx, c.line)
    # Ambiguous lock naming breaks the auditor's own identity model
    # (locks are matched by terminal name within a module): two locks
    # both named `_lock` would silently disable the lock-order check
    # and let a write under the WRONG lock satisfy the lockset. Enforce
    # distinct terminals rather than documenting the hole.
    by_terminal: dict[str, list[str]] = {}
    for qual in list(model.lock_defs) + list(c.locks):
        if qual.startswith("<anonymous"):
            continue
        by_terminal.setdefault(_terminal(qual), []).append(qual)
    for terminal, quals in sorted(by_terminal.items()):
        distinct = sorted(set(quals))
        if len(distinct) > 1:
            yield _finding(
                ctx,
                "concurrency-contract",
                anchor,
                f"locks {', '.join(f'`{q}`' for q in distinct)} share "
                f"the terminal name `{terminal}`: the auditor matches "
                "locks by terminal name within a module, so ambiguous "
                "naming disables the lock-order check and weakens the "
                "lockset; rename for distinct terminals",
            )
    created_terminals = {_terminal(n) for n in model.lock_defs}
    for lock in c.locks:
        if _terminal(lock) not in created_terminals:
            yield _finding(
                ctx,
                "concurrency-contract",
                anchor,
                f"declared lock `{lock}` is never created in this "
                "module — the contract went stale",
            )
    for lock, states in c.locks.items():
        for s in states:
            if not _module_mentions(model, _terminal(s)):
                yield _finding(
                    ctx,
                    "concurrency-contract",
                    anchor,
                    f"declared guarded state `{s}` (under `{lock}`) "
                    "does not exist in this module — the contract "
                    "went stale",
                )
    for lock_name, node in model.lock_defs.items():
        if lock_name.startswith("<anonymous"):
            continue
        if not any(
            _terminal(lock_name) == _terminal(d) for d in c.locks
        ):
            yield _finding(
                ctx,
                "concurrency-contract",
                node,
                f"lock `{lock_name}` is created here but not declared "
                "in CONCURRENCY_AUDIT.locks — declare what it guards",
            )
    for entry in c.thread_entries:
        if model.defs.get(_terminal(entry)) is None:
            yield _finding(
                ctx,
                "concurrency-contract",
                anchor,
                f"declared thread entry `{entry}` does not exist in "
                "this module — the contract went stale",
            )
    for entry, reason in c.jax_dispatch_ok.items():
        if model.defs.get(_terminal(entry)) is None:
            yield _finding(
                ctx,
                "concurrency-contract",
                anchor,
                f"jax_dispatch_ok entry `{entry}` does not exist in "
                "this module — the contract went stale",
            )
        if not reason.strip():
            yield _finding(
                ctx,
                "concurrency-contract",
                anchor,
                f"jax_dispatch_ok entry `{entry}` has no reason — the "
                "waiver is part of the contract and must say why the "
                "off-thread dispatch is safe",
            )


class _ContractAnchor:
    """Anchors contract-level findings to the declaration line so the
    per-line suppression mechanism applies to them too."""

    def __init__(self, ctx: ModuleContext, line: int):
        self.lineno = line or 1
        self.col_offset = 0


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

_CHECKS = (
    check_contract,
    check_unlocked_shared_write,
    check_blocking_under_lock,
    check_lock_order,
    check_dropped_future,
    check_thread_hygiene,
    check_jax_dispatch_off_thread,
)


def audit_source(source: str, path: str = "<string>") -> list[Finding]:
    """All tier-3 findings for one source blob, suppressions applied."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                message=f"file does not parse: {exc.msg}",
            )
        ]
    ctx = ModuleContext(path, source, tree)
    model = build_model(ctx)
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for check in _CHECKS:
        for f in check(model):
            key = (f.rule, f.line, f.col, f.message)
            if key in seen:
                continue
            seen.add(key)
            sup = ctx.suppressions.get(f.line)
            if sup is not None and sup.covers(f.rule):
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=sup.reason
                )
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def audit_file(path: str | Path) -> list[Finding]:
    p = Path(path)
    return audit_source(p.read_text(encoding="utf-8"), path=str(p))


def audit_paths(paths: Iterable[str | Path]) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(audit_file(f))
    return findings


def collect_contracts(
    paths: Iterable[str | Path],
) -> dict[str, ConcurrencyContract]:
    """Contract name -> declaration, for reports and tests."""
    out: dict[str, ConcurrencyContract] = {}
    for f in iter_python_files(paths):
        contract, _ = parse_contract(
            ast.parse(f.read_text(encoding="utf-8"), filename=str(f))
        )
        if contract is not None:
            out[contract.name] = contract
    return out


def render_rule_list() -> str:
    width = max(len(r) for r in CONCURRENCY_RULES)
    return "\n".join(
        f"{rid.ljust(width)}  {summary}"
        for rid, summary in CONCURRENCY_RULES.items()
    )
