"""Static per-program cost model: FLOPs / HBM bytes from lowered HLO.

``jax.stages.Lowered.cost_analysis()`` runs XLA's HLO cost analysis over
the *unoptimized* module — no compilation, no device — and returns FLOP
and bytes-accessed counts per program. Dividing by the target chip's
peaks gives a roofline lower bound on runtime per dispatch, which is the
number ``bench.py`` compares measured throughput against
(measured-vs-predicted utilization).

These are COMPILER counts, not the analytic model-FLOP counts in
``bench.py`` (which exclude padding): the two deliberately bracket the
truth — cost_analysis counts every padded lane the program will really
execute, the analytic count only the useful model work.
"""

from __future__ import annotations

import json
import re
from typing import Any, Iterable, Mapping

# Per-chip peaks used for the roofline summary. v5e is the repo's target
# part (bench.py uses the same numbers for measured utilization).
# ``ici_bytes_per_sec`` is the per-chip aggregate inter-chip-interconnect
# bandwidth (v5e: 4 links x ~400 Gb/s); it prices collective transfers —
# the --spmd auditor's implicit-reshard findings — as a per-dispatch
# lower bound the same way hbm_bytes_per_sec prices local traffic.
CHIP_PEAKS = {
    "tpu_v5e": {
        "flops_per_sec": 197e12,
        "hbm_bytes_per_sec": 819e9,
        "ici_bytes_per_sec": 186e9,
    },
}
DEFAULT_CHIP = "tpu_v5e"


def program_cost(lowered: Any) -> dict[str, float]:
    """Normalized cost counters for one lowered program.

    Returns ``{"flops", "hbm_bytes", "transcendentals"}`` (floats, 0.0 for
    counters the backend does not report). ``cost_analysis`` may return a
    dict or a one-element list of dicts depending on the jax version, and
    some backends return None — all normalized here. Backends that omit
    ``bytes accessed`` entirely fall back to the program's operand +
    result aval bytes (a one-pass lower bound — every operand is read
    and every result written at least once) so the roofline row keeps an
    HBM estimate instead of silently degrading to measured-only.
    """
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, Mapping):
        ca = {}
    hbm = ca.get("bytes accessed")
    if hbm is None:
        hbm = _boundary_aval_bytes(lowered)
    return {
        "flops": float(ca.get("flops", 0.0)),
        "hbm_bytes": float(hbm),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
    }


def _boundary_aval_bytes(lowered: Any) -> float:
    """Sum of input + output aval bytes of a lowered program — the
    fallback HBM-traffic floor when the backend's ``cost_analysis``
    reports no ``bytes accessed`` counter."""
    import numpy as np

    def leaf_bytes(info) -> float:
        shape = getattr(info, "shape", None)
        dtype = getattr(info, "dtype", None)
        if shape is None or dtype is None:
            return 0.0
        size = 1
        for dim in shape:
            size *= int(dim)
        return float(size * np.dtype(dtype).itemsize)

    total = 0.0
    for attr in ("args_info", "out_info"):
        tree = getattr(lowered, attr, None)
        if tree is None:
            continue
        import jax

        leaves = jax.tree_util.tree_leaves(
            tree, is_leaf=lambda n: hasattr(n, "shape")
        )
        total += sum(leaf_bytes(leaf) for leaf in leaves)
    return total


def roofline(
    cost: Mapping[str, float], chip: str = DEFAULT_CHIP
) -> dict[str, Any]:
    """Roofline classification of one program's cost counters.

    ``min_seconds`` is the per-dispatch lower bound at the chip's peaks;
    ``bound`` names the resource that sets it. Arithmetic intensity below
    the chip's ridge point (peak_flops / peak_hbm) means HBM-bound — the
    expected regime for GLM training (bench.py module docstring).
    """
    peaks = CHIP_PEAKS[chip]
    flops = float(cost.get("flops", 0.0))
    bytes_ = float(cost.get("hbm_bytes", 0.0))
    t_flops = flops / peaks["flops_per_sec"]
    t_hbm = bytes_ / peaks["hbm_bytes_per_sec"]
    return {
        "chip": chip,
        "arithmetic_intensity": (flops / bytes_) if bytes_ else None,
        "min_seconds_flops": t_flops,
        "min_seconds_hbm": t_hbm,
        "min_seconds": max(t_flops, t_hbm),
        "bound": "flops" if t_flops >= t_hbm else "hbm",
    }


def program_report(
    lowered: Any, chip: str = DEFAULT_CHIP
) -> dict[str, Any]:
    """cost + roofline for one lowered program (bench/report entry)."""
    cost = program_cost(lowered)
    out = dict(cost)
    out["roofline"] = roofline(cost, chip)
    return out


def fused_fit_report(
    fused: Any, coords: dict, chip: str = DEFAULT_CHIP
) -> dict[str, Any]:
    """Per-program predicted cost of one FusedFit generation.

    Lowers (never executes) the whole-fit program and the slab
    materialization program for the given coordinate structure — the two
    dispatches of a fused fit — and returns
    ``{program_name: {flops, hbm_bytes, roofline}}``.
    """
    return {
        "fused_fit": program_report(fused.lower(coords), chip),
        "materialize": program_report(fused.lower_materialize(coords), chip),
    }


# --------------------------------------------------------------------------
# collective-transfer pricing (the --spmd implicit-reshard detector)
# --------------------------------------------------------------------------

# One HLO shape token: dtype[dims] — "f32[128,64]", "bf16[8]", "pred[]".
# Tuple shapes of async collective pairs contain several tokens; summing
# them prices the whole transfer.
_HLO_SHAPE_RE = re.compile(
    r"\b(pred|s8|u8|s16|u16|s32|u32|s64|u64|f8e\w+|bf16|f16|f32|f64"
    r"|c64|c128)\[([0-9,]*)\]"
)

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def hlo_shape_bytes(shape_text: str) -> float:
    """Total bytes of every dtype[dims] token in an HLO shape string.

    Accepts the raw shape region of an instruction line — scalar
    (``f32[]``), array (``f32[128,64]{1,0}``), or tuple
    (``(f32[8]{0}, f32[8]{0})``) — and sums them all; layout annotations
    are ignored. Unknown dtypes (future f8 variants) price at 1 byte —
    an undercount, never a silent zero.
    """
    total = 0.0
    for dtype, dims in _HLO_SHAPE_RE.findall(shape_text):
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * _HLO_DTYPE_BYTES.get(dtype, 1)
    return total


def collective_transfer(
    sequence: Iterable[Mapping[str, str]], chip: str = DEFAULT_CHIP
) -> dict[str, Any]:
    """Price an ordered collective sequence as bytes over the interconnect.

    ``sequence`` is ``spmd.collective_sequence`` output
    (``[{"op", "shape"}, ...]``). Returns per-op bytes, the total, and
    the ICI-bandwidth lower bound per dispatch — the cost an implicit
    compiler-inserted reshard silently adds to every step.
    """
    ops: list[dict[str, Any]] = []
    total = 0.0
    for step in sequence:
        b = hlo_shape_bytes(step.get("shape", ""))
        total += b
        ops.append({"op": step.get("op", "?"), "bytes": b})
    peak = CHIP_PEAKS[chip].get("ici_bytes_per_sec")
    return {
        "chip": chip,
        "ops": ops,
        "total_bytes": total,
        "min_seconds_ici": (total / peak) if peak else None,
    }


def write_report(path: str, report: Mapping[str, Any]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
