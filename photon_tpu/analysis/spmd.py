"""Tier-6 SPMD auditor: static multi-host divergence proofs for the mesh.

Multi-host SPMD bugs are the worst failure class this repo can ship: a
host whose trace diverges (a ``process_index`` baked into a shape, a
clock read in a branch predicate) compiles a DIFFERENT program than its
peers, and the first mismatched collective hangs the whole fleet with no
error on any host. PR 19's fleet ledger can observe such a hang *after*
the fact; this tier exists to make the bug a static CI finding *before*
any device sees the program. Four families of proof:

- **cross-host trace determinism** (``spmd-trace-divergence``): every
  mesh-audited entry point is traced under simulated ``process_index``
  0..N-1 (abstract shapes, no devices — CPU CI is enough) and the jaxprs
  must be byte-identical across hosts. When they are not, the first
  divergent jaxpr line names the guilty op — this is the jaxpr half of
  the host-divergence lint, and the proof that all processes compile the
  same executable.
- **host-divergence lint** (``spmd-host-divergence``): a pure-``ast``
  taint pass flagging host-varying values (``jax.process_index``, clock
  reads, unseeded RNGs, hostname/pid/env reads) flowing into
  trace-affecting positions: array-constructor shapes,
  ``jax.ShapeDtypeStruct`` shapes, and branch predicates inside
  functions that build traced programs. (Recompile-key fields are
  covered dynamically by the cross-host trace hash above: a host-varying
  static arg cannot produce byte-identical jaxprs on two hosts.)
- **collective-order deadlock census** (``spmd-collective-order`` /
  ``spmd-implicit-reshard``): the ORDERED collective sequence
  (all-reduce / all-gather / collective-permute / reduce-scatter ...)
  is extracted from each simulated host's compiled HLO; the sequences
  must match position-by-position across hosts (a mismatch is a static
  deadlock), and every op must be declared in the contract's
  ``ordered_collectives`` — an undeclared op is an implicit reshard the
  compiler inserted behind the author's back, priced as bytes over the
  interconnect via ``costmodel.collective_transfer``. This census is the
  single source of truth the tier-2 mesh audit delegates to
  (``program.hlo_collectives``), and ``obs.fleet`` joins it against the
  runtime collective ledger (``fleet.crosscheck_collective_census``).
- **partition-rule coverage** (``spmd-partition-coverage``): every
  named param/slab pytree leaf the mesh places must be matched by
  EXACTLY one regex partition rule (``parallel.mesh.PARTITION_RULES`` —
  the rule tree ROADMAP item 1's pjit rebuild will feed pjit), the
  placed sharding must agree with the matched rule (a slab the rules
  say to shard that is silently replicated is a finding, not a slow
  day), and every rule must still match at least one leaf (dead rules
  rot).

Contracts are declared beside the audited code as plain ``SPMD_AUDIT``
dicts (``photon_tpu/parallel/mesh.py``), mirroring tiers 2-5; builders
live here so the audited modules never import analysis code. Run via
``python -m photon_tpu.analysis --spmd`` (exit 0 clean, 1 findings, 2
usage); ``--hosts N`` simulates an N-process fleet (CI's multichip-smoke
job runs the 8-device gloo dryrun's 2-host config).
"""

from __future__ import annotations

import ast
import contextlib
import dataclasses
import importlib
import re
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator

from photon_tpu.analysis.core import (
    Finding,
    ModuleContext,
    iter_python_files,
)

SPMD_RULES = {
    "spmd-trace-divergence": (
        "an audited entry point traces to different jaxprs on different "
        "hosts — the fleet would compile divergent programs"
    ),
    "spmd-host-divergence": (
        "a host-varying value (process_index, clock, unseeded RNG, "
        "hostname, env) flows into a trace-affecting position (shape, "
        "ShapeDtypeStruct, branch predicate around trace/jit)"
    ),
    "spmd-collective-order": (
        "the ordered collective sequence differs between hosts' compiled "
        "HLO — the first mismatched collective deadlocks the fleet"
    ),
    "spmd-implicit-reshard": (
        "compiled HLO carries a collective the contract did not declare — "
        "an implicit compiler-inserted reshard paying interconnect bytes "
        "on every dispatch"
    ),
    "spmd-partition-coverage": (
        "a placed pytree leaf is matched by zero or multiple partition "
        "rules, or its placed sharding contradicts the matched rule "
        "(e.g. a slab intended to shard is silently replicated)"
    ),
    "spmd-contract": "contract declaration or builder integrity error",
}

# Modules that declare SPMD contracts (each exports SPMD_AUDIT — one
# declaration dict or a list of them; plain data, no analysis imports).
SPMD_DECLARING_MODULES = ("photon_tpu.parallel.mesh",)

# Tier-2 program contracts that declare mesh semantics (an axis, sharded
# operands, or allowed collectives) must be covered by a tier-6 contract
# (its ``covers`` field) or waived here WITH a reason. A stale waiver —
# naming a tier-2 contract that no longer exists or is now covered — is
# itself a finding, so this table cannot rot silently.
TIER2_SPMD_WAIVERS: dict[str, str] = {}


# --------------------------------------------------------------------------
# the collective census (single source of truth; tier-2 delegates here)
# --------------------------------------------------------------------------

# Cross-device transfer ops as they appear in HLO text. Shared with the
# tier-2 sharding audit via ``program.hlo_collectives`` so the two tiers
# cannot drift.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "reduce-scatter",
    "collective-broadcast",
)

# One HLO instruction whose opcode is a collective:
#   %name = f32[128,64]{1,0} all-gather(%operand), dimensions={0} ...
# The shape region between '=' and the opcode is kept verbatim so
# costmodel.hlo_shape_bytes can price the transfer (tuple shapes of
# async pairs included). '-done' halves of async pairs are skipped —
# the '-start' already carries the transfer.
_COLLECTIVE_LINE_RE = re.compile(
    r"=\s*(?P<shape>[^=]*?)\s*"
    r"(?P<op>" + "|".join(COLLECTIVE_OPS) + r")(?P<phase>-start|-done)?\("
)


def _hlo_text(hlo: Any) -> str:
    return hlo if isinstance(hlo, str) else hlo.as_text()


def collective_sequence(hlo: Any) -> list[dict[str, str]]:
    """The ORDERED collective sequence of an HLO module.

    ``hlo`` is HLO text or anything with ``.as_text()`` (a Compiled or a
    Lowered). Returns ``[{"op", "shape"}, ...]`` in program-text order —
    the static proxy for the issue order every host must agree on. Two
    hosts whose sequences differ at any position deadlock at that
    position: each waits in a different collective.
    """
    out: list[dict[str, str]] = []
    for line in _hlo_text(hlo).splitlines():
        m = _COLLECTIVE_LINE_RE.search(line)
        if m is None or m.group("phase") == "-done":
            continue
        out.append({"op": m.group("op"), "shape": m.group("shape").strip()})
    return out


def collective_census(hlo: Any) -> list[str]:
    """Sorted set of collective op names present in HLO text.

    Deliberately a conservative substring census (an op mentioned
    anywhere counts) — this is the exact check the tier-2 mesh audit has
    gated on since PR 2, now owned here; ``collective_sequence`` is the
    stricter ordered parse layered on top.
    """
    text = _hlo_text(hlo)
    return sorted(op for op in COLLECTIVE_OPS if op in text)


# --------------------------------------------------------------------------
# simulated hosts
# --------------------------------------------------------------------------


@contextlib.contextmanager
def simulated_host(process_index: int, process_count: int):
    """Make ``jax.process_index()/process_count()`` report a simulated
    host while tracing — no distributed runtime, no devices beyond the
    virtual CPU platform. Audited entry points that consult the public
    names see host ``process_index`` of ``process_count``; a value that
    leaks into the trace then diverges the jaxpr across the simulated
    fleet, which is exactly the proof obligation.

    Clears the jit caches on entry AND exit: pjit's cache is keyed on
    the underlying function object, so re-tracing the same callable
    under the next simulated host would silently replay the previous
    host's jaxpr — a cached trace would mask exactly the divergence
    this proof exists to catch (and, symmetrically, a host-k trace
    must not leak into post-audit real traces)."""
    import jax

    saved = (jax.process_index, jax.process_count)
    jax.process_index = lambda backend=None: process_index
    jax.process_count = lambda backend=None: process_count
    jax.clear_caches()
    try:
        yield
    finally:
        jax.process_index, jax.process_count = saved
        jax.clear_caches()


# --------------------------------------------------------------------------
# data model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class HostTrace:
    """One simulated host's view: traced programs + ordered collectives."""

    process_index: int
    programs: dict[str, Any]  # name -> program.TracedProgram
    sequences: dict[str, list[dict[str, str]]] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class SpmdTrace:
    """Everything a contract's builder hands the checks.

    ``hosts`` holds one :class:`HostTrace` per simulated process;
    ``coverage`` is the partition-rule coverage table from
    :func:`partition_coverage` (None when the builder ran single-device
    or the contract declares no rules); ``notes`` surface in the report.
    """

    hosts: list[HostTrace]
    coverage: dict | None = None
    notes: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class SpmdContract:
    name: str
    entry: str  # human-readable entry-point path (report/docs)
    build: Callable[[int], SpmdTrace]  # takes the simulated host count
    hosts: int = 2
    ordered_collectives: tuple[str, ...] = ()
    partition_rules: str | None = None  # attr name on the declaring module
    covers: tuple[str, ...] = ()  # tier-2 contract names this one verifies
    suppress: dict[str, str] = dataclasses.field(default_factory=dict)


def _finding(contract: SpmdContract, rule: str, message: str) -> Finding:
    return Finding(
        rule=rule, path=f"<{contract.name}>", line=0, col=0, message=message
    )


# --------------------------------------------------------------------------
# partition-rule coverage
# --------------------------------------------------------------------------


def _spec_shards(spec: Any) -> bool:
    """True when a PartitionSpec (or its str) names at least one mesh
    axis — i.e. the placement actually splits the leaf."""
    if spec is None:
        return False
    try:
        return any(ax is not None for ax in spec)
    except TypeError:
        return False


def partition_coverage(
    rules: Iterable[tuple[str, Any]], leaves: dict[str, Any]
) -> dict:
    """Match named placed leaves against the regex partition-rule tree.

    ``rules`` is ``((pattern, PartitionSpec), ...)`` (the
    ``match_partition_rules`` shape); ``leaves`` maps slash-joined pytree
    path names to the PLACED arrays. The table records, per leaf, every
    matching rule index, the matched spec, the placed spec, and whether
    each side actually shards — the checks turn disagreements into
    findings. Scalars are exempt (they are replicated by construction).
    """
    rules = list(rules)
    table: dict[str, dict] = {}
    for name, leaf in sorted(leaves.items()):
        ndim = int(getattr(leaf, "ndim", 0))
        matches = [
            i for i, (pat, _) in enumerate(rules) if re.search(pat, name)
        ]
        matched_spec = rules[matches[0]][1] if matches else None
        placed_spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        table[name] = {
            "ndim": ndim,
            "matches": matches,
            "rule": rules[matches[0]][0] if matches else None,
            "spec": None if matched_spec is None else str(matched_spec),
            "placed": None if placed_spec is None else str(placed_spec),
            "intended_sharded": _spec_shards(matched_spec),
            "placed_sharded": _spec_shards(placed_spec),
        }
    return {"rules": [pat for pat, _ in rules], "leaves": table}


# --------------------------------------------------------------------------
# the shard_map path diagnosis (the xfail, named statically)
# --------------------------------------------------------------------------


def diagnose_shard_map_path() -> dict[str, Any]:
    """Statically diagnose the column-sharded (tensor-parallel) mesh path.

    Traces ``FeatureShardedSparse.matvec`` abstractly and returns a
    structured verdict: ``ok`` (True / False / None when single-device),
    the ``stage`` reached, the ``divergent_op`` the trace died in, and
    the raw ``reason``. On jax 0.4.37 the path dies importing
    ``jax.shard_map`` (it lives in ``jax.experimental.shard_map`` until
    0.4.38+) — the auditor names that op so the 4 xfailed
    TestColumnFeatureSharding tests cite a diagnosed finding instead of
    a mystery failure (tests/test_analysis_spmd.py pins this).
    """
    import jax
    import numpy as np

    from photon_tpu.parallel.mesh import (
        MODEL_AXIS,
        make_mesh,
        shard_features_by_column,
    )

    if len(jax.devices()) < 2:
        return {
            "ok": None,
            "stage": "setup",
            "divergent_op": None,
            "reason": "single visible device — column sharding needs >= 2",
        }
    stage = "build"
    try:
        mesh = make_mesh(axis_name=MODEL_AXIS)
        n_dev = int(mesh.shape[MODEL_AXIS])
        n, d = 4, 2 * n_dev
        rng = np.random.default_rng(0)
        indices = rng.integers(0, d, size=(n, 2))
        values = rng.normal(size=(n, 2)).astype(np.float32)
        fs = shard_features_by_column(indices, values, d, mesh)
        stage = "trace"
        jax.jit(lambda w: fs.matvec(w)).trace(
            jax.ShapeDtypeStruct((fs.d,), np.float32)
        )
        stage = "done"
        return {"ok": True, "stage": stage, "divergent_op": None, "reason": ""}
    except Exception as exc:  # noqa: BLE001 — the diagnosis IS the catch
        m = re.search(r"cannot import name '(\w+)'", str(exc))
        op = m.group(1) if m else type(exc).__name__
        return {
            "ok": False,
            "stage": stage,
            "divergent_op": op,
            "reason": f"{type(exc).__name__}: {exc}",
            "hint": (
                "jax 0.4.37 ships shard_map as jax.experimental."
                "shard_map.shard_map, not jax.shard_map — the mesh "
                "rebuild (ROADMAP item 1) must import the experimental "
                "path or move to pjit/NamedSharding"
            ),
        }


# --------------------------------------------------------------------------
# contract builders
# --------------------------------------------------------------------------


def _named_mesh_leaves(batch, re_ds, w) -> dict[str, Any]:
    """Slash-named placed leaves of the mesh fixture — the pytree the
    partition-rule tree must cover exactly once each."""
    leaves: dict[str, Any] = {
        "fe/features": batch.features.x,
        "fe/labels": batch.labels,
        "fe/offsets": batch.offsets,
        "fe/weights": batch.weights,
        "coef/w": w,
    }
    uids = getattr(batch, "uids", None)
    if uids is not None:
        leaves["fe/uids"] = uids
    for i, b in enumerate(re_ds.blocks):
        for field in (
            "entity_codes", "row_ids", "row_counts", "proj",
            "intercept_slots",
        ):
            leaf = getattr(b, field, None)
            if leaf is not None:
                leaves[f"re/block{i}/{field}"] = leaf
    raw = getattr(re_ds, "raw", None)
    if raw is not None:
        raw_leaf = getattr(raw, "x", None)
        if raw_leaf is None:
            raw_leaf = raw.values
        leaves["re/raw"] = raw_leaf
    codes = getattr(re_ds, "score_codes", None)
    if codes is not None:
        leaves["re/score_codes"] = codes
    return leaves


def build_mesh_spmd(hosts: int) -> SpmdTrace:
    """The mesh contract: the data-parallel GLM objective traced under
    every simulated host, its ordered collective census per host, and
    the partition-rule coverage of every placed fixed-effect and
    random-effect leaf. The same fixture family as the tier-2 sharding
    audit — tier 6 proves the multi-host properties tier 2 assumes."""
    import jax
    import numpy as np

    from photon_tpu.analysis.program import _tiny_glmix, trace_program
    from photon_tpu.data.dataset import make_dense_batch
    from photon_tpu.data.random_effect import (
        RandomEffectDataConfiguration,
        build_random_effect_dataset,
    )
    from photon_tpu.ops import glm as glm_ops
    from photon_tpu.ops import losses as losses_mod
    from photon_tpu.ops.normalization import NormalizationContext
    from photon_tpu.parallel import mesh as mesh_mod
    from photon_tpu.types import TaskType

    if len(jax.devices()) < 2:
        return SpmdTrace(
            hosts=[],
            notes=[
                "SPMD audit SKIPPED: single visible device (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8, as "
                "CI does, to exercise it)",
            ],
        )

    mesh = mesh_mod.make_mesh()
    n_dev = len(mesh.devices.reshape(-1))
    n, d = 8 * n_dev, 5
    rng = np.random.default_rng(1)
    batch = mesh_mod.shard_batch(
        make_dense_batch(
            rng.normal(size=(n, d)).astype(np.float32),
            (rng.uniform(size=n) < 0.5).astype(np.float32),
        ),
        mesh,
    )
    loss = losses_mod.get_loss(TaskType.LOGISTIC_REGRESSION)

    def objective(b, w):
        return glm_ops.make_value_and_grad(b, loss, NormalizationContext())(w)

    w = jax.device_put(
        jax.numpy.zeros(d, batch.labels.dtype), mesh_mod.replicated(mesh)
    )

    host_traces: list[HostTrace] = []
    for k in range(hosts):
        with simulated_host(k, hosts):
            prog = trace_program("sharded_objective", objective, batch, w)
            seq = collective_sequence(prog.lowered.compile())
        host_traces.append(
            HostTrace(
                process_index=k,
                programs={"sharded_objective": prog},
                sequences={"sharded_objective": seq},
            )
        )

    # Random-effect placement + the named-leaf coverage table.
    est, data = _tiny_glmix(n=16 * n_dev, e=2 * n_dev)
    re_ds = build_random_effect_dataset(
        data,
        RandomEffectDataConfiguration("userId", "userShard"),
        intercept_index=3,
    )
    re_ds = mesh_mod.shard_random_effect_dataset(re_ds, mesh)
    coverage = partition_coverage(
        mesh_mod.PARTITION_RULES, _named_mesh_leaves(batch, re_ds, w)
    )

    notes = [
        f"{hosts} simulated hosts x {n_dev} devices; "
        f"{len(coverage['leaves'])} placed leaves against "
        f"{len(coverage['rules'])} partition rules"
    ]
    diag = diagnose_shard_map_path()
    if diag["ok"] is False:
        notes.append(
            "column (shard_map) path statically diagnosed: divergent op "
            f"'{diag['divergent_op']}' at stage {diag['stage']} — "
            f"{diag['reason']}"
        )
    return SpmdTrace(hosts=host_traces, coverage=coverage, notes=notes)


_BUILDERS: dict[str, Callable[[int], SpmdTrace]] = {
    "build_mesh_spmd": build_mesh_spmd,
}


def contract_from_declaration(spec: dict) -> SpmdContract:
    builder = spec.get("builder")
    if builder not in _BUILDERS:
        raise ValueError(
            f"SPMD_AUDIT declaration {spec.get('name')!r} names unknown "
            f"builder {builder!r}"
        )
    return SpmdContract(
        name=spec["name"],
        entry=spec["entry"],
        build=_BUILDERS[builder],
        hosts=int(spec.get("hosts", 2)),
        ordered_collectives=tuple(spec.get("ordered_collectives", ())),
        partition_rules=spec.get("partition_rules"),
        covers=tuple(spec.get("covers", ())),
        suppress=dict(spec.get("suppress", {})),
    )


def collect_contracts() -> list[SpmdContract]:
    """The repo's declared SPMD contract registry (module hooks)."""
    specs: list[dict] = []
    for modname in SPMD_DECLARING_MODULES:
        mod = importlib.import_module(modname)
        decl = getattr(mod, "SPMD_AUDIT", None)
        if decl is None:
            raise ValueError(
                f"{modname} is an SPMD declaring module but exports no "
                "SPMD_AUDIT"
            )
        specs.extend(decl if isinstance(decl, (list, tuple)) else [decl])
    return [contract_from_declaration(s) for s in specs]


# --------------------------------------------------------------------------
# contract checks
# --------------------------------------------------------------------------


_JAXPR_OP_RE = re.compile(r"=\s*([A-Za-z_][\w.\-\[\]]*)")


def _first_divergence(a: str, b: str) -> str:
    """Name the first divergent jaxpr line (and its primitive) between
    two hosts' traces — the 'statically names the divergent op' half of
    the proof."""
    la, lb = a.splitlines(), b.splitlines()
    for i, (x, y) in enumerate(zip(la, lb)):
        if x != y:
            m = _JAXPR_OP_RE.search(x) or _JAXPR_OP_RE.search(y)
            op = m.group(1) if m else "<structural>"
            return (
                f"first divergence at jaxpr line {i + 1} (op {op}): "
                f"{x.strip()!r} != {y.strip()!r}"
            )
    if len(la) != len(lb):
        return (
            f"jaxprs differ in length ({len(la)} vs {len(lb)} lines) "
            "after a common prefix"
        )
    return "texts differ (no line-level divergence found)"


def check_trace_divergence(
    contract: SpmdContract, trace: SpmdTrace
) -> Iterator[Finding]:
    if len(trace.hosts) < 2:
        return
    base = trace.hosts[0]
    for host in trace.hosts[1:]:
        for name, prog in base.programs.items():
            other = host.programs.get(name)
            if other is None:
                yield _finding(
                    contract,
                    "spmd-trace-divergence",
                    f"program '{name}' traced on host 0 but not on host "
                    f"{host.process_index} — the fleet would compile "
                    "different program sets",
                )
                continue
            if other.text != prog.text:
                yield _finding(
                    contract,
                    "spmd-trace-divergence",
                    f"program '{name}' jaxprs diverge between host 0 "
                    f"(sig {prog.signature}) and host "
                    f"{host.process_index} (sig {other.signature}); "
                    + _first_divergence(prog.text, other.text),
                )


def check_collective_order(
    contract: SpmdContract, trace: SpmdTrace
) -> Iterator[Finding]:
    if not trace.hosts:
        return
    base = trace.hosts[0]
    for host in trace.hosts[1:]:
        for name, seq in base.sequences.items():
            other = host.sequences.get(name, [])
            ops_a = [s["op"] for s in seq]
            ops_b = [s["op"] for s in other]
            if ops_a == ops_b:
                continue
            idx = next(
                (
                    i
                    for i, (x, y) in enumerate(zip(ops_a, ops_b))
                    if x != y
                ),
                min(len(ops_a), len(ops_b)),
            )
            at_a = ops_a[idx] if idx < len(ops_a) else "<end>"
            at_b = ops_b[idx] if idx < len(ops_b) else "<end>"
            yield _finding(
                contract,
                "spmd-collective-order",
                f"program '{name}' collective sequences diverge between "
                f"host 0 and host {host.process_index} at position "
                f"{idx}: {at_a} vs {at_b} (host 0: "
                f"{' -> '.join(ops_a) or 'none'}; host "
                f"{host.process_index}: {' -> '.join(ops_b) or 'none'}) "
                "— the fleet deadlocks at the first mismatched "
                "collective",
            )


def check_implicit_reshard(
    contract: SpmdContract, trace: SpmdTrace
) -> Iterator[Finding]:
    if not trace.hosts:
        return
    declared = set(contract.ordered_collectives)
    seen_any = False
    for name, seq in trace.hosts[0].sequences.items():
        seen_any = seen_any or bool(seq)
        undeclared = [s for s in seq if s["op"] not in declared]
        if not undeclared:
            continue
        from photon_tpu.analysis import costmodel

        price = costmodel.collective_transfer(undeclared)
        ici = price["min_seconds_ici"]
        yield _finding(
            contract,
            "spmd-implicit-reshard",
            f"program '{name}' HLO carries undeclared collective(s) "
            f"{', '.join(sorted({s['op'] for s in undeclared}))} "
            f"(declared: {', '.join(sorted(declared)) or 'none'}) — an "
            "implicit reshard moving "
            f"{int(price['total_bytes'])} bytes over the interconnect "
            f"per dispatch"
            + (f" (>= {ici:.2e} s at ICI peak)" if ici else ""),
        )
    if declared and trace.hosts and not seen_any:
        yield _finding(
            contract,
            "spmd-contract",
            "contract declares ordered_collectives "
            f"({', '.join(sorted(declared))}) but no traced program "
            "contains any collective — the declaration is unchecked",
        )


def check_partition_coverage(
    contract: SpmdContract, trace: SpmdTrace
) -> Iterator[Finding]:
    cov = trace.coverage
    if cov is None:
        if contract.partition_rules and trace.hosts:
            yield _finding(
                contract,
                "spmd-contract",
                f"contract declares partition rules "
                f"({contract.partition_rules}) but the builder produced "
                "no coverage table",
            )
        return
    rules_hit: set[int] = set()
    for name, row in cov["leaves"].items():
        if row["ndim"] == 0:
            continue  # scalars are replicated by construction
        if not row["matches"]:
            yield _finding(
                contract,
                "spmd-partition-coverage",
                f"placed leaf '{name}' (ndim {row['ndim']}, placed "
                f"{row['placed']}) matches NO partition rule — the "
                "pjit rebuild would have no spec for it",
            )
            continue
        if len(row["matches"]) > 1:
            pats = ", ".join(
                repr(cov["rules"][i]) for i in row["matches"]
            )
            yield _finding(
                contract,
                "spmd-partition-coverage",
                f"placed leaf '{name}' matches {len(row['matches'])} "
                f"partition rules ({pats}) — rules must partition the "
                "namespace, first-match ordering is a silent tiebreak",
            )
        rules_hit.update(row["matches"][:1])
        if row["intended_sharded"] and not row["placed_sharded"]:
            yield _finding(
                contract,
                "spmd-partition-coverage",
                f"leaf '{name}' is intended to shard (rule "
                f"{row['rule']!r} -> {row['spec']}) but was placed "
                f"{row['placed']} — a silently-replicated slab pays "
                "full-copy HBM on every device",
            )
        elif row["placed_sharded"] and not row["intended_sharded"]:
            yield _finding(
                contract,
                "spmd-partition-coverage",
                f"leaf '{name}' is placed sharded ({row['placed']}) but "
                f"its rule {row['rule']!r} says replicate ({row['spec']})"
                " — the rule tree and the placement code disagree",
            )
    for i, pat in enumerate(cov["rules"]):
        if i not in rules_hit:
            yield _finding(
                contract,
                "spmd-contract",
                f"partition rule {pat!r} matched no placed leaf as a "
                "first match — a dead rule documents sharding that no "
                "longer exists",
            )


CHECKS = (
    check_trace_divergence,
    check_collective_order,
    check_implicit_reshard,
    check_partition_coverage,
)


def run_checks(
    contract: SpmdContract, trace: SpmdTrace
) -> list[Finding]:
    """All checks over one contract's trace, suppressions applied."""
    findings: list[Finding] = []
    for unknown in sorted(set(contract.suppress) - set(SPMD_RULES)):
        findings.append(
            _finding(
                contract,
                "spmd-contract",
                f"suppression names unknown rule '{unknown}'",
            )
        )
    for check in CHECKS:
        for f in check(contract, trace):
            reason = contract.suppress.get(f.rule)
            if reason is not None:
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=reason
                )
            findings.append(f)
    return findings


def check_tier2_alignment(
    contracts: Iterable[SpmdContract],
) -> list[Finding]:
    """Tier-2/tier-6 drift guard.

    Every tier-2 program contract that declares mesh semantics (an axis
    or allowed collectives) must be named in some tier-6 contract's
    ``covers`` — or reason-waived in :data:`TIER2_SPMD_WAIVERS` — and a
    covered contract's ``allowed_collectives`` must equal the covering
    tier-6 contract's ``ordered_collectives`` as a set (the dedup that
    keeps the PR 2 census and this tier's census one census).
    """
    from photon_tpu.analysis import program as program_mod

    findings: list[Finding] = []
    tier6 = list(contracts)
    covered = {name: c for c in tier6 for name in c.covers}
    tier2 = {c.name: c for c in program_mod.collect_contracts()}

    def orphan(rule: str, msg: str) -> Finding:
        return Finding(
            rule=rule, path="<tier2-alignment>", line=0, col=0, message=msg
        )

    for name, t2 in sorted(tier2.items()):
        is_mesh = bool(t2.axis) or bool(t2.allowed_collectives)
        if not is_mesh:
            continue
        t6 = covered.get(name)
        if t6 is None:
            if name in TIER2_SPMD_WAIVERS:
                continue
            findings.append(
                orphan(
                    "spmd-contract",
                    f"tier-2 contract '{name}' declares mesh semantics "
                    f"(axis={t2.axis!r}, allowed_collectives="
                    f"{list(t2.allowed_collectives)}) but no tier-6 "
                    "contract covers it and no waiver explains why",
                )
            )
            continue
        if set(t2.allowed_collectives) != set(t6.ordered_collectives):
            findings.append(
                orphan(
                    "spmd-contract",
                    f"tier-2 contract '{name}' allows collectives "
                    f"{sorted(t2.allowed_collectives)} but covering "
                    f"tier-6 contract '{t6.name}' orders "
                    f"{sorted(t6.ordered_collectives)} — the two tiers "
                    "have drifted apart",
                )
            )
    for name, c in covered.items():
        if name not in tier2:
            findings.append(
                orphan(
                    "spmd-contract",
                    f"tier-6 contract '{c.name}' covers tier-2 contract "
                    f"'{name}' which no longer exists",
                )
            )
    for name in sorted(TIER2_SPMD_WAIVERS):
        if name not in tier2 or name in covered:
            findings.append(
                orphan(
                    "spmd-contract",
                    f"stale TIER2_SPMD_WAIVERS entry '{name}' — the "
                    "tier-2 contract is "
                    + ("now covered" if name in covered else "gone")
                    + "; delete the waiver",
                )
            )
    return findings


# --------------------------------------------------------------------------
# the host-divergence AST lint
# --------------------------------------------------------------------------

# Calls whose return value differs between hosts of one fleet. Seeded
# RNGs (np.random.default_rng(42)) are NOT here — they are deterministic
# and host-uniform; only the unseeded form varies.
_HOST_VARYING_CALLS = frozenset(
    {
        "jax.process_index",
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "os.getpid",
        "os.urandom",
        "os.getenv",
        "socket.gethostname",
        "socket.getfqdn",
        "uuid.uuid1",
        "uuid.uuid4",
        "random.random",
        "random.randint",
        "random.randrange",
        "random.getrandbits",
        "secrets.token_bytes",
        "secrets.token_hex",
        "secrets.randbits",
    }
)

# Array constructors whose shape argument becomes part of the compiled
# program: a host-varying shape IS a divergent trace.
_SHAPE_CONSTRUCTORS = frozenset(
    {
        "jax.numpy.zeros",
        "jax.numpy.ones",
        "jax.numpy.full",
        "jax.numpy.empty",
        "jax.numpy.arange",
        "jax.numpy.linspace",
        "jax.numpy.eye",
        "jax.numpy.tile",
        "jax.numpy.broadcast_to",
        "jax.numpy.reshape",
        "jax.ShapeDtypeStruct",
    }
)

# A branch on a host-varying value is trace-affecting when the enclosing
# function builds programs: different hosts take different sides and
# trace different jaxprs.
_TRACE_ENTRY_CALLS = frozenset(
    {
        "jax.jit",
        "jax.pmap",
        "jax.shard_map",
        "jax.experimental.shard_map.shard_map",
        "jax.experimental.pjit.pjit",
        "jax.eval_shape",
        "jax.make_jaxpr",
    }
)


def _host_varying_source(ctx: ModuleContext, node: ast.AST) -> str | None:
    """The host-varying source a single expression node IS, else None."""
    if isinstance(node, ast.Call):
        resolved = ctx.resolve(node.func)
        if resolved in _HOST_VARYING_CALLS:
            return resolved
        if resolved == "numpy.random.default_rng" and not node.args:
            return "numpy.random.default_rng()  # unseeded"
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and ctx.resolve(node.func.value) == "os.environ"
        ):
            return "os.environ.get"
    if (
        isinstance(node, ast.Subscript)
        and ctx.resolve(node.value) == "os.environ"
    ):
        return "os.environ[...]"
    return None


def _taint_sources(
    ctx: ModuleContext, expr: ast.AST, tainted: dict[str, str]
) -> list[str]:
    """Every host-varying source reachable inside one expression: direct
    host-varying calls/env reads plus already-tainted local names."""
    out: list[str] = []
    for node in ast.walk(expr):
        src = _host_varying_source(ctx, node)
        if src is not None:
            out.append(src)
        elif (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in tainted
        ):
            out.append(f"{node.id} (from {tainted[node.id]})")
    return out


def _scope_of(ctx: ModuleContext, node: ast.AST) -> ast.AST | None:
    return ctx.enclosing_function(node)


def _function_taint(
    ctx: ModuleContext,
) -> dict[ast.AST | None, dict[str, str]]:
    """Per-scope forward taint map: local names assigned (directly or
    transitively, in line order) from host-varying sources."""
    taint: dict[ast.AST | None, dict[str, str]] = {}
    assigns: list[tuple[int, ast.AST | None, ast.AST, ast.AST]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                assigns.append(
                    (node.lineno, _scope_of(ctx, node), tgt, node.value)
                )
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            assigns.append(
                (node.lineno, _scope_of(ctx, node), node.target, node.value)
            )
        elif isinstance(node, ast.AugAssign):
            assigns.append(
                (node.lineno, _scope_of(ctx, node), node.target, node.value)
            )
    for lineno, scope, tgt, value in sorted(assigns, key=lambda t: t[0]):
        scope_taint = taint.setdefault(scope, {})
        sources = _taint_sources(ctx, value, scope_taint)
        if not sources:
            continue
        for leaf in ast.walk(tgt):
            if isinstance(leaf, ast.Name):
                scope_taint[leaf.id] = sources[0]
    return taint


def _scope_builds_programs(ctx: ModuleContext, scope: ast.AST | None) -> bool:
    """True when a function (or the module body) contains a trace/jit
    entry call — branches inside it select which program gets traced."""
    root = scope if scope is not None else ctx.tree
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            if ctx.resolve(node.func) in _TRACE_ENTRY_CALLS:
                return True
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in (
                "trace",
                "lower",
            ):
                # obj.trace(...) / obj.lower(...) — the jax.stages
                # surface; resolves to None for local objects, so match
                # on the attribute.
                return True
    return False


def _shape_args(call: ast.Call) -> list[ast.AST]:
    out: list[ast.AST] = []
    if call.args:
        out.append(call.args[0])
    for kw in call.keywords:
        if kw.arg == "shape":
            out.append(kw.value)
    return out


def audit_source(source: str, path: str = "<string>") -> list[Finding]:
    """The spmd-host-divergence lint over one source blob.

    Flags host-varying values flowing into (a) array-constructor /
    ShapeDtypeStruct shape arguments and (b) branch predicates inside
    program-building scopes. Per-line ``# photon: ignore[...]``
    suppressions apply as in every other AST tier.
    """
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                message=f"file does not parse: {exc.msg}",
            )
        ]
    ctx = ModuleContext(path, source, tree)
    taint = _function_taint(ctx)
    builds_cache: dict[ast.AST | None, bool] = {}
    findings: list[Finding] = []
    seen: set[tuple] = set()

    def emit(node: ast.AST, message: str) -> None:
        f = Finding(
            rule="spmd-host-divergence",
            path=path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )
        key = (f.line, f.col, f.message)
        if key in seen:
            return
        seen.add(key)
        sup = ctx.suppressions.get(f.line)
        if sup is not None and sup.covers(f.rule):
            f = dataclasses.replace(
                f, suppressed=True, suppress_reason=sup.reason
            )
        findings.append(f)

    for node in ast.walk(tree):
        scope = _scope_of(ctx, node)
        scope_taint = taint.get(scope, {})
        if isinstance(node, ast.Call):
            resolved = ctx.resolve(node.func)
            if resolved in _SHAPE_CONSTRUCTORS:
                for arg in _shape_args(node):
                    sources = _taint_sources(ctx, arg, scope_taint)
                    if sources:
                        emit(
                            node,
                            f"host-varying value ({sources[0]}) flows "
                            f"into the shape of {resolved} — every host "
                            "traces a different program",
                        )
                        break
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            sources = _taint_sources(ctx, node.test, scope_taint)
            if not sources:
                continue
            if scope not in builds_cache:
                builds_cache[scope] = _scope_builds_programs(ctx, scope)
            if builds_cache[scope]:
                emit(
                    node,
                    f"branch predicate on a host-varying value "
                    f"({sources[0]}) in a scope that builds traced "
                    "programs — hosts taking different sides trace "
                    "divergent programs",
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


def audit_paths(paths: Iterable[str | Path]) -> list[Finding]:
    findings: list[Finding] = []
    for p in iter_python_files(paths):
        findings.extend(
            audit_source(p.read_text(encoding="utf-8"), path=str(p))
        )
    return findings


# --------------------------------------------------------------------------
# the audit driver
# --------------------------------------------------------------------------


def _package_paths() -> list[str]:
    """The package source root, resolved from the import (not the CWD)
    — the CLI forbids path arguments, so the lint half must find the
    code regardless of where the gate runs."""
    import photon_tpu

    return [str(Path(photon_tpu.__file__).parent)]


def audit(
    contracts: Iterable[SpmdContract] | None = None,
    *,
    hosts: int | None = None,
    lint_paths: Iterable[str | Path] | None = None,
    with_lint: bool = True,
) -> tuple[list[Finding], dict]:
    """Run the host-divergence lint + every SPMD contract.

    ``hosts`` overrides each contract's declared simulated host count
    (CI's multichip-smoke step passes the gloo dryrun's process count).
    Returns ``(findings, report)``; builds run under ``disable_x64`` so
    the audited traces match the production (f32) configuration.
    """
    from photon_tpu.analysis import program as program_mod

    program_mod._ensure_virtual_devices()
    from jax.experimental import disable_x64

    findings: list[Finding] = []
    report: dict[str, Any] = {"contracts": {}}
    if with_lint:
        lint = audit_paths(
            lint_paths if lint_paths is not None else _package_paths()
        )
        findings.extend(lint)
        report["lint"] = {
            "findings": len(lint),
            "suppressed": sum(1 for f in lint if f.suppressed),
        }
    with disable_x64(), program_mod._serial_ingest_env():
        resolved = (
            collect_contracts() if contracts is None else list(contracts)
        )
        findings.extend(check_tier2_alignment(resolved))
        for contract in resolved:
            n_hosts = hosts if hosts is not None else contract.hosts
            entry: dict[str, Any] = {
                "entry": contract.entry,
                "hosts": n_hosts,
                "programs": {},
                "notes": [],
            }
            report["contracts"][contract.name] = entry
            if n_hosts < 2:
                findings.append(
                    _finding(
                        contract,
                        "spmd-contract",
                        f"contract declares {n_hosts} host(s) — the "
                        "cross-host proof needs at least 2",
                    )
                )
                continue
            try:
                trace = contract.build(n_hosts)
            except Exception as exc:  # noqa: BLE001 — any builder crash is a finding
                findings.append(
                    _finding(
                        contract,
                        "spmd-contract",
                        f"contract builder failed: {exc!r}",
                    )
                )
                continue
            entry["notes"] = list(trace.notes)
            if trace.hosts:
                base = trace.hosts[0]
                for name, prog in base.programs.items():
                    sigs = {
                        h.process_index: h.programs[name].signature
                        for h in trace.hosts
                        if name in h.programs
                    }
                    entry["programs"][name] = {
                        "signatures": sigs,
                        "identical": len(set(sigs.values())) == 1
                        and len(sigs) == len(trace.hosts),
                        "collectives": [
                            s["op"] for s in base.sequences.get(name, [])
                        ],
                    }
            if trace.coverage is not None:
                leaves = trace.coverage["leaves"]
                entry["coverage"] = {
                    "rules": len(trace.coverage["rules"]),
                    "leaves": len(leaves),
                    "uncovered": sorted(
                        n
                        for n, row in leaves.items()
                        if row["ndim"] > 0 and not row["matches"]
                    ),
                }
            findings.extend(run_checks(contract, trace))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, report


def render_rule_list() -> str:
    width = max(len(r) for r in SPMD_RULES)
    return "\n".join(
        f"{rule_id.ljust(width)}  {summary}"
        for rule_id, summary in sorted(SPMD_RULES.items())
    )
