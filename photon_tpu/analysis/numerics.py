"""Tier 5: the numerics auditor — dtype-flow verification of the
mixed-precision policy on the traced jaxprs.

``python -m photon_tpu.analysis --numerics``

The roofline push made bf16 storage with f32 accumulators the default
bench path (PERFORMANCE.md), but until this tier the only guard was the
tier-1 ``bf16-accumulation`` AST rule — a textual pattern-match that
cannot see through helper indirection, ``preferred_element_type``
plumbing, or scan carries. This tier re-traces the audited programs
abstractly (no device, same harness as tiers 2 and 4) and walks a
**dtype-provenance lattice** over each jaxpr, recursing into
scan/while/cond/pjit/custom-call bodies and the Pallas kernel boundary,
to verify the policy *semantically*:

1. **Accumulation-dtype audit** — every reduction-class eqn
   (``reduce_sum``, ``dot_general``, scatter/segment reductions, the
   Pallas segment-reduce kernel, scan carries that accumulate) whose
   operand lineage carries bf16 must accumulate in f32
   (``numerics-bf16-accumulation``).
2. **Cast census** — pointless f32→bf16→f32 round-trips
   (``numerics-cast-roundtrip``), downcasts of accumulator outputs that
   are then RE-reduced (``numerics-acc-downcast``), and per-iteration
   re-roundings of loop-carried state inside scan/while bodies
   (``numerics-scan-recast``). Deliberate instances (the fused fit's
   idempotent score quantization, its bf16 score carries) are
   suppressed per contract with a written reason.
3. **Static error budgets** — each contract declares a worst-case
   relative-error budget per program as a formula over the builder's
   dims (the MEMORY_AUDIT formula language plus the rounding constants
   ``u16`` = 2^-9 and ``u32`` = 2^-24). The auditor derives a bound
   from the cast graph and the static reduction lengths::

       derived = u16 * max_rounds + u32 * reduce_len

   where ``max_rounds`` is the deepest chain of bf16 roundings along
   any dataflow path (scan bodies multiply their per-iteration deltas
   by the static trip count) and ``reduce_len`` is the summed static
   length of every f32 accumulation over bf16-lineage operands (the
   f32 accumulator's own rounding grows with the reduction length).
   Gated BOTH directions at the contract tolerance, like tier 4:
   undeclared error growth (``numerics-undeclared-error``) and rotten
   budgets (``numerics-stale-budget``) both fail. This ties the
   PERFORMANCE.md per-family parity tolerances to a derivation.
4. **Reduction-determinism census** — every order-nondeterministic
   primitive family present in a program (``scatter-add`` and friends)
   must be declared deterministic-by-construction in the contract
   (e.g. "sorted bucket-slab segment ids") or carry a reasoned waiver
   (``numerics-nondeterministic-reduce``); stale declarations are
   contract findings.
5. **Coverage gate** — every tier-2 PROGRAM_AUDIT name must be claimed
   by a ``NUMERICS_AUDIT`` contract or a reasoned ``TIER2_WAIVERS``
   entry; stale waivers are findings (the tier-4 discipline).

Plus the **unstable-exp check** (``numerics-unstable-exp``): an ``exp``
whose operand carries no dominating upper bound (no ``min``/``clamp``
on the path, no ``-|x|`` shape) feeding a reduction — the failure mode
the Poisson linkage had before its margin clamp (ops/losses.py).

Contracts are plain-data ``NUMERICS_AUDIT`` dicts declared beside the
code they audit (ops/precision.py, algorithm/fused_fit.py,
ops/segment_reduce.py, serve/programs.py), naming a builder in this
module — importing the audited modules never imports the analysis
machinery. See ANALYSIS.md (tier 5) for the contract schema.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import importlib
from collections import Counter
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from photon_tpu.analysis.core import Finding

NUMERICS_RULES: dict[str, str] = {
    "numerics-bf16-accumulation": (
        "a reduction-class eqn with bf16 operand lineage accumulates "
        "below f32 (bf16 dot_general/reduce/scatter output, or a bf16 "
        "scan carry that accumulates) — the semantic form of the "
        "tier-1 bf16-accumulation rule"
    ),
    "numerics-cast-roundtrip": (
        "a single-use f32->bf16->f32 cast round-trip: the value is "
        "rounded twice and never stored — either a wasted double "
        "rounding or an intentional quantization that needs a reason"
    ),
    "numerics-acc-downcast": (
        "an f32 accumulator output is downcast to bf16 and then "
        "RE-reduced — the accumulated precision is thrown away "
        "between reduction stages"
    ),
    "numerics-scan-recast": (
        "a loop-carried value is re-rounded to bf16 every iteration "
        "inside a scan/while body — one rounding per trip compounds "
        "across the loop"
    ),
    "numerics-unstable-exp": (
        "an exp() whose operand carries no dominating upper bound "
        "feeds a reduction — a large margin overflows to inf and "
        "poisons the whole accumulation (the raw-exp Poisson bug)"
    ),
    "numerics-undeclared-error": (
        "a program's derived worst-case relative-error bound exceeds "
        "its declared budget formula beyond the contract tolerance — "
        "error grew that the contract does not price"
    ),
    "numerics-stale-budget": (
        "a declared error budget prices far above the derived bound "
        "(or no longer evaluates) — the contract rotted and would "
        "mask real error growth"
    ),
    "numerics-nondeterministic-reduce": (
        "an order-nondeterministic reduction family (scatter-add, "
        "unsorted segment ops) appears in a program without a "
        "deterministic-by-construction declaration"
    ),
    "numerics-contract": (
        "numerics-contract declaration, coverage, or builder "
        "integrity error (uncovered tier-2 entry point, stale "
        "waiver or declaration, builder crash)"
    ),
}

# Modules that declare numerics contracts (each exports NUMERICS_AUDIT —
# one declaration dict or a list of them). Plain data, like the tier-2
# PROGRAM_AUDIT / tier-4 MEMORY_AUDIT hooks.
NUMERICS_DECLARING_MODULES = (
    "photon_tpu.ops.precision",
    "photon_tpu.algorithm.fused_fit",
    "photon_tpu.ops.segment_reduce",
    "photon_tpu.ops.serve_kernel",
    "photon_tpu.serve.programs",
)

# Tier-2 contracts with NO numerics contract, each with its reason. The
# coverage check keeps this list honest: a new tier-2 contract fails
# the audit until someone either audits its dtype flow or writes its
# waiver down here.
TIER2_WAIVERS: dict[str, str] = {
    "fused-cache-key": (
        "key-only contract — traces no programs; precision is one of "
        "its declared key fields and the fused-fit numerics contract "
        "audits the programs the keys select"
    ),
    "unfused-coordinate-update": (
        "the unfused CD path is the f32 debugging fallback; it never "
        "receives bf16 operands (precision is plumbed only through "
        "FusedFit) and its reductions are covered by the fused-fit "
        "contract's f32 control program"
    ),
    "newton-kernel": (
        "executes only inline inside the fused-fit program — its eqns "
        "are walked by the fused-fit contract's recursion; the f32-only "
        "Pallas variant gates itself off bf16 slabs (PERFORMANCE.md)"
    ),
    "mesh-sharding": (
        "sharding annotations do not change dtype flow; the replicated "
        "fused programs this tier walks are the same jaxprs the mesh "
        "partitions, and cross-device psum determinism needs the mesh "
        "geometry (ROADMAP item 1's verification harness)"
    ),
    "ingest-pipeline": (
        "host-side ETL at f64/f32 numpy; the device programs it feeds "
        "are audited by the fused-fit contract"
    ),
    "streaming-ingest": (
        "host-side shard streaming; same story as ingest-pipeline"
    ),
    "telemetry": "host-side spans/counters; no float device programs",
    "trace": "host-side chrome-trace writer; no device programs",
    "monitor": "host-side HTTP surface; no device programs",
    "ledger": (
        "the ledger measures seconds and bytes in f64 host floats; it "
        "traces no device reductions"
    ),
    "health": (
        "sketches/calibration accumulate in f64 host floats; the "
        "device-side sentinel reduces are f32-only O(1) scalars"
    ),
    "pilot": (
        "the pilot serves the same ScorePrograms ladder the serving "
        "numerics contract audits and trains through the fused-fit "
        "contract's programs; it adds no reductions of its own"
    ),
    "resilience-retry": (
        "host-side retry machinery; zero device programs is already "
        "its tier-2 contract"
    ),
    "fleet-obs": (
        "host-side bundle shipping and trace merge in f64 host "
        "floats; its tier-2 contract proves byte-identical device "
        "programs with the fleet armed — it traces no reductions"
    ),
    "evaluation-scoring": (
        "evaluators reduce f32 scores at f64 numpy precision on host; "
        "no bf16 operand can reach them (scores are upcast at the "
        "serve/fit boundary)"
    ),
}

# Rounding constants of the budget-formula language: one bf16 storage
# rounding is 2^-9 relative (8 mantissa bits incl. the implicit one),
# one f32 accumulation step is 2^-24.
U16 = 2.0 ** -9
U32 = 2.0 ** -24

# Order-nondeterministic primitive families for the determinism census:
# XLA does not pin the combination order of colliding scatter indices,
# so any of these in a program needs a deterministic-by-construction
# declaration (sorted ids, unique ids) or a reasoned waiver.
NONDETERMINISTIC_FAMILIES = frozenset({
    "scatter-add",
    "scatter-mul",
    "scatter",
})


# --------------------------------------------------------------------------
# data model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ProgramNumerics:
    """One traced entry point under the dtype-flow walk: its closed
    jaxpr and per-program dims merged over the trace dims when pricing
    error-budget formulas."""

    name: str
    jaxpr: Any
    dims: dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class NumericsTrace:
    """Everything a contract builder hands the checks."""

    programs: dict[str, ProgramNumerics] = dataclasses.field(
        default_factory=dict
    )
    dims: dict[str, float] = dataclasses.field(default_factory=dict)
    notes: list[str] = dataclasses.field(default_factory=list)
    # memoized flow states, keyed by program name (filled lazily)
    _flows: dict[str, "FlowState"] = dataclasses.field(
        default_factory=dict, repr=False
    )


@dataclasses.dataclass(frozen=True)
class NumericsContract:
    """One NUMERICS_AUDIT declaration, resolved."""

    name: str
    entry: str
    build: Callable[[], NumericsTrace]
    covers: tuple[str, ...] = ()
    # program name (or fnmatch pattern) -> error-budget formula over
    # dims (+ u16/u32/min/max)
    budgets: dict[str, str] = dataclasses.field(default_factory=dict)
    # "program:family" fnmatch pattern -> deterministic-by-construction
    # reason for the determinism census
    deterministic: dict[str, str] = dataclasses.field(default_factory=dict)
    tolerance: float = 1.5
    suppress: dict[str, str] = dataclasses.field(default_factory=dict)


def _finding(
    contract: NumericsContract, rule: str, message: str
) -> Finding:
    return Finding(
        rule=rule, path=f"<{contract.name}>", line=0, col=0, message=message
    )


# --------------------------------------------------------------------------
# the dtype-provenance lattice
# --------------------------------------------------------------------------


@dataclasses.dataclass
class VarInfo:
    """Per-value lattice state, joined across operands at each eqn."""

    bf16: bool = False          # lineage passed through bf16 storage
    rounds: int = 0             # deepest chain of narrowing roundings
    lo_bounded: bool = False    # value has a static lower bound
    hi_bounded: bool = False    # value has a static upper bound
    unstable_exp: bool = False  # derives from exp() of an unbounded arg
    acc_out: bool = False       # is (a cast/reshape of) an f32
    #                             accumulator output over bf16 lineage
    carries: frozenset = frozenset()  # loop-carry tokens in the lineage

    def join(self, other: "VarInfo") -> "VarInfo":
        return VarInfo(
            bf16=self.bf16 or other.bf16,
            rounds=max(self.rounds, other.rounds),
            lo_bounded=False,
            hi_bounded=False,
            unstable_exp=self.unstable_exp or other.unstable_exp,
            acc_out=False,
            carries=self.carries | other.carries,
        )


@dataclasses.dataclass
class FlowEvent:
    kind: str    # a NUMERICS_RULES key minus the "numerics-" prefix
    detail: str


@dataclasses.dataclass
class FlowState:
    """Accumulated result of walking one program's jaxpr."""

    events: list[FlowEvent] = dataclasses.field(default_factory=list)
    families: set[str] = dataclasses.field(default_factory=set)
    max_rounds: int = 0
    reduce_len: float = 0.0  # summed static length of f32 accumulations
    #                          over bf16-lineage operands
    notes: list[str] = dataclasses.field(default_factory=list)

    @property
    def derived_bound(self) -> float:
        return U16 * self.max_rounds + U32 * self.reduce_len


def _aval(v: Any) -> Any:
    a = getattr(v, "aval", None)
    # pallas kernels take Refs; unwrap to the carried array aval
    return getattr(a, "inner_aval", a)


def _dtype(v: Any):
    a = _aval(v)
    return getattr(a, "dtype", None)


def _shape(v: Any) -> tuple:
    a = _aval(v)
    return tuple(getattr(a, "shape", ()) or ())


def _is_bf16(dt) -> bool:
    return dt is not None and str(dt) == "bfloat16"


def _is_f32(dt) -> bool:
    return dt is not None and str(dt) == "float32"


def _is_narrow_float(dt) -> bool:
    return dt is not None and str(dt) in (
        "bfloat16", "float16", "float8_e4m3fn", "float8_e5m2"
    )


def _is_float(dt) -> bool:
    return dt is not None and (
        str(dt).startswith("float") or str(dt).startswith("bfloat")
    )


def _is_literal(v: Any) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


def _count(shape: Iterable[int]) -> float:
    out = 1.0
    for s in shape:
        out *= float(s)
    return out


# ops that move values without arithmetic: acc_out survives them (a
# reshape of an accumulator output is still an accumulator output),
# everything else is joined generically
_SHAPE_OPS = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "slice", "concatenate", "rev", "copy", "stop_gradient",
    "expand_dims",
})

# reduction-class primitives: (name -> True) means the output dtype IS
# the accumulator dtype
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_window_sum", "cumsum",
    "cumlogsumexp", "dot_general",
})

_PASSTHROUGH_TRACE = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "copy", "stop_gradient",
})


def _closed(j: Any) -> Any:
    """Normalize ClosedJaxpr-or-Jaxpr to the open Jaxpr."""
    return getattr(j, "jaxpr", j)


def _literal_info(v: Any) -> VarInfo:
    return VarInfo(
        bf16=_is_bf16(_dtype(v)),
        rounds=1 if _is_bf16(_dtype(v)) else 0,
        lo_bounded=True,
        hi_bounded=True,
    )


def _seed_info(v: Any) -> VarInfo:
    dt = _dtype(v)
    if _is_bf16(dt):
        # an entry operand already stored in bf16 carries one rounding
        # relative to the real-valued quantity it represents
        return VarInfo(bf16=True, rounds=1)
    return VarInfo()


def _operand_infos(
    eqn: Any, env: dict, default: Callable[[Any], VarInfo] = _seed_info
) -> list[VarInfo]:
    out = []
    for v in eqn.invars:
        if _is_literal(v):
            out.append(_literal_info(v))
        else:
            out.append(env.get(v) or default(v))
    return out


def _defining(jaxpr: Any) -> dict:
    return {ov: eqn for eqn in jaxpr.eqns for ov in eqn.outvars}


def _traces_to(
    var: Any, target: Any, defs: dict, depth: int = 0
) -> bool:
    """Does ``var``'s def chain reach ``target`` through arithmetic
    accumulation ops and shape/cast passthroughs only? (Used to decide
    whether a scan carry ACCUMULATES — new = old + delta — versus being
    rebuilt from scratch each iteration.)"""
    if depth > 64:
        return False
    if var is target:
        return True
    eqn = defs.get(var)
    if eqn is None:
        return False
    if eqn.primitive.name in _PASSTHROUGH_TRACE or eqn.primitive.name in (
        "add", "sub", "add_any"
    ):
        return any(
            _traces_to(v, target, defs, depth + 1)
            for v in eqn.invars
            if not _is_literal(v)
        )
    return False


def analyze_jaxpr(
    jaxpr: Any,
    in_infos: list[VarInfo],
    state: FlowState,
    *,
    in_loop: bool = False,
) -> list[VarInfo]:
    """Walk one (open) jaxpr with the given entry infos; returns the
    outvar infos and accumulates events/lengths into ``state``."""
    jaxpr = _closed(jaxpr)
    env: dict[Any, VarInfo] = {}
    for v, info in zip(jaxpr.invars, in_infos):
        env[v] = info
    for v in jaxpr.constvars:
        env[v] = _seed_info(v)

    uses: Counter = Counter()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not _is_literal(v):
                uses[v] += 1
    for v in jaxpr.outvars:
        if not _is_literal(v):
            uses[v] += 1
    defs = _defining(jaxpr)

    for eqn in jaxpr.eqns:
        _apply_eqn(eqn, env, state, uses, defs, in_loop=in_loop)
        for ov in eqn.outvars:
            info = env.get(ov)
            if info is not None and info.rounds > state.max_rounds:
                state.max_rounds = info.rounds

    out: list[VarInfo] = []
    for v in jaxpr.outvars:
        if _is_literal(v):
            out.append(_literal_info(v))
        else:
            out.append(env.get(v) or _seed_info(v))
    return out


def _join_all(infos: list[VarInfo]) -> VarInfo:
    out = VarInfo()
    for i in infos:
        out = out.join(i)
    return out


def _apply_eqn(
    eqn: Any,
    env: dict,
    state: FlowState,
    uses: Counter,
    defs: dict,
    *,
    in_loop: bool,
) -> None:
    name = eqn.primitive.name
    infos = _operand_infos(eqn, env)
    joined = _join_all(infos)

    if name == "convert_element_type":
        _apply_convert(eqn, env, state, uses, defs, infos[0],
                       in_loop=in_loop)
        return

    if name == "scan":
        _apply_scan(eqn, env, state, infos)
        return
    if name == "while":
        _apply_while(eqn, env, state, infos)
        return
    if name == "cond":
        _apply_cond(eqn, env, state, infos)
        return
    if name == "pallas_call":
        _apply_pallas(eqn, env, state, infos)
        return
    sub = _mapped_sub_jaxpr(eqn)
    if sub is not None:
        outs = analyze_jaxpr(sub, infos, state, in_loop=in_loop)
        for ov, info in zip(eqn.outvars, outs):
            env[ov] = info
        return

    if name in _REDUCE_PRIMS:
        _apply_reduction(eqn, env, state, infos, joined)
        return
    if name in NONDETERMINISTIC_FAMILIES:
        state.families.add(name)
        _apply_scatter(eqn, env, state, infos, joined)
        return
    if name == "exp":
        op = infos[0]
        out = joined
        out = dataclasses.replace(
            out,
            lo_bounded=True,
            hi_bounded=op.hi_bounded,
            unstable_exp=op.unstable_exp or not op.hi_bounded,
        )
        env[eqn.outvars[0]] = out
        return

    # bounds-aware elementwise transfer
    out = joined
    if name in ("min", "max"):
        a, b = infos[0], infos[1]
        if name == "min":
            out = dataclasses.replace(
                out,
                hi_bounded=a.hi_bounded or b.hi_bounded,
                lo_bounded=a.lo_bounded and b.lo_bounded,
            )
        else:
            out = dataclasses.replace(
                out,
                lo_bounded=a.lo_bounded or b.lo_bounded,
                hi_bounded=a.hi_bounded and b.hi_bounded,
            )
    elif name == "clamp":
        lo, _x, hi = infos[0], infos[1], infos[2]
        out = dataclasses.replace(
            out, lo_bounded=lo.lo_bounded, hi_bounded=hi.hi_bounded
        )
    elif name == "abs":
        out = dataclasses.replace(out, lo_bounded=True,
                                  hi_bounded=infos[0].hi_bounded
                                  and infos[0].lo_bounded)
    elif name == "neg":
        out = dataclasses.replace(
            out,
            lo_bounded=infos[0].hi_bounded,
            hi_bounded=infos[0].lo_bounded,
        )
    elif name in ("logistic", "tanh", "erf", "sin", "cos", "sign"):
        out = dataclasses.replace(out, lo_bounded=True, hi_bounded=True)
    elif name in ("add", "sub"):
        a, b = infos[0], infos[1]
        if name == "add":
            out = dataclasses.replace(
                out,
                lo_bounded=a.lo_bounded and b.lo_bounded,
                hi_bounded=a.hi_bounded and b.hi_bounded,
            )
        else:
            out = dataclasses.replace(
                out,
                lo_bounded=a.lo_bounded and b.hi_bounded,
                hi_bounded=a.hi_bounded and b.lo_bounded,
            )
    elif name in _SHAPE_OPS:
        # pure data movement: bounds AND accumulator-output status ride
        out = dataclasses.replace(
            out,
            lo_bounded=infos[0].lo_bounded,
            hi_bounded=infos[0].hi_bounded,
            acc_out=infos[0].acc_out,
        )
    elif name == "select_n":
        cases = infos[1:]
        out = dataclasses.replace(
            out,
            lo_bounded=all(c.lo_bounded for c in cases),
            hi_bounded=all(c.hi_bounded for c in cases),
        )
    for ov in eqn.outvars:
        env[ov] = out


def _apply_convert(
    eqn: Any,
    env: dict,
    state: FlowState,
    uses: Counter,
    defs: dict,
    op: VarInfo,
    *,
    in_loop: bool,
) -> None:
    src = eqn.invars[0]
    dst = eqn.outvars[0]
    src_dt, dst_dt = _dtype(src), _dtype(dst)
    out = dataclasses.replace(
        op, lo_bounded=op.lo_bounded, hi_bounded=op.hi_bounded
    )
    narrowing = (
        _is_float(src_dt)
        and _is_narrow_float(dst_dt)
        and not _is_narrow_float(src_dt)
    )
    if narrowing:
        out = dataclasses.replace(
            out, bf16=True, rounds=op.rounds + 1, acc_out=op.acc_out
        )
        # downcast of a fresh accumulator output: remembered; flagged
        # only if the bf16 value is re-reduced (_apply_reduction)
        if in_loop and op.carries:
            state.events.append(FlowEvent(
                "scan-recast",
                f"{_src(eqn)}: loop-carried value re-rounded to "
                f"{dst_dt} every iteration",
            ))
        # pointless round-trip: this bf16 value's ONLY use is an
        # immediate upcast — the value is rounded twice, stored never
        if uses.get(dst, 0) == 1:
            for e2 in _consumers_of(dst, defs, uses):
                if (
                    e2.primitive.name == "convert_element_type"
                    and not _is_narrow_float(_dtype(e2.outvars[0]))
                ):
                    state.events.append(FlowEvent(
                        "cast-roundtrip",
                        f"{_src(eqn)}: f32->bf16->f32 round-trip "
                        "(single-use downcast immediately upcast)",
                    ))
    else:
        out = dataclasses.replace(out, acc_out=op.acc_out)
    env[dst] = out


def _consumers_of(var: Any, defs: dict, uses: Counter) -> list:
    # defs maps outvar -> eqn; consumers need the eqn list — walk the
    # defining jaxpr's eqns lazily via the defs values' containers
    seen = []
    for eqn in {id(e): e for e in defs.values()}.values():
        if any(v is var for v in eqn.invars):
            seen.append(eqn)
    return seen


def _src(eqn: Any) -> str:
    """A short human-readable source anchor for an eqn."""
    try:
        from jax._src import source_info_util

        name = source_info_util.summarize(eqn.source_info)
        if name:
            return f"{eqn.primitive.name} @ {name.rsplit('/', 1)[-1]}"
    except Exception:  # noqa: BLE001 — source info is best-effort
        pass
    return eqn.primitive.name


def _reduction_length(eqn: Any) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        shape = _shape(eqn.invars[0])
        return _count(shape[d] for d in lhs_c) or 1.0
    in_n = _count(_shape(eqn.invars[0]))
    out_n = _count(_shape(eqn.outvars[0])) or 1.0
    return max(in_n / out_n, 1.0)


def _apply_reduction(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo],
    joined: VarInfo,
) -> None:
    out_dt = _dtype(eqn.outvars[0])
    float_ops = [
        i for i, v in zip(infos, eqn.invars)
        if _dtype(v) is not None
        and (str(_dtype(v)).startswith("float") or _is_bf16(_dtype(v)))
    ]
    bf16_lineage = any(i.bf16 for i in float_ops)
    if bf16_lineage and _is_narrow_float(out_dt):
        state.events.append(FlowEvent(
            "bf16-accumulation",
            f"{_src(eqn)}: {eqn.primitive.name} over bf16 lineage "
            f"accumulates in {out_dt} — use an f32 accumulator "
            "(ops.precision.acc_sum/acc_einsum or "
            "preferred_element_type=float32)",
        ))
    if any(i.acc_out for i in infos):
        state.events.append(FlowEvent(
            "acc-downcast",
            f"{_src(eqn)}: {eqn.primitive.name} re-reduces a value "
            "that was downcast from an f32 accumulator output — the "
            "accumulated precision was thrown away between stages",
        ))
    if any(i.unstable_exp for i in float_ops):
        state.events.append(FlowEvent(
            "unstable-exp",
            f"{_src(eqn)}: {eqn.primitive.name} reduces an exp() of an "
            "unbounded operand — clamp the argument at a documented "
            "threshold first (the ops/losses.py Poisson pattern)",
        ))
    acc_is_f32 = _is_f32(out_dt)
    if bf16_lineage and acc_is_f32:
        state.reduce_len += _reduction_length(eqn)
    out = dataclasses.replace(
        joined, acc_out=bf16_lineage and acc_is_f32
    )
    for ov in eqn.outvars:
        env[ov] = out


_ACCUMULATING_SCATTERS = frozenset({"scatter-add", "scatter-mul"})


def _apply_scatter(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo],
    joined: VarInfo,
) -> None:
    # plain `scatter` (an .at[].set overwrite) moves storage without
    # combining — an accumulation hazard only for the -add/-mul forms;
    # ALL forms join the determinism census (colliding indices combine
    # or overwrite in an unpinned order)
    accumulates = eqn.primitive.name in _ACCUMULATING_SCATTERS
    out_dt = _dtype(eqn.outvars[0])
    if accumulates and joined.bf16 and _is_narrow_float(out_dt):
        state.events.append(FlowEvent(
            "bf16-accumulation",
            f"{_src(eqn)}: {eqn.primitive.name} over bf16 lineage "
            f"accumulates in {out_dt} — upcast the operand to f32 "
            "before scattering (the segment_reduce fallback pattern)",
        ))
    if any(i.unstable_exp for i in infos):
        state.events.append(FlowEvent(
            "unstable-exp",
            f"{_src(eqn)}: {eqn.primitive.name} scatters an exp() of "
            "an unbounded operand",
        ))
    if accumulates and joined.bf16 and _is_f32(out_dt):
        # count one accumulation step per scattered element
        state.reduce_len += _count(_shape(eqn.invars[-1]))
    for ov in eqn.outvars:
        env[ov] = dataclasses.replace(joined, acc_out=False)


def _mapped_sub_jaxpr(eqn: Any) -> Any | None:
    """A sub-jaxpr whose invars map 1:1 onto the eqn's operands
    (pjit, closed_call, custom_jvp/vjp, remat)."""
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key) if isinstance(eqn.params, dict) else None
        if sub is None:
            continue
        inner = _closed(sub)
        if hasattr(inner, "eqns") and len(inner.invars) == len(eqn.invars):
            return inner
    return None


def _apply_scan(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo]
) -> None:
    body = _closed(eqn.params["jaxpr"])
    nc = eqn.params.get("num_consts", 0)
    k = eqn.params.get("num_carry", 0)
    length = float(eqn.params.get("length", 1) or 1)

    def seed() -> list[VarInfo]:
        inner: list[VarInfo] = []
        for i, info in enumerate(infos):
            if nc <= i < nc + k:
                info = dataclasses.replace(
                    info, carries=info.carries | {(id(eqn), i - nc)}
                )
            inner.append(info)
        return inner

    # pass 1 (throwaway state): let carry-out info reach carry-in so
    # booleans stabilize; pass 2 records the real events
    probe = FlowState()
    first = analyze_jaxpr(body, seed(), probe, in_loop=True)
    carried = seed()
    for i in range(k):
        carried[nc + i] = carried[nc + i].join(first[i])
        carried[nc + i] = dataclasses.replace(
            carried[nc + i],
            rounds=infos[nc + i].rounds,  # rounds re-derived below
            carries=carried[nc + i].carries | {(id(eqn), i)},
        )
    sub = FlowState()
    outs = analyze_jaxpr(body, carried, sub, in_loop=True)

    # per-iteration rounding deltas compound across the static trip
    # count; body reduction lengths likewise run once per iteration
    state.events.extend(sub.events)
    state.families |= sub.families
    state.reduce_len += sub.reduce_len * length
    state.max_rounds = max(state.max_rounds, sub.max_rounds)
    defs = _defining(body)
    for i in range(k):
        in_info = carried[nc + i]
        out_info = outs[i]
        delta = max(0, out_info.rounds - in_info.rounds)
        total_rounds = in_info.rounds + int(delta * length)
        out_info = dataclasses.replace(out_info, rounds=total_rounds)
        state.max_rounds = max(state.max_rounds, total_rounds)
        # a bf16 carry that ACCUMULATES (new = old + delta) rounds its
        # running value every iteration — bf16 accumulation, whatever
        # dtype the increments had
        ov = body.outvars[i]
        carry_dt = _dtype(eqn.outvars[i]) if i < len(eqn.outvars) else None
        def_eqn = defs.get(ov)
        if (
            _is_narrow_float(carry_dt)
            and def_eqn is not None
            and def_eqn.primitive.name in ("add", "sub", "add_any")
            and _traces_to(ov, body.invars[nc + i], defs)
        ):
            state.events.append(FlowEvent(
                "bf16-accumulation",
                f"{_src(def_eqn)}: scan carry {i} accumulates in "
                f"{carry_dt} across {int(length)} iterations — carry "
                "the running value in f32 and cast on store",
            ))
        outs[i] = out_info
    for ov, info in zip(eqn.outvars, outs):
        env[ov] = info


def _apply_while(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo]
) -> None:
    body = _closed(eqn.params["body_jaxpr"])
    cn = eqn.params.get("cond_nconsts", 0)
    bn = eqn.params.get("body_nconsts", 0)
    # eqn operands: cond consts, body consts, carry
    carry_infos = infos[cn + bn:]
    body_in = list(infos[cn:cn + bn]) + [
        dataclasses.replace(ci, carries=ci.carries | {(id(eqn), i)})
        for i, ci in enumerate(carry_infos)
    ]
    probe = FlowState()
    first = analyze_jaxpr(body, body_in, probe, in_loop=True)
    for i in range(len(carry_infos)):
        body_in[bn + i] = body_in[bn + i].join(first[i])
        body_in[bn + i] = dataclasses.replace(
            body_in[bn + i],
            rounds=carry_infos[i].rounds,
            carries=body_in[bn + i].carries | {(id(eqn), i)},
        )
    sub = FlowState()
    outs = analyze_jaxpr(body, body_in, sub, in_loop=True)
    state.events.extend(sub.events)
    state.families |= sub.families
    # trip count is dynamic: charge the body once and note it
    state.reduce_len += sub.reduce_len
    state.max_rounds = max(state.max_rounds, sub.max_rounds)
    if any(
        max(0, outs[i].rounds - body_in[bn + i].rounds) > 0
        for i in range(len(carry_infos))
    ):
        state.notes.append(
            "while-loop carry gains a rounding per iteration with a "
            "dynamic trip count — bound not statically priceable"
        )
    for ov, info in zip(eqn.outvars, outs):
        env[ov] = info


def _apply_cond(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo]
) -> None:
    branches = eqn.params["branches"]
    operand_infos = infos[1:]
    branch_outs: list[list[VarInfo]] = []
    for br in branches:
        branch_outs.append(
            analyze_jaxpr(_closed(br), list(operand_infos), state)
        )
    for i, ov in enumerate(eqn.outvars):
        joined = branch_outs[0][i]
        for bo in branch_outs[1:]:
            joined = joined.join(bo[i])
        env[ov] = joined


def _apply_pallas(
    eqn: Any, env: dict, state: FlowState, infos: list[VarInfo]
) -> None:
    """The kernel boundary: recurse into the kernel jaxpr when its ref
    arity maps, and regardless check the boundary dtype contract —
    bf16 operands must come out through f32 outputs."""
    joined = _join_all(infos)
    out_dts = [_dtype(ov) for ov in eqn.outvars]
    if joined.bf16 and any(_is_narrow_float(dt) for dt in out_dts):
        state.events.append(FlowEvent(
            "bf16-accumulation",
            f"{_src(eqn)}: pallas_call with bf16 operands writes a "
            "narrow-float output — the kernel accumulator must be f32 "
            "(out_shape float32, preferred_element_type=float32)",
        ))
    sub = eqn.params.get("jaxpr") if isinstance(eqn.params, dict) else None
    if sub is not None:
        inner = _closed(sub)
        try:
            seeds = [_seed_info(v) for v in inner.invars]
            analyze_jaxpr(inner, seeds, state)
        except Exception:  # noqa: BLE001 — kernel walk is best-effort
            state.notes.append(
                "pallas kernel jaxpr not walkable on this jax version; "
                "boundary dtype contract checked only"
            )
    if joined.bf16:
        # charge the kernel's streamed elements to the f32 accumulator
        state.reduce_len += max(
            (_count(_shape(v)) for v in eqn.invars), default=0.0
        )
    for ov in eqn.outvars:
        env[ov] = dataclasses.replace(
            joined, acc_out=joined.bf16
        )


def flow_program(prog: ProgramNumerics) -> FlowState:
    """Walk one traced program's jaxpr end to end."""
    jaxpr = _closed(prog.jaxpr)
    state = FlowState()
    seeds = [_seed_info(v) for v in jaxpr.invars]
    analyze_jaxpr(jaxpr, seeds, state)
    return state


def _flows(trace: NumericsTrace) -> dict[str, FlowState]:
    for name, prog in trace.programs.items():
        if name not in trace._flows:
            trace._flows[name] = flow_program(prog)
    return trace._flows


# --------------------------------------------------------------------------
# budget pricing (the MEMORY_AUDIT formula language + u16/u32)
# --------------------------------------------------------------------------


def _price(formula: str, dims: dict[str, float]) -> float:
    scope = dict(dims)
    scope["min"] = min
    scope["max"] = max
    scope["u16"] = U16
    scope["u32"] = U32
    return float(eval(formula, {"__builtins__": {}}, scope))  # noqa: S307


def _budget_for(
    contract: NumericsContract, program: str
) -> str | None:
    if program in contract.budgets:
        return contract.budgets[program]
    for pat, formula in contract.budgets.items():
        if fnmatch.fnmatchcase(program, pat):
            return formula
    return None


# --------------------------------------------------------------------------
# the checks
# --------------------------------------------------------------------------

_EVENT_RULES = {
    "bf16-accumulation": "numerics-bf16-accumulation",
    "cast-roundtrip": "numerics-cast-roundtrip",
    "acc-downcast": "numerics-acc-downcast",
    "scan-recast": "numerics-scan-recast",
    "unstable-exp": "numerics-unstable-exp",
}


def check_flow(
    contract: NumericsContract, trace: NumericsTrace
) -> Iterator[Finding]:
    """Accumulation-dtype audit + cast census + unstable-exp, from the
    walked flow events."""
    for name, flow in _flows(trace).items():
        seen: set[tuple[str, str]] = set()
        for ev in flow.events:
            key = (ev.kind, ev.detail)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                contract,
                _EVENT_RULES[ev.kind],
                f"program {name!r}: {ev.detail}",
            )


def check_error_budgets(
    contract: NumericsContract, trace: NumericsTrace
) -> Iterator[Finding]:
    """Price every declared error budget against the derived bound,
    both directions (the tier-4 dual gate)."""
    tol = contract.tolerance
    flows = _flows(trace)
    for name, prog in trace.programs.items():
        flow = flows[name]
        derived = flow.derived_bound
        formula = _budget_for(contract, name)
        if formula is None:
            yield _finding(
                contract,
                "numerics-contract",
                f"traced program {name!r} has no declared error "
                "budget: every audited entry point must carry a "
                "worst-case relative-error formula",
            )
            continue
        dims = {**trace.dims, **prog.dims}
        try:
            declared = _price(formula, dims)
        except Exception as exc:  # noqa: BLE001 — rotten formula IS the finding
            yield _finding(
                contract,
                "numerics-stale-budget",
                f"program {name!r}: error budget {formula!r} no longer "
                f"evaluates over dims {sorted(dims)}: {exc!r}",
            )
            continue
        if derived > declared * tol:
            yield _finding(
                contract,
                "numerics-undeclared-error",
                f"program {name!r}: derived error bound {derived:.3e} "
                f"(rounds={flow.max_rounds}, "
                f"reduce_len={flow.reduce_len:.0f}) exceeds the "
                f"declared budget {formula!r} = {declared:.3e} beyond "
                f"the {tol}x tolerance — error grew that the contract "
                "does not price",
            )
        elif declared > derived * tol and declared - derived > 1e-6:
            yield _finding(
                contract,
                "numerics-stale-budget",
                f"program {name!r}: declared budget {formula!r} = "
                f"{declared:.3e} prices beyond {tol}x the derived "
                f"bound {derived:.3e} — the formula rotted above "
                "reality and would mask real error growth",
            )
    for pat in contract.budgets:
        if not any(
            pat == name or fnmatch.fnmatchcase(name, pat)
            for name in trace.programs
        ):
            yield _finding(
                contract,
                "numerics-contract",
                f"error-budget key {pat!r} matches no traced program — "
                "stale declaration",
            )


def _determinism_reason(
    contract: NumericsContract, program: str, family: str
) -> str | None:
    key = f"{program}:{family}"
    if key in contract.deterministic:
        return contract.deterministic[key]
    for pat, reason in contract.deterministic.items():
        if fnmatch.fnmatchcase(key, pat):
            return reason
    return None


def check_determinism(
    contract: NumericsContract, trace: NumericsTrace
) -> Iterator[Finding]:
    """Every order-nondeterministic primitive family per program must
    be declared deterministic-by-construction, with a reason."""
    flows = _flows(trace)
    present: set[str] = set()
    for name, flow in flows.items():
        for family in sorted(flow.families):
            present.add(f"{name}:{family}")
            reason = _determinism_reason(contract, name, family)
            if reason is None:
                yield _finding(
                    contract,
                    "numerics-nondeterministic-reduce",
                    f"program {name!r} contains {family!r} with no "
                    "deterministic-by-construction declaration — "
                    "declare WHY the combination order cannot matter "
                    "(sorted ids, unique ids) or restructure the "
                    "reduction",
                )
            elif not reason.strip():
                yield _finding(
                    contract,
                    "numerics-contract",
                    f"determinism declaration for {name}:{family} has "
                    "no reason — a declaration without a reason is a "
                    "gap, not a decision",
                )
    for pat, reason in contract.deterministic.items():
        if not reason or not reason.strip():
            yield _finding(
                contract,
                "numerics-contract",
                f"determinism declaration {pat!r} has no reason",
            )
        if not any(
            pat == key or fnmatch.fnmatchcase(key, pat)
            for key in present
        ):
            yield _finding(
                contract,
                "numerics-contract",
                f"determinism declaration {pat!r} matches no "
                "nondeterministic site in any traced program — stale "
                "declaration",
            )


CHECKS = (
    check_flow,
    check_error_budgets,
    check_determinism,
)


def run_checks(
    contract: NumericsContract, trace: NumericsTrace
) -> list[Finding]:
    """All numerics checks over one contract's trace, suppressions
    applied (suppressed findings are kept, with reasons, for the
    report — the tier-2/4 discipline)."""
    findings: list[Finding] = []
    for check in CHECKS:
        for f in check(contract, trace):
            reason = contract.suppress.get(f.rule)
            if reason is not None:
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=reason
                )
            findings.append(f)
    return findings


# --------------------------------------------------------------------------
# contract builders (named by the NUMERICS_AUDIT declarations)
# --------------------------------------------------------------------------


def build_precision_numerics() -> NumericsTrace:
    """Probe programs for the policy helpers themselves and the GLM
    loss families over bf16-stored margins — acc_sum/acc_einsum must
    accumulate f32, and every family's exp() must be dominated by a
    clamp (the Poisson stability fix)."""
    import jax
    import jax.numpy as jnp

    from photon_tpu.ops import losses
    from photon_tpu.ops import precision as px

    m, b, k = 4096, 128, 64
    bf = jnp.bfloat16
    f32 = np.float32
    S = jax.ShapeDtypeStruct

    def acc_sum_probe(x):
        return px.acc_sum(x)

    def acc_einsum_probe(a, v):
        return px.acc_einsum("bk,k->b", a, v)

    programs = {
        "acc_sum": ProgramNumerics(
            "acc_sum",
            jax.jit(acc_sum_probe).trace(S((m,), bf)).jaxpr,
            dims={},
        ),
        "acc_einsum": ProgramNumerics(
            "acc_einsum",
            jax.jit(acc_einsum_probe).trace(
                S((b, k), bf), S((k,), bf)
            ).jaxpr,
            dims={},
        ),
    }
    for loss in (losses.LOGISTIC, losses.SQUARED, losses.POISSON,
                 losses.SMOOTHED_HINGE):
        def family_probe(z, y, _l=loss):
            # margins arrive bf16-STORED (the fused sweep's score-carry
            # shape) and are upcast on read; loss, curvature, and link
            # each reduce with the sanctioned f32 accumulator
            zz = z.astype(jnp.float32)
            return (
                px.acc_sum(_l.loss(zz, y))
                + px.acc_sum(_l.dzz(zz, y))
                + px.acc_sum(_l.mean(zz))
            )

        programs[f"loss_{loss.name}"] = ProgramNumerics(
            f"loss_{loss.name}",
            jax.jit(family_probe).trace(  # photon: ignore[recompile-hazard] -- trace-only audit builder, one trace per family per audit run; nothing executes
                S((m,), bf), S((m,), f32)
            ).jaxpr,
            dims={},
        )
    return NumericsTrace(
        programs=programs,
        dims={"m": float(m), "b": float(b), "k": float(k)},
        notes=[
            "policy helpers + all four GLM families over bf16-stored "
            "margins (the score-carry shape); one storage rounding "
            "each, f32 accumulation"
        ],
    )


def build_fused_fit_numerics() -> NumericsTrace:
    """The fused whole-fit programs at BOTH precisions: the bf16
    variant is the policy under audit, the f32 variant is the control
    (zero bf16 lineage — a leak there is a policy bug too)."""
    from photon_tpu.algorithm.fused_fit import FusedFit
    from photon_tpu.analysis import program as tier2

    est, data = tier2._tiny_glmix()
    datasets, _ = est.prepare(data)
    n = data.num_samples
    coords = est._build_coordinates(datasets, {}, {}, logical_rows=n)
    coord = coords["per-user"]
    ds = getattr(coord, "inner", coord).dataset
    programs: dict[str, ProgramNumerics] = {}
    for precision, tag in (("float32", "f32"), ("bfloat16", "bf16")):
        fused = FusedFit(
            coords, est.update_sequence, 2, set(), precision=precision
        )
        mat = fused._mat_jit.trace(fused._mat_operands(coords))
        fit = fused.trace(coords)
        programs[f"materialize_{tag}"] = ProgramNumerics(
            f"materialize_{tag}", mat.jaxpr
        )
        programs[f"fit_{tag}"] = ProgramNumerics(f"fit_{tag}", fit.jaxpr)
    return NumericsTrace(
        programs=programs,
        dims={
            "n": float(n),
            "d": 5.0,
            "du": 4.0,
            "e": float(ds.num_entities),
            "s": float(ds.max_sub_dim),
            "iters": 2.0,
            "coords": 2.0,
        },
        notes=[
            "tier-2 tiny GLMix fixture traced through FusedFit at f32 "
            "(control: no bf16 lineage) and bf16 (the audited policy)"
        ],
    )


def build_segment_reduce_numerics() -> NumericsTrace:
    """The segment-reduce at the kernel boundary AND the fallback, on
    bf16 values — both must accumulate f32."""
    import functools
    import os

    import jax

    from photon_tpu.ops import segment_reduce as sr

    m, nseg = 4096, 2048
    S = jax.ShapeDtypeStruct
    programs: dict[str, ProgramNumerics] = {}
    prev = os.environ.get("PHOTON_SEGMENT_KERNEL")
    for mode, tag in (("force", "kernel"), ("off", "fallback")):
        os.environ["PHOTON_SEGMENT_KERNEL"] = mode
        try:
            fn = functools.partial(
                sr.sorted_segment_sum,
                num_segments=nseg,
                multiplicity=2,
                interpret=sr.interpret_required(),
            )
            traced = jax.jit(fn).trace(  # photon: ignore[recompile-hazard] -- trace-only audit builder, one trace per engage mode per audit run; nothing executes
                S((m,), jax.numpy.bfloat16), S((m,), np.int32)
            )
            programs[f"segment_sum_{tag}"] = ProgramNumerics(
                f"segment_sum_{tag}", traced.jaxpr
            )
        finally:
            if prev is None:  # photon: ignore[spmd-host-divergence] -- env save/restore of the audit fixture's kernel flag; host-local tooling, not fleet code
                os.environ.pop("PHOTON_SEGMENT_KERNEL", None)
            else:
                os.environ["PHOTON_SEGMENT_KERNEL"] = prev
    return NumericsTrace(
        programs=programs,
        dims={"m": float(m), "nseg": float(nseg)},
        notes=[
            "sorted_segment_sum on bf16 values through the forced "
            "Pallas kernel (interpret off-TPU) and the XLA fallback"
        ],
    )


def build_serve_kernel_numerics() -> NumericsTrace:
    """The fused serve kernel over bf16 tables (PHOTON_SERVE_KERNEL
    forced; env restored after) — the production serving precision
    through the pallas path, next to ``build_serving_numerics``'s jit
    fallback on the same fixture."""
    import os

    from photon_tpu.analysis.memory import _tiny_game_model
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 7, 3, 6
    model = _tiny_game_model(
        d, e, s, du, proj_seed=1234, rng_seed=20260803
    )
    ladder = ShapeLadder((1, 8))
    prev = os.environ.get("PHOTON_SERVE_KERNEL")
    os.environ["PHOTON_SERVE_KERNEL"] = "force"
    try:
        tables = CoefficientTables.from_game_model(model, "bfloat16")
        programs = ScorePrograms(
            tables, ladder=ladder, compile_now=False
        )
        if not programs.use_kernel:
            raise RuntimeError(
                "PHOTON_SERVE_KERNEL=force did not engage the fused "
                "kernel — the serve-kernel numerics contract audits "
                "nothing"
            )
        out = {
            f"serve_kernel_b{r}": ProgramNumerics(
                f"serve_kernel_b{r}",
                programs.trace(r).jaxpr,
                dims={"rung": float(r)},
            )
            for r in ladder.rungs
        }
    finally:
        if prev is None:  # photon: ignore[spmd-host-divergence] -- env save/restore of the audit fixture's kernel flag; host-local tooling, not fleet code
            os.environ.pop("PHOTON_SERVE_KERNEL", None)
        else:
            os.environ["PHOTON_SERVE_KERNEL"] = prev
    return NumericsTrace(
        programs=out,
        dims={
            "d": float(d), "e": float(e), "s": float(s), "du": float(du),
        },
        notes=[
            f"fused kernel ladder {ladder.rungs} over BF16 tables, "
            "interpret-path lowering; request payloads f32"
        ],
    )


def build_serving_numerics() -> NumericsTrace:
    """The serve score ladder over bf16 coefficient tables — the
    production mixed-precision serving path."""
    from photon_tpu.analysis.memory import _tiny_game_model
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 7, 3, 6
    model = _tiny_game_model(
        d, e, s, du, proj_seed=1234, rng_seed=20260803
    )
    ladder = ShapeLadder((1, 8))
    tables = CoefficientTables.from_game_model(model, "bfloat16")
    programs = ScorePrograms(tables, ladder=ladder, compile_now=False)
    out: dict[str, ProgramNumerics] = {}
    for r in ladder.rungs:
        traced = programs.trace(r)
        out[f"score_b{r}"] = ProgramNumerics(
            f"score_b{r}", traced.jaxpr, dims={"rung": float(r)}
        )
    return NumericsTrace(
        programs=out,
        dims={
            "d": float(d), "e": float(e), "s": float(s), "du": float(du),
        },
        notes=[
            f"score ladder {ladder.rungs} over BF16 tables (the "
            "production serving precision); request payloads f32"
        ],
    )


_BUILDERS: dict[str, Callable[[], NumericsTrace]] = {
    "build_precision_numerics": build_precision_numerics,
    "build_fused_fit_numerics": build_fused_fit_numerics,
    "build_segment_reduce_numerics": build_segment_reduce_numerics,
    "build_serve_kernel_numerics": build_serve_kernel_numerics,
    "build_serving_numerics": build_serving_numerics,
}


def contract_from_declaration(spec: dict) -> NumericsContract:
    builder = spec.get("builder")
    if builder not in _BUILDERS:
        raise ValueError(
            f"NUMERICS_AUDIT declaration {spec.get('name')!r} names "
            f"unknown builder {builder!r}"
        )
    return NumericsContract(
        name=spec["name"],
        entry=spec["entry"],
        build=_BUILDERS[builder],
        covers=tuple(spec.get("covers", ())),
        budgets=dict(spec.get("budgets", {})),
        deterministic=dict(spec.get("deterministic", {})),
        tolerance=float(spec.get("tolerance", 1.5)),
        suppress=dict(spec.get("suppress", {})),
    )


def collect_contracts() -> list[NumericsContract]:
    """The repo's declared numerics-contract registry."""
    specs: list[dict] = []
    for modname in NUMERICS_DECLARING_MODULES:
        mod = importlib.import_module(modname)
        decl = getattr(mod, "NUMERICS_AUDIT", None)
        if decl is None:
            raise ValueError(
                f"{modname} is a numerics-declaring module but exports "
                "no NUMERICS_AUDIT"
            )
        specs.extend(decl if isinstance(decl, (list, tuple)) else [decl])
    return [contract_from_declaration(s) for s in specs]


def check_coverage(
    contracts: Iterable[NumericsContract],
) -> list[Finding]:
    """Every tier-2 entry point carries a numerics contract or a
    reasoned waiver — and no waiver outlives its reason."""
    from photon_tpu.analysis import program as tier2

    tier2_names = {c.name for c in tier2.collect_contracts()}
    covered: dict[str, str] = {}
    findings: list[Finding] = []
    anchor = NumericsContract(
        name="numerics-coverage", entry="analysis.numerics",
        build=NumericsTrace,
    )
    for c in contracts:
        for name in c.covers:
            if name not in tier2_names:
                findings.append(
                    _finding(
                        anchor,
                        "numerics-contract",
                        f"numerics contract {c.name!r} covers unknown "
                        f"tier-2 contract {name!r}",
                    )
                )
            covered[name] = c.name
    for name, reason in TIER2_WAIVERS.items():
        if name not in tier2_names:
            findings.append(
                _finding(
                    anchor,
                    "numerics-contract",
                    f"stale waiver: {name!r} is not a tier-2 contract",
                )
            )
        elif name in covered:
            findings.append(
                _finding(
                    anchor,
                    "numerics-contract",
                    f"stale waiver: {name!r} is covered by numerics "
                    f"contract {covered[name]!r} — drop the waiver",
                )
            )
        if not reason or not reason.strip():
            findings.append(
                _finding(
                    anchor,
                    "numerics-contract",
                    f"waiver for {name!r} has no reason — a waiver "
                    "without a reason is a gap, not a decision",
                )
            )
    for name in sorted(tier2_names):
        if name not in covered and name not in TIER2_WAIVERS:
            findings.append(
                _finding(
                    anchor,
                    "numerics-contract",
                    f"tier-2 contract {name!r} has no NUMERICS_AUDIT "
                    "coverage and no waiver: audit its dtype flow or "
                    "add a reasoned TIER2_WAIVERS entry",
                )
            )
    return findings


# --------------------------------------------------------------------------
# the audit driver
# --------------------------------------------------------------------------


def audit(
    contracts: Iterable[NumericsContract] | None = None,
) -> tuple[list[Finding], dict]:
    """Run every numerics contract; returns (findings, report).

    Builds run under ``disable_x64`` (the tier-2 discipline: audited
    traces match the production f32 configuration even when the host
    process enabled x64).
    """
    from jax.experimental import disable_x64

    findings: list[Finding] = []
    report: dict[str, Any] = {
        "contracts": {},
        "waivers": dict(TIER2_WAIVERS),
    }
    with disable_x64():
        resolved = (
            collect_contracts() if contracts is None else list(contracts)
        )
        findings.extend(check_coverage(resolved))
        for contract in resolved:
            entry: dict[str, Any] = {
                "entry": contract.entry,
                "covers": list(contract.covers),
                "programs": {},
                "notes": [],
            }
            report["contracts"][contract.name] = entry
            try:
                trace = contract.build()
            except Exception as exc:  # noqa: BLE001 — any builder crash is a finding
                findings.append(
                    _finding(
                        contract,
                        "numerics-contract",
                        f"contract builder failed: {exc!r}",
                    )
                )
                continue
            findings.extend(run_checks(contract, trace))
            for name, prog in trace.programs.items():
                flow = _flows(trace)[name]
                dims = {**trace.dims, **prog.dims}
                formula = _budget_for(contract, name)
                pentry: dict[str, Any] = {
                    "rounds": flow.max_rounds,
                    "reduce_len": flow.reduce_len,
                    "derived_bound": flow.derived_bound,
                    "budget": formula,
                    "families": sorted(flow.families),
                }
                if formula is not None:
                    try:
                        pentry["budget_value"] = _price(formula, dims)
                    except Exception:  # noqa: BLE001 — already a finding
                        pass
                entry["programs"][name] = pentry
            entry["notes"] = list(trace.notes) + [
                n for f in _flows(trace).values() for n in f.notes
            ]
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings, report
