"""Finding reporters: human text and machine JSON."""

from __future__ import annotations

import json
from typing import Iterable

from photon_tpu.analysis.core import Finding, registered_rules


def summarize(findings: Iterable[Finding]) -> dict:
    findings = list(findings)
    active = [f for f in findings if not f.suppressed]
    by_rule: dict[str, int] = {}
    for f in active:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "total": len(findings),
        "unsuppressed": len(active),
        "suppressed": len(findings) - len(active),
        "by_rule": dict(sorted(by_rule.items())),
    }


def render_text(
    findings: list[Finding], show_suppressed: bool = False
) -> str:
    lines = [
        f.format()
        for f in findings
        if show_suppressed or not f.suppressed
    ]
    s = summarize(findings)
    tail = (
        f"{s['unsuppressed']} finding(s), {s['suppressed']} suppressed"
    )
    if s["by_rule"]:
        tail += " [" + ", ".join(
            f"{k}: {v}" for k, v in s["by_rule"].items()
        ) + "]"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.to_json() for f in findings],
            "summary": summarize(findings),
        },
        indent=2,
    )


def render_rule_list() -> str:
    rules = registered_rules()
    width = max(len(r) for r in rules)
    return "\n".join(
        f"{r.id.ljust(width)}  {r.summary}" for r in rules.values()
    )
