"""Jit-scope discovery and a taint walk over traced values.

Two questions every JAX-aware rule needs answered:

1. **Which function bodies execute under tracing?** Functions decorated
   with ``jax.jit`` / ``pjit`` (directly or through ``functools.partial``),
   functions passed to ``jax.jit(fn, ...)`` by name (including
   ``self.method`` resolved against the enclosing class), and the body /
   cond / branch callables handed to ``lax.scan`` / ``while_loop`` /
   ``fori_loop`` / ``cond`` / ``switch`` / ``map`` and ``jax.vmap`` /
   ``jax.grad`` / ``jax.checkpoint``.

2. **Which values inside such a body are tracers?** Parameters are the
   taint sources — minus ``static_argnums`` / ``static_argnames``, which
   are concrete Python values. Taint propagates through expressions and
   assignments in statement order, and *stops* at the places JAX makes
   static again: ``.shape`` / ``.dtype`` / ``.ndim`` / ``.size``,
   ``len()`` / ``isinstance()`` / ``type()``, and ``is (not) None``
   structure checks. This keeps ``for i in range(x.shape[0])`` and
   ``if residuals is not None`` clean while ``if jnp.any(mask)`` flags.

The walk is a deliberately simple single in-order pass (last writer wins)
— the right fidelity for a linter: precise enough that the whole package
carries only a handful of suppressions, cheap enough to run on every test
invocation.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterator

from photon_tpu.analysis.core import ModuleContext

# Attribute reads that yield static (host) values even on a tracer.
STATIC_ATTRS = frozenset(
    {"shape", "dtype", "ndim", "size", "aval", "sharding", "weak_type"}
)
# Builtins whose result is host-static regardless of argument taint.
STATIC_CALLS = frozenset(
    {"isinstance", "issubclass", "hasattr", "len", "type", "id", "callable",
     "repr"}
)
# Calling these on a tracer forces a host sync (or raises under trace).
HOST_SYNC_CASTS = frozenset({"bool", "int", "float", "complex"})
HOST_SYNC_METHODS = frozenset({"item", "tolist", "__bool__", "__index__"})

_JIT_WRAPPERS = frozenset({"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"})
# callable-argument positions for tracing entry points: name -> indices
_TRACED_CALLEES: dict[str, tuple[int, ...]] = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.map": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
}


@dataclasses.dataclass
class JitScope:
    """A function body that runs under a JAX trace."""

    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    why: str  # human-readable provenance for messages
    static_argnums: frozenset[int] = frozenset()
    static_argnames: frozenset[str] = frozenset()

    def traced_params(self) -> set[str]:
        args = self.node.args
        positional = [*args.posonlyargs, *args.args]
        traced: set[str] = set()
        for i, a in enumerate(positional):
            if i in self.static_argnums or a.arg in self.static_argnames:
                continue
            if a.arg in ("self", "cls"):
                continue
            traced.add(a.arg)
        for a in args.kwonlyargs:
            if a.arg not in self.static_argnames:
                traced.add(a.arg)
        if args.vararg is not None:
            traced.add(args.vararg.arg)
        return traced


def _int_elems(node: ast.AST | None) -> frozenset[int]:
    if node is None:
        return frozenset()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
        return frozenset(out)
    return frozenset()


def _str_elems(node: ast.AST | None) -> frozenset[str]:
    if node is None:
        return frozenset()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
        return frozenset(out)
    return frozenset()


def _jit_statics(
    call: ast.Call | None,
) -> tuple[frozenset[int], frozenset[str]]:
    """static_argnums / static_argnames from a jit(...) call's keywords."""
    nums: frozenset[int] = frozenset()
    names: frozenset[str] = frozenset()
    if call is None:
        return nums, names
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _int_elems(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_elems(kw.value)
    return nums, names


def _is_jit_expr(ctx: ModuleContext, node: ast.AST) -> ast.Call | None:
    """``jax.jit`` / ``partial(jax.jit, ...)`` -> the call carrying statics.

    Returns the ast.Call whose keywords hold static_argnums/argnames (the
    partial call, or the jit call itself), or None when ``node`` is not a
    jit wrapper expression. A bare ``jax.jit`` reference (no statics)
    returns a synthetic empty marker via the enclosing caller.
    """
    if ctx.resolve(node) in _JIT_WRAPPERS:
        return ast.Call(func=node, args=[], keywords=[])  # no statics
    if isinstance(node, ast.Call):
        path = ctx.resolve(node.func)
        if path in _JIT_WRAPPERS:
            return node
        if path == "functools.partial" and node.args:
            if ctx.resolve(node.args[0]) in _JIT_WRAPPERS:
                return node
    return None


def _local_functions(
    ctx: ModuleContext,
) -> dict[ast.AST, dict[str, ast.FunctionDef]]:
    """scope node -> {name: FunctionDef defined directly in that scope}."""
    out: dict[ast.AST, dict[str, ast.FunctionDef]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parent = ctx.parents.get(node)
            # functions sit directly in Module / ClassDef / FunctionDef
            out.setdefault(parent, {})[node.name] = node
    return out


def _resolve_callable(
    ctx: ModuleContext,
    funcs: dict[ast.AST, dict[str, ast.FunctionDef]],
    ref: ast.AST,
) -> ast.FunctionDef | ast.Lambda | None:
    """Resolve a callable reference to its def, searching enclosing scopes."""
    if isinstance(ref, ast.Lambda):
        return ref
    if isinstance(ref, ast.Name):
        scope: ast.AST | None = ref
        while scope is not None:
            scope = next(
                (
                    a
                    for a in ctx.parent_chain(scope)
                    if isinstance(
                        a,
                        (ast.Module, ast.ClassDef, ast.FunctionDef,
                         ast.AsyncFunctionDef),
                    )
                ),
                None,
            )
            if scope is None:
                return None
            found = funcs.get(scope, {}).get(ref.id)
            if found is not None:
                return found
        return None
    # self.method -> method def on the nearest enclosing class
    if (
        isinstance(ref, ast.Attribute)
        and isinstance(ref.value, ast.Name)
        and ref.value.id == "self"
    ):
        for anc in ctx.parent_chain(ref):
            if isinstance(anc, ast.ClassDef):
                return funcs.get(anc, {}).get(ref.attr)
    return None


def find_jit_scopes(ctx: ModuleContext) -> list[JitScope]:
    """Every function body in the module that executes under a trace.

    Memoized on the context: several rules consult the scope list and
    must not redo discovery per rule.
    """
    cached = getattr(ctx, "_jit_scopes_cache", None)
    if cached is not None:
        return cached
    funcs = _local_functions(ctx)
    scopes: dict[ast.AST, JitScope] = {}

    def add(node, why, nums=frozenset(), names=frozenset()):
        if node is not None and node not in scopes:
            scopes[node] = JitScope(
                node=node, why=why, static_argnums=nums, static_argnames=names
            )

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                call = _is_jit_expr(ctx, deco)
                if call is not None:
                    nums, names = _jit_statics(call)
                    add(node, "decorated with jax.jit", nums, names)
        if not isinstance(node, ast.Call):
            continue
        # jax.jit(fn, ...) / partial(jax.jit, ...)(fn)
        call = _is_jit_expr(ctx, node.func)
        if call is not None and node.args:
            target = _resolve_callable(ctx, funcs, node.args[0])
            nums, names = _jit_statics(call)
            n2, s2 = _jit_statics(node)
            add(
                target,
                "wrapped by jax.jit",
                nums | n2,
                names | s2,
            )
            continue
        path = ctx.resolve(node.func)
        if path in _TRACED_CALLEES:
            short = path.removeprefix("jax.")
            for idx in _TRACED_CALLEES[path]:
                if idx < len(node.args):
                    add(
                        _resolve_callable(ctx, funcs, node.args[idx]),
                        f"passed to {short}",
                    )
    result = list(scopes.values())
    ctx._jit_scopes_cache = result
    return result


# --------------------------------------------------------------------------
# taint walk
# --------------------------------------------------------------------------

# Event kinds emitted to rule callbacks.
HOST_SYNC = "host-sync"
NUMPY_ON_TRACER = "numpy-on-tracer"

# Sentinel: a plainly-tainted iteration element (vs structural False).
PLAIN_TAINTED = True


def _spec_any(spec) -> bool:
    if isinstance(spec, list):
        return any(_spec_any(s) for s in spec)
    return bool(spec)

EventFn = Callable[[str, ast.AST, str], None]


class TaintWalker:
    """Walk one jit scope, tracking tracer-reachable names.

    ``on_event(kind, node, detail)`` fires for host-sync and
    numpy-on-tracer hazards; rules wrap it into Findings.
    """

    def __init__(self, ctx: ModuleContext, scope: JitScope, on_event: EventFn):
        self.ctx = ctx
        self.scope = scope
        self.on_event = on_event
        self.tainted: set[str] = scope.traced_params()

    # -- expression taint ------------------------------------------------

    def is_tainted(self, node: ast.AST | None) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value) or self.is_tainted(node.slice)
        if isinstance(node, ast.Call):
            return self._call_tainted(node)
        if isinstance(node, ast.Compare):
            if all(
                op.__class__ in (ast.Is, ast.IsNot, ast.In, ast.NotIn)
                for op in node.ops
            ):
                # `is (not) None` and dict/key membership are pytree
                # STRUCTURE, static under trace. (Membership in a traced
                # *array* would be traced — rare enough to accept the
                # miss; documented in ANALYSIS.md limitations.)
                return False
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return any(
                self.is_tainted(n) for n in (node.test, node.body, node.orelse)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_tainted(v) for v in node.values) or any(
                self.is_tainted(k) for k in node.keys if k is not None
            )
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Slice):
            return any(
                self.is_tainted(n)
                for n in (node.lower, node.upper, node.step)
            )
        if isinstance(node, ast.NamedExpr):
            return self.is_tainted(node.value)
        if isinstance(node, ast.JoinedStr):
            return any(
                self.is_tainted(v.value)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            )
        # Constants, lambdas (defined, not called), comprehensions: treat
        # comprehensions as tainted when any iterable source is.
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return any(self.is_tainted(g.iter) for g in node.generators)
        if isinstance(node, ast.DictComp):
            return any(self.is_tainted(g.iter) for g in node.generators)
        return False

    def _call_tainted(self, node: ast.Call) -> bool:
        if isinstance(node.func, ast.Name) and node.func.id in STATIC_CALLS:
            return False
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in STATIC_ATTRS
        ):
            return False
        parts = [
            *(node.args),
            *(kw.value for kw in node.keywords),
        ]
        if any(self.is_tainted(a) for a in parts):
            return True
        # method on a tracer returns a tracer (x.astype(...), x.sum(), ...)
        if isinstance(node.func, ast.Attribute):
            return self.is_tainted(node.func.value)
        return False

    # -- statement walk --------------------------------------------------

    def run(self) -> None:
        body = self.scope.node.body
        if isinstance(self.scope.node, ast.Lambda):
            self._check_expr(self.scope.node.body)
            return
        for stmt in body:
            self._walk_stmt(stmt)

    def _assign_target(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, tainted)
        # Attribute / Subscript targets mutate an object; the base keeps
        # whatever taint it already has.

    # -- structural iteration --------------------------------------------
    #
    # ``for i, (op, st) in enumerate(zip(ops, statics))`` iterates PYTREE
    # STRUCTURE: keys/indices are static, and each zipped source carries
    # its own taint. Model the common structural iterators so a static
    # companion (static_argnames pytrees, dict keys) doesn't get smeared
    # with taint from its traced neighbor.

    def _iter_element_taint(self, it: ast.AST):
        """Taint spec for one element of ``it``: bool, or a list of specs
        for a tuple-shaped element (zip/enumerate/items)."""
        if isinstance(it, ast.Call):
            fn = it.func
            if isinstance(fn, ast.Name):
                if fn.id == "range":
                    return False
                if fn.id == "zip":
                    return [self._iter_element_taint(a) for a in it.args]
                if fn.id == "enumerate" and it.args:
                    return [False, self._iter_element_taint(it.args[0])]
                if fn.id in ("sorted", "reversed", "list", "tuple") and it.args:
                    return self._iter_element_taint(it.args[0])
            if isinstance(fn, ast.Attribute):
                if fn.attr == "items":
                    t = self.is_tainted(fn.value)
                    return [False, PLAIN_TAINTED if t else False]
                if fn.attr == "keys":
                    return False
                if fn.attr == "values":
                    return (
                        PLAIN_TAINTED if self.is_tainted(fn.value) else False
                    )
        return PLAIN_TAINTED if self.is_tainted(it) else False

    def _assign_iter_target(self, target: ast.AST, spec) -> None:
        if isinstance(spec, list):
            if isinstance(target, (ast.Tuple, ast.List)) and len(
                target.elts
            ) == len(spec):
                for t, s in zip(target.elts, spec):
                    self._assign_iter_target(t, s)
                return
            spec = _spec_any(spec)
        self._assign_target(target, bool(spec))

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: runs under the same trace when called; params and
            # closed-over tracers are tainted inside it.
            inner = JitScope(node=stmt, why=self.scope.why)
            sub = TaintWalker(self.ctx, inner, self.on_event)
            sub.tainted |= self.tainted
            sub.run()
            return
        if isinstance(stmt, (ast.Assign,)):
            self._check_expr(stmt.value)
            tainted = self.is_tainted(stmt.value)
            for t in stmt.targets:
                self._assign_target(t, tainted)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._check_expr(stmt.value)
                self._assign_target(stmt.target, self.is_tainted(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_expr(stmt.value)
            if self.is_tainted(stmt.value):
                self._assign_target(stmt.target, True)
            return
        if isinstance(stmt, ast.If):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self.on_event(
                    HOST_SYNC,
                    stmt.test,
                    "`if` on a traced value forces a host sync / trace-time "
                    "concretization; use jnp.where or lax.cond",
                )
            for s in [*stmt.body, *stmt.orelse]:
                self._walk_stmt(s)
            return
        if isinstance(stmt, ast.While):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self.on_event(
                    HOST_SYNC,
                    stmt.test,
                    "`while` on a traced value cannot stay on device; use "
                    "lax.while_loop",
                )
            for s in [*stmt.body, *stmt.orelse]:
                self._walk_stmt(s)
            return
        if isinstance(stmt, ast.For):
            self._check_expr(stmt.iter)
            spec = self._iter_element_taint(stmt.iter)
            if spec is PLAIN_TAINTED:
                # Iterating a bare traced value: a traced ARRAY unrolls /
                # concretizes. (Python-container pytrees iterate fine and
                # are handled structurally above via zip/enumerate/items.)
                self.on_event(
                    HOST_SYNC,
                    stmt.iter,
                    "iterating a traced value concretizes or unrolls it; "
                    "use lax.scan or index with a static length",
                )
            self._assign_iter_target(stmt.target, spec)
            for s in [*stmt.body, *stmt.orelse]:
                self._walk_stmt(s)
            return
        if isinstance(stmt, ast.Assert):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self.on_event(
                    HOST_SYNC,
                    stmt.test,
                    "`assert` on a traced value concretizes it; use "
                    "checkify or a debug callback",
                )
            return
        if isinstance(stmt, (ast.Return,)):
            if stmt.value is not None:
                self._check_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._check_expr(stmt.value)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            for s in stmt.body:
                self._walk_stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in [
                *stmt.body,
                *(h_s for h in stmt.handlers for h_s in h.body),
                *stmt.orelse,
                *stmt.finalbody,
            ]:
                self._walk_stmt(s)
            return
        # Raise / Pass / Import / Global / Nonlocal / Delete: nothing traced.

    # -- expression-level hazard checks ---------------------------------

    def _check_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._check_call(node)

    def _check_call(self, node: ast.Call) -> None:
        args_tainted = any(
            self.is_tainted(a) for a in node.args
        ) or any(self.is_tainted(kw.value) for kw in node.keywords)
        # bool(x) / int(x) / float(x) on a tracer
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in HOST_SYNC_CASTS
            and node.args
            and self.is_tainted(node.args[0])
        ):
            self.on_event(
                HOST_SYNC,
                node,
                f"`{node.func.id}()` on a traced value forces a host sync "
                "(concretization error under jit)",
            )
            return
        if isinstance(node.func, ast.Attribute):
            # x.item() / x.tolist() on a tracer
            if node.func.attr in HOST_SYNC_METHODS and self.is_tainted(
                node.func.value
            ):
                self.on_event(
                    HOST_SYNC,
                    node,
                    f"`.{node.func.attr}()` on a traced value forces a "
                    "device->host transfer",
                )
                return
            path = self.ctx.resolve(node.func)
            if path is not None and (
                path.startswith("numpy.") or path == "numpy"
            ):
                if args_tainted:
                    if node.func.attr in ("asarray", "array", "copy"):
                        self.on_event(
                            HOST_SYNC,
                            node,
                            f"`np.{node.func.attr}` on a traced value pulls "
                            "it to the host; use jnp",
                        )
                    else:
                        self.on_event(
                            NUMPY_ON_TRACER,
                            node,
                            f"`np.{node.func.attr}` called on a traced "
                            "value executes on host per call; use the jnp "
                            "equivalent",
                        )


def walk_jit_scopes(
    ctx: ModuleContext, on_event: Callable[[str, ast.AST, str, JitScope], None]
) -> None:
    """Run the taint walk over every jit scope in the module."""
    for scope in find_jit_scopes(ctx):
        def fire(kind: str, node: ast.AST, detail: str, _s=scope) -> None:
            on_event(kind, node, detail, _s)

        TaintWalker(ctx, scope, fire).run()


def nearest_loop_before_function(
    ctx: ModuleContext, node: ast.AST
) -> ast.AST | None:
    """The For/While the node sits in, unless a def/lambda intervenes."""
    for anc in ctx.parent_chain(node):
        if isinstance(anc, (ast.For, ast.While)):
            return anc
        if isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return None
    return None


def iter_calls(ctx: ModuleContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            yield node
