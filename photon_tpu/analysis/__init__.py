"""photon_tpu.analysis — six static-analysis tiers that gate the package.

Tier 1 is a pure-``ast`` lint pass (nothing analyzed is imported, no JAX
needed at analysis time), so it runs in milliseconds on any machine. The
rule set encodes the failure modes that silently destroy TPU performance
or correctness and that this repo has actually hit: hidden host syncs
inside jitted code, numpy-on-tracer calls, recompile-triggering jit
misuse, float64 leaking into float32 pipelines, int32 index arithmetic
near 2^31, and leftover debugging debris.

Tier 2 (``--semantic``; analysis/program.py) audits the PROGRAMS the
package builds rather than the source text: the public jitted entry
points are traced under abstract shapes (no device execution — CPU CI is
enough) and the jaxprs/lowered HLO are checked against contracts each
audited module declares (dispatch census, recompile-key stability,
host-boundary and f64 audits, mesh sharding, and a static FLOP/HBM cost
model for the roofline numbers bench.py compares against).

Tier 3 (``--concurrency``; analysis/concurrency.py) audits the THREADED
HOST RUNTIME: a pure-``ast`` lockset lint (Eraser-style) checked against
the ``CONCURRENCY_AUDIT`` contracts the concurrent modules declare —
unlocked writes to guarded state, blocking calls under a lock, AB/BA
lock-order hazards, dropped futures, executor/thread hygiene, off-thread
JAX dispatch without a declared reason, and stale contracts.

Tier 4 (``--memory``; analysis/memory.py) audits the MEMORY of those
same programs before any device sees them: a static live-range walk of
each tier-2-traced entry point yields its peak-HBM high-water mark
(donation-aware), every declared buffer donation is verified to actually
alias in the compiled HLO (XLA drops unusable donations silently), and
each audited module's ``MEMORY_AUDIT`` contract prices the peak as a
formula in model-dimension terms — so HBM growth and rotten budgets both
fail CI, and ``predict_resident_bytes`` answers the admission question
("will this model fit") statically.

Tier 5 (``--numerics``; analysis/numerics.py) audits the DTYPE FLOW of
those same programs: a dtype-provenance lattice walk proves every
reduction over bf16-stored values accumulates in f32 (into
scan/while/cond bodies and across the Pallas boundary), censuses
narrowing casts, prices a static worst-case rounding-error bound per
program against declared ``NUMERICS_AUDIT`` budgets, and requires
order-nondeterministic reductions to be declared
deterministic-by-construction with a reason.

Tier 6 (``--spmd``; analysis/spmd.py) audits the MULTI-HOST behavior of
the mesh path on one CPU machine: each ``SPMD_AUDIT`` contract's entry
points are traced under N simulated ``jax.process_index()`` values (jit
caches cleared per host, so the proof cannot be satisfied by cache
replay) and the jaxprs must be byte-identical; the ordered collective
sequence of each host's compiled HLO must match position-by-position (a
mismatch is the deadlock, named statically); a host-divergence AST lint
flags time/env/pid/``process_index``/unseeded-RNG values flowing into
shapes or trace-affecting branches; and the declared ``PARTITION_RULES``
tree must cover every placed pytree leaf exactly once, implicit reshards
priced as bytes over the interconnect.

Usage::

    python -m photon_tpu.analysis photon_tpu/            # tier-1 gate
    python -m photon_tpu.analysis --semantic             # tier-2 gate
    python -m photon_tpu.analysis --concurrency          # tier-3 gate
    python -m photon_tpu.analysis --memory               # tier-4 gate
    python -m photon_tpu.analysis --numerics             # tier-5 gate
    python -m photon_tpu.analysis --spmd                 # tier-6 gate
    python -m photon_tpu.analysis --list-rules
    python -m photon_tpu.analysis --format json photon_tpu/data/

Per-line suppression (reason after ``--`` is part of the contract)::

    y = labels.astype(np.float64)  # photon: ignore[float64-literal] -- host-side stats

See ANALYSIS.md for every rule's rationale with its in-repo example.
"""

from photon_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    analyze_file,
    analyze_paths,
    analyze_source,
    registered_rules,
    rule,
)
from photon_tpu.analysis.report import (
    render_json,
    render_rule_list,
    render_text,
    summarize,
)

__all__ = [
    "Finding",
    "ModuleContext",
    "Rule",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "registered_rules",
    "rule",
    "render_json",
    "render_rule_list",
    "render_text",
    "summarize",
]
