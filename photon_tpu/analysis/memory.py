"""Tier 4: the memory auditor — static peak-HBM accounting and
donation-safety audits against declared ``MEMORY_AUDIT`` budget contracts.

The ledger (obs/ledger.py) knows the serving/fit HBM footprint at
RUNTIME, after the allocation already happened; ROADMAP items 3 and 5
(beyond-HBM tiering, multi-tenant admission) need the answer BEFORE a
device allocation. This tier computes it statically, with the same
contract machinery as tier 2 (analysis/program.py) and no device
execution — CPU CI is enough:

- **Static peak accounting**: every public jitted entry point already
  traced by tier 2 (fused materialize/fit, the serve score ladder,
  eval/score) is walked under abstract shapes for a live-buffer
  high-water mark (:func:`static_peak_bytes` — aval bytes over equation
  live ranges, donation-aware: a donated operand's bytes retire at its
  last use). Where the backend supports it the walk is cross-checked
  against ``lowered.compile().memory_analysis()`` (argument / output /
  temp / generated sizes) in the report.
- **Donation safety**: each declared donation must actually alias in
  the compiled HLO (``tf.aliasing_output`` / ``jax.buffer_donor`` arg
  attributes). XLA drops an unaliasable donation SILENTLY — the operand
  is simply DCE'd from the entry signature with no warning — so a
  dropped donation is a finding naming the operand
  (``memory-dropped-donation``). The source-level half is the tier-1
  ``use-after-donate`` rule (analysis/rules.py).
- **Budget contracts**: the declaring modules (``MEMORY_DECLARING_
  MODULES``) export ``MEMORY_AUDIT`` — each entry point's expected
  peak-HBM formula in model-dimension terms (E/S/d/rung/precision
  byte-widths) plus its donation map. The auditor prices every formula
  against the static walk and flags drift in BOTH directions: real
  growth the formula missed (``memory-undeclared-growth``) and a
  formula that rotted above reality (``memory-stale-formula``).
  ``rebuild_from``'s double-residency window is an explicit declared
  transient allowance, not an accident.
- **The admission oracle**: :func:`predict_resident_bytes` — the
  static "will this model + ladder + precision fit" half that ROADMAP
  items 3/5 call, keyed to match the ledger's ``table/<coordinate>``
  owners byte-for-byte (pinned by tests and by bench's
  ``predicted_vs_measured_hbm`` join against the measured watermark).

Run it: ``python -m photon_tpu.analysis --memory``. Exit codes follow
the other tiers: 0 clean, 1 unsuppressed findings, 2 usage error.
Contract schema and the four-tier table: ANALYSIS.md.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import importlib
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from photon_tpu.analysis.core import Finding

MEMORY_RULES: dict[str, str] = {
    "memory-undeclared-growth": (
        "a program's static peak-HBM walk exceeds its declared budget "
        "formula beyond the contract tolerance"
    ),
    "memory-stale-formula": (
        "a declared budget formula prices far above the static walk "
        "(or no longer evaluates) — the contract rotted"
    ),
    "memory-dropped-donation": (
        "a declared donation did not alias in the compiled HLO — XLA "
        "dropped it silently and both buffers stay resident"
    ),
    "memory-contract": (
        "memory-contract declaration, coverage, or builder integrity "
        "error (uncovered tier-2 entry point, stale waiver, oracle "
        "drift, builder crash)"
    ),
}

# Modules that declare memory contracts (each exports MEMORY_AUDIT —
# one declaration dict or a list of them). Plain data, like the tier-2
# PROGRAM_AUDIT hooks: importing the audited modules never imports the
# analysis machinery.
MEMORY_DECLARING_MODULES = (
    "photon_tpu.algorithm.fused_fit",
    "photon_tpu.ops.serve_kernel",
    "photon_tpu.serve.programs",
    "photon_tpu.serve.tables",
    "photon_tpu.pilot.serving",
)

# Tier-2 contracts with NO memory contract, each with its reason. The
# coverage check (every tier-2 entry point carries a MEMORY_AUDIT or a
# reasoned waiver) is what keeps this list honest: a new tier-2
# contract fails the audit until someone either budgets it or writes
# its waiver down here.
TIER2_WAIVERS: dict[str, str] = {
    "ingest-pipeline": (
        "host-side ETL: device residency is the packed ingest buffer, "
        "accounted by the pipeline's own ledger booking, and its "
        "programs are one-shot transforms, not resident state"
    ),
    "streaming-ingest": (
        "bounded by the declared chunk size by construction; no "
        "long-lived device buffers beyond the in-flight chunk"
    ),
    "fused-cache-key": (
        "key-only contract — it traces no programs and allocates "
        "nothing; the fused-fit memory contract covers the programs "
        "the keys select"
    ),
    "unfused-coordinate-update": (
        "the unfused CD path is the debugging fallback; its per-block "
        "working set is strictly dominated by the fused fit's budget"
    ),
    "telemetry": "host-side spans/counters; no device allocations",
    "trace": "host-side chrome-trace writer; no device allocations",
    "monitor": "host-side HTTP surface; no device allocations",
    "ledger": (
        "the ledger MEASURES residency; it allocates only host dicts"
    ),
    "health": (
        "sketches and calibration bins are tiny host-side state; the "
        "device-side sentinel reduces are O(1) scalars"
    ),
    "newton-kernel": (
        "executes only inline inside the fused-fit program; its slabs "
        "are priced by the fused-fit budget it is embedded in"
    ),
    "segment-reduce-kernel": (
        "same: an inlined kernel of the fused program, no buffers of "
        "its own beyond the fused-fit budget"
    ),
    "mesh-sharding": (
        "per-device residency under a mesh is the global budget over "
        "the axis size; a per-shard budget needs the mesh geometry, "
        "which is a runtime deployment choice (ROADMAP item 2)"
    ),
    "resilience-retry": (
        "host-side retry/fault machinery; zero device programs is "
        "already its tier-2 contract"
    ),
    "fleet-obs": (
        "host-side bundle shipping and trace merge; its tier-2 "
        "contract proves byte-identical device programs with the "
        "fleet armed, and the bundles live on disk, not HBM"
    ),
    "evaluation-scoring": (
        "one [n] score vector per evaluator invocation, freed on "
        "return; dominated by the fit/serve budgets that feed it"
    ),
}


# --------------------------------------------------------------------------
# data model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ProgramMemory:
    """One traced entry point under the memory walk: its closed jaxpr,
    optional Lowered (donation flags + XLA cross-check), and the
    per-program dims (e.g. this rung's batch) merged over the trace
    dims when pricing formulas."""

    name: str
    jaxpr: Any
    lowered: Any | None = None
    dims: dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DonationProbe:
    """One lowered donating program to verify against the compiled HLO:
    ``declared`` is the donate_argnums the source declares for it."""

    name: str
    lowered: Any
    declared: tuple[int, ...]


@dataclasses.dataclass
class ResidentProbe:
    """Built device tables at one precision: measured bytes per ledger
    owner next to the admission oracle's prediction for the same
    model/precision."""

    precision: str
    dims: dict[str, float]
    measured: dict[str, float]
    predicted: dict[str, float]


@dataclasses.dataclass
class MemoryTrace:
    """Everything a memory contract's builder hands the checks."""

    programs: dict[str, ProgramMemory] = dataclasses.field(
        default_factory=dict
    )
    dims: dict[str, float] = dataclasses.field(default_factory=dict)
    donation_probes: list[DonationProbe] = dataclasses.field(
        default_factory=list
    )
    residents: list[ResidentProbe] = dataclasses.field(
        default_factory=list
    )
    transient_values: dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    notes: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class MemoryContract:
    name: str
    entry: str  # human-readable entry-point path (report/docs)
    build: Callable[[], MemoryTrace]
    covers: tuple[str, ...] = ()  # tier-2 contract names this budgets
    budgets: dict[str, str] = dataclasses.field(default_factory=dict)
    resident: dict[str, str] = dataclasses.field(default_factory=dict)
    transients: dict[str, str] = dataclasses.field(default_factory=dict)
    donations: dict[str, tuple[int, ...]] = dataclasses.field(
        default_factory=dict
    )
    tolerance: float = 1.5
    suppress: dict[str, str] = dataclasses.field(default_factory=dict)


def _finding(contract: MemoryContract, rule: str, message: str) -> Finding:
    return Finding(
        rule=rule, path=f"<{contract.name}>", line=0, col=0, message=message
    )


# --------------------------------------------------------------------------
# the static walk
# --------------------------------------------------------------------------


def aval_nbytes(aval: Any) -> int:
    """Bytes of one abstract value (0 for non-array avals)."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    size = 1
    for dim in getattr(aval, "shape", ()):
        size *= int(dim)
    return int(size) * np.dtype(dtype).itemsize


def _is_literal(v: Any) -> bool:
    return hasattr(v, "val")  # jax.core.Literal duck type


def _sub_jaxprs(params: dict) -> Iterator[Any]:
    for v in params.values():
        for cand in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                if hasattr(getattr(cand, "jaxpr", cand), "eqns"):
                    yield cand


def _jaxpr_boundary_bytes(jaxpr: Any) -> int:
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for v in list(inner.invars) + list(inner.outvars):
        if not _is_literal(v):
            total += aval_nbytes(v.aval)
    return total


def static_peak_bytes(
    jaxpr: Any, donated: Iterable[bool] | None = None
) -> int:
    """Live-buffer high-water mark of a (Closed)Jaxpr, in bytes.

    An event sweep over the top-level equations: non-donated inputs and
    constants stay live for the whole program (the caller owns them), a
    DONATED input's bytes retire after its last use (that is the whole
    point of donation), an intermediate lives from its defining
    equation to its last use, and outputs live to the end. A sub-jaxpr
    (scan/while/cond body, inner pjit) contributes its own recursive
    internal peak minus its boundary bytes as a transient spike at its
    equation — its boundary operands are already priced as this level's
    live values.

    This is a STATIC model, deliberately scheduler-naive: XLA may do
    better (rematerialization, buffer sharing between disjoint live
    ranges it proves) and the declared contract tolerance absorbs that;
    what the model cannot do is silently miss a new slab-sized buffer,
    which is the failure the budget contracts exist to catch.
    """
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    eqns = list(inner.eqns)
    n = len(eqns)
    donated = list(donated) if donated is not None else []
    if len(donated) != len(inner.invars):
        donated = [False] * len(inner.invars)

    last_use: dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    outvars = {v for v in inner.outvars if not _is_literal(v)}

    # live interval per var: [start, end] inclusive over eqn indices;
    # index n is the program epilogue (outputs + caller-owned inputs).
    starts: dict[int, int] = {}
    ends: dict[int, int] = {}

    def add(start: int, end: int, nbytes: int) -> None:
        if nbytes <= 0:
            return
        starts[start] = starts.get(start, 0) + nbytes
        ends[end] = ends.get(end, 0) + nbytes

    for v in getattr(inner, "constvars", ()):
        add(0, n, aval_nbytes(v.aval))
    for v, dn in zip(inner.invars, donated):
        if v in outvars:
            end = n
        elif dn:
            end = last_use.get(v, 0)
        else:
            end = n
        add(0, end, aval_nbytes(v.aval))
    seen_inv = set(inner.invars) | set(getattr(inner, "constvars", ()))
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if v in seen_inv:
                continue
            end = n if v in outvars else last_use.get(v, i)
            add(i, end, aval_nbytes(v.aval))

    # transient spikes from sub-jaxprs, attributed to their equation
    extra: dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for sub in _sub_jaxprs(eqn.params):
            spike = static_peak_bytes(sub) - _jaxpr_boundary_bytes(sub)
            if spike > 0:
                extra[i] = extra.get(i, 0) + spike

    live = 0
    peak = 0
    for t in range(n + 1):
        live += starts.get(t, 0)
        peak = max(peak, live + extra.get(t, 0))
        live -= ends.get(t, 0)
    return peak


def donated_mask(lowered: Any) -> list[bool] | None:
    """Per-flat-invar donation flags from a Lowered's args_info (leaf
    order matches the flattened jaxpr invars), or None when the tree is
    unavailable."""
    info = getattr(lowered, "args_info", None)
    if info is None:
        return None
    import jax

    leaves = jax.tree_util.tree_leaves(
        info, is_leaf=lambda x: hasattr(x, "donated")
    )
    if not leaves:
        return None
    return [bool(getattr(x, "donated", False)) for x in leaves]


def program_peak(prog: ProgramMemory) -> int:
    """Static peak of one traced program, donation-aware when its
    Lowered carries arg info."""
    mask = donated_mask(prog.lowered) if prog.lowered is not None else None
    return static_peak_bytes(prog.jaxpr, mask)


# --------------------------------------------------------------------------
# donation-safety audit
# --------------------------------------------------------------------------

_ALIAS_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


def donation_report(lowered: Any) -> dict[str, Any]:
    """Declared-vs-compiled donation facts for one lowered program.

    ``declared`` counts args_info leaves marked donated; ``aliased``
    counts input/output alias attributes in the lowered module text. A
    donation XLA could not use leaves NO trace — the argument is DCE'd
    from the entry signature without a warning — so ``aliased <
    declared`` is the silent-drop signal.
    """
    mask = donated_mask(lowered) or []
    txt = lowered.as_text()
    aliased = sum(txt.count(marker) for marker in _ALIAS_MARKERS)
    return {
        "declared": sum(mask),
        "aliased": aliased,
        "positions": [i for i, d in enumerate(mask) if d],
    }


# --------------------------------------------------------------------------
# formula pricing
# --------------------------------------------------------------------------


def _price(formula: str, dims: dict[str, float]) -> float:
    """Evaluate a declared budget formula over the builder's dims.

    The formula language is deliberately just Python arithmetic over
    named dims (plus min/max) — expressive enough for E*S*wbytes-style
    budgets, reviewable in a diff, and with no access to anything else.
    """
    scope = dict(dims)
    scope["min"] = min
    scope["max"] = max
    return float(eval(formula, {"__builtins__": {}}, scope))  # noqa: S307


def _budget_for(contract: MemoryContract, program: str) -> str | None:
    """The budget formula covering ``program`` (exact key first, then
    fnmatch patterns — the serve ladder declares one formula for every
    ``score_b*`` rung)."""
    if program in contract.budgets:
        return contract.budgets[program]
    for pat, formula in contract.budgets.items():
        if fnmatch.fnmatchcase(program, pat):
            return formula
    return None


# --------------------------------------------------------------------------
# the admission oracle
# --------------------------------------------------------------------------


def predict_resident_bytes(
    model: Any, ladder: Any = None, precision: str = "float32"
) -> dict[str, Any]:
    """Predicted device-resident bytes for serving ``model`` — the
    static half of the HBM admission question, from model SHAPES alone
    (no arrays are built, no device is touched).

    Keys under ``"tables"`` are exactly the ledger's resident owners
    (``table/<coordinate>``; serve/tables.account_resident), so the
    prediction can be joined byte-for-byte against the measured
    watermark — bench.py's ``predicted_vs_measured_hbm``.

    ``rebuild_peak_bytes`` is the transient high-water mark of a
    structure-changing ``rebuild_from``: the new generation is built
    OFF-PATH while the old one keeps serving, so both are resident
    until the swap.
    """
    from photon_tpu.models.game import (
        FixedEffectModel,
        RandomEffectModel,
    )
    from photon_tpu.ops import precision as precision_mod

    resolved = precision_mod.resolve(precision)
    wbytes = 2 if resolved == "bfloat16" else 4
    tables: dict[str, float] = {}
    shard_width: dict[str, int] = {}
    n_random = 0
    for name, sub in model.items():
        if isinstance(sub, FixedEffectModel):
            d = int(sub.model.coefficients.means.shape[0])
            tables[f"table/{name}"] = float(d * wbytes)
            shard_width[sub.feature_shard_id] = max(
                shard_width.get(sub.feature_shard_id, 1), d
            )
        elif isinstance(sub, RandomEffectModel):
            e, s = (int(x) for x in sub.coefficients.shape)
            # weights [E,S] at storage width + projector [E,S] int32
            # (the projector never narrows; serve/tables.from_game_model)
            tables[f"table/{name}"] = float(e * s * (wbytes + 4))
            proj = np.asarray(sub.proj_all)
            width = int(proj.max(initial=-1)) + 1 if proj.size else 1
            shard_width[sub.feature_shard_id] = max(
                shard_width.get(sub.feature_shard_id, 1), width
            )
            n_random += 1
        else:
            raise TypeError(f"unknown sub-model type for {name!r}")
    total = float(sum(tables.values()))
    out: dict[str, Any] = {
        "precision": resolved,
        "tables": tables,
        "tables_total_bytes": total,
        "rebuild_peak_bytes": 2.0 * total,
    }
    if ladder is not None:
        # Request payloads stay a numpy-native float even over bf16
        # tables (serve/programs.ScorePrograms.dtype): 4 bytes/lane.
        payload = 4
        per_rung = {
            int(r): float(
                r * sum(shard_width.values()) * payload  # features
                + r * 4 * n_random  # int32 row codes
                + r * 4  # the score output
            )
            for r in ladder.rungs
        }
        out["per_rung_request_bytes"] = per_rung
        out["peak_bytes"] = total + max(per_rung.values())
    else:
        out["peak_bytes"] = total
    return out


# --------------------------------------------------------------------------
# checks
# --------------------------------------------------------------------------


def check_budgets(
    contract: MemoryContract, trace: MemoryTrace
) -> Iterator[Finding]:
    """Price every budget formula against the static walk, both ways."""
    tol = contract.tolerance
    for name, prog in trace.programs.items():
        formula = _budget_for(contract, name)
        if formula is None:
            yield _finding(
                contract,
                "memory-contract",
                f"traced program {name!r} has no declared budget: every "
                "audited entry point must carry a peak-HBM formula",
            )
            continue
        peak = program_peak(prog)
        dims = {**trace.dims, **prog.dims}
        try:
            declared = _price(formula, dims)
        except Exception as exc:  # noqa: BLE001 — a rotten formula is the finding
            yield _finding(
                contract,
                "memory-stale-formula",
                f"program {name!r}: budget formula {formula!r} no longer "
                f"evaluates over dims {sorted(dims)}: {exc!r}",
            )
            continue
        if peak > declared * tol:
            yield _finding(
                contract,
                "memory-undeclared-growth",
                f"program {name!r}: static peak {peak} B exceeds the "
                f"declared budget {formula!r} = {declared:.0f} B beyond "
                f"the {tol}x tolerance — a buffer grew that the "
                "contract does not price",
            )
        elif declared > peak * tol and declared - peak > 1024:
            yield _finding(
                contract,
                "memory-stale-formula",
                f"program {name!r}: declared budget {formula!r} = "
                f"{declared:.0f} B prices beyond {tol}x the static peak "
                f"{peak} B — the formula rotted above reality and would "
                "mask real growth",
            )
    for pat in contract.budgets:
        if not any(
            pat == name or fnmatch.fnmatchcase(name, pat)
            for name in trace.programs
        ):
            yield _finding(
                contract,
                "memory-contract",
                f"budget key {pat!r} matches no traced program — stale "
                "declaration",
            )


def check_donations(
    contract: MemoryContract, trace: MemoryTrace
) -> Iterator[Finding]:
    """Every probed donation must alias in the compiled HLO."""
    probed = set()
    for probe in trace.donation_probes:
        probed.add(probe.name)
        rep = donation_report(probe.lowered)
        if rep["declared"] != len(probe.declared):
            yield _finding(
                contract,
                "memory-dropped-donation",
                f"{probe.name}: {len(probe.declared)} donation(s) "
                f"declared at positions {tuple(probe.declared)} but the "
                f"traced program marks {rep['declared']} operand(s) "
                "donated — the donate_argnums drifted from the "
                "declaration",
            )
            continue
        if rep["aliased"] < rep["declared"]:
            dropped = rep["declared"] - rep["aliased"]
            yield _finding(
                contract,
                "memory-dropped-donation",
                f"{probe.name}: {dropped} of {rep['declared']} declared "
                f"donation(s) (operand position(s) "
                f"{tuple(rep['positions'])}) did not alias in the "
                "lowered module — XLA dropped the donation silently, "
                "both generations stay resident",
            )
    for name in contract.donations:
        if name not in probed:
            # Declared-but-unprobed donations (e.g. _solve_block, whose
            # operand assembly needs a full coordinate build) are noted,
            # not failed: the tier-1 use-after-donate rule covers their
            # call sites.
            trace.notes.append(
                f"donation map entry {name!r} declared at positions "
                f"{tuple(contract.donations[name])} is not probed "
                "against lowered HLO (covered by the tier-1 "
                "use-after-donate rule at its call sites)"
            )


def check_residents(
    contract: MemoryContract, trace: MemoryTrace
) -> Iterator[Finding]:
    """Resident-byte formulas vs built tables vs the admission oracle."""
    tol = contract.tolerance
    for probe in trace.residents:
        dims = {**trace.dims, **probe.dims}
        for owner, formula in contract.resident.items():
            measured = probe.measured.get(owner)
            if measured is None:
                yield _finding(
                    contract,
                    "memory-contract",
                    f"resident formula for {owner!r} matches no built "
                    f"table at precision {probe.precision} — stale "
                    "declaration",
                )
                continue
            try:
                declared = _price(formula, dims)
            except Exception as exc:  # noqa: BLE001
                yield _finding(
                    contract,
                    "memory-stale-formula",
                    f"resident {owner!r}: formula {formula!r} no longer "
                    f"evaluates: {exc!r}",
                )
                continue
            if measured > declared * tol:
                yield _finding(
                    contract,
                    "memory-undeclared-growth",
                    f"resident {owner!r} at {probe.precision}: built "
                    f"tables hold {measured:.0f} B, beyond {tol}x the "
                    f"declared {formula!r} = {declared:.0f} B",
                )
            elif declared > measured * tol:
                yield _finding(
                    contract,
                    "memory-stale-formula",
                    f"resident {owner!r} at {probe.precision}: declared "
                    f"{formula!r} = {declared:.0f} B prices beyond "
                    f"{tol}x the built {measured:.0f} B",
                )
        for owner, measured in probe.measured.items():
            predicted = probe.predicted.get(owner)
            if predicted is None or int(predicted) != int(measured):
                yield _finding(
                    contract,
                    "memory-contract",
                    f"admission-oracle drift at {probe.precision}: "
                    f"predict_resident_bytes says {predicted} B for "
                    f"{owner!r} but the built tables hold "
                    f"{measured:.0f} B — the static half of the "
                    "admission answer no longer matches reality",
                )


def check_transients(
    contract: MemoryContract, trace: MemoryTrace
) -> Iterator[Finding]:
    """Declared transient allowances (rebuild double-residency) vs the
    builder's computed transient peaks."""
    tol = contract.tolerance
    for name, formula in contract.transients.items():
        observed = trace.transient_values.get(name)
        if observed is None:
            yield _finding(
                contract,
                "memory-contract",
                f"transient allowance {name!r} has no computed value "
                "from the builder — stale declaration",
            )
            continue
        try:
            declared = _price(formula, trace.dims)
        except Exception as exc:  # noqa: BLE001
            yield _finding(
                contract,
                "memory-stale-formula",
                f"transient {name!r}: formula {formula!r} no longer "
                f"evaluates: {exc!r}",
            )
            continue
        if observed > declared * tol:
            yield _finding(
                contract,
                "memory-undeclared-growth",
                f"transient {name!r}: computed double-residency peak "
                f"{observed:.0f} B exceeds the declared allowance "
                f"{formula!r} = {declared:.0f} B beyond {tol}x",
            )
        elif declared > observed * tol:
            yield _finding(
                contract,
                "memory-stale-formula",
                f"transient {name!r}: declared allowance {formula!r} = "
                f"{declared:.0f} B prices beyond {tol}x the computed "
                f"{observed:.0f} B",
            )


CHECKS = (
    check_budgets,
    check_donations,
    check_residents,
    check_transients,
)


def run_checks(
    contract: MemoryContract, trace: MemoryTrace
) -> list[Finding]:
    """All memory checks over one contract's trace, suppressions
    applied (the tier-2 run_checks discipline: suppressed findings are
    kept, with their reasons, for the report)."""
    findings: list[Finding] = []
    for check in CHECKS:
        for f in check(contract, trace):
            reason = contract.suppress.get(f.rule)
            if reason is not None:
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=reason
                )
            findings.append(f)
    return findings


# --------------------------------------------------------------------------
# shared tiny serving fixtures (abstract-trace scale; CPU-cheap)
# --------------------------------------------------------------------------


def _tiny_game_model(
    d: int, e: int, s: int, du: int, *, proj_seed: int, rng_seed: int,
    scale: float = 1.0,
):
    """The tier-2 serving/pilot fixture model, parameterized: one dense
    fixed effect + one random effect with a non-trivial projector."""
    import jax.numpy as jnp

    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(rng_seed)
    prng = np.random.default_rng(proj_seed)
    proj = np.sort(
        np.stack([prng.permutation(du)[:s] for _ in range(e)]), axis=1
    ).astype(np.int64)
    return GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(means=jnp.asarray(
                    scale * rng.normal(size=d).astype(np.float32)
                )),
                TaskType.LOGISTIC_REGRESSION,
            ),
            "features",
        ),
        "per-user": RandomEffectModel(
            coefficients=jnp.asarray(
                scale * rng.normal(size=(e, s)).astype(np.float32)
            ),
            random_effect_type="userId",
            feature_shard_id="userShard",
            task=TaskType.LOGISTIC_REGRESSION,
            proj_all=proj,
            entity_keys=tuple(str(i) for i in range(e)),
        ),
    })


def _measured_table_bytes(tables: Any) -> dict[str, float]:
    """tree_nbytes of the BUILT device arrays, keyed like the ledger's
    resident owners (serve/tables.account_resident)."""
    from photon_tpu.obs import ledger

    out: dict[str, float] = {}
    for n, t in tables.fixed.items():
        out[f"table/{n}"] = float(ledger.tree_nbytes(t.weights))
    for n, t in tables.random.items():
        out[f"table/{n}"] = float(
            ledger.tree_nbytes((t.weights, t.proj))
        )
    return out


def _score_rung_programs(
    programs: Any, rungs: Iterable[int]
) -> dict[str, ProgramMemory]:
    out: dict[str, ProgramMemory] = {}
    for r in rungs:
        traced = programs.trace(r)
        out[f"score_b{r}"] = ProgramMemory(
            name=f"score_b{r}",
            jaxpr=traced.jaxpr,
            lowered=traced.lower(),
            dims={"rung": float(r)},
        )
    return out


def _donating_swap_probe(shape, dtype) -> DonationProbe:
    """The serve reload's donating value swap — the PRODUCTION body
    (serve/tables._swap_values), lowered with donation ON. The runtime
    wrapper gates donation off on CPU backends to avoid per-call
    warnings; the audit must check the donating form regardless of the
    host backend, so it jits the body with the donation forced."""
    import jax

    from photon_tpu.serve.tables import _swap_values

    fn = jax.jit(_swap_values, donate_argnums=(0,))
    sds = jax.ShapeDtypeStruct(tuple(shape), dtype)
    return DonationProbe(
        name="serve.tables._swap_values",
        lowered=fn.trace(sds, sds).lower(),
        declared=(0,),
    )


# --------------------------------------------------------------------------
# contract builders (named by the MEMORY_AUDIT declarations)
# --------------------------------------------------------------------------


def build_fused_fit_memory() -> MemoryTrace:
    """Trace one fused-fit generation's three programs for the walk and
    probe the CD sweep's donating carry."""
    from photon_tpu.algorithm.coordinate_descent import _sub_add_donating
    from photon_tpu.algorithm.fused_fit import FusedFit
    from photon_tpu.analysis import program as tier2

    import jax

    est, data = tier2._tiny_glmix()
    datasets, _ = est.prepare(data)
    n = data.num_samples
    coords = est._build_coordinates(datasets, {}, {}, logical_rows=n)
    fused = FusedFit(
        coords, est.update_sequence, 2, set(), precision="float32"
    )
    mat = fused._mat_jit.trace(fused._mat_operands(coords))
    fit = fused.trace(coords)
    fit_warm = fused.trace(coords, tier2._zero_initial_models(coords))
    coord = coords["per-user"]
    ds = getattr(coord, "inner", coord).dataset
    programs = {
        "materialize": ProgramMemory(
            "materialize", mat.jaxpr, mat.lower()
        ),
        "fit": ProgramMemory("fit", fit.jaxpr, fit.lower()),
        "fit_warm": ProgramMemory(
            "fit_warm", fit_warm.jaxpr, fit_warm.lower()
        ),
    }
    sds = jax.ShapeDtypeStruct((n,), np.float32)
    probe = DonationProbe(
        name="algorithm.coordinate_descent._sub_add_donating",
        lowered=_sub_add_donating.trace(sds, sds, sds).lower(),
        declared=(0,),
    )
    return MemoryTrace(
        programs=programs,
        dims={
            "n": float(n),
            "d": 5.0,
            "du": 4.0,
            "e": float(ds.num_entities),
            "s": float(ds.max_sub_dim),
            "iters": 2.0,
            "coords": 2.0,
            "wbytes": 4.0,
        },
        donation_probes=[probe],
        notes=[
            "dims from the tier-2 tiny GLMix fixture (one dense fixed "
            "effect [n,d] + one random effect [e,s] over du features); "
            "f32 storage",
        ],
    )


def build_serving_memory() -> MemoryTrace:
    """The serve score ladder's per-rung peaks + the reload donation."""
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 7, 3, 6
    model = _tiny_game_model(d, e, s, du, proj_seed=1234, rng_seed=20260803)
    ladder = ShapeLadder((1, 8, 64))
    tables = CoefficientTables.from_game_model(model)
    programs = ScorePrograms(tables, ladder=ladder, compile_now=False)
    return MemoryTrace(
        programs=_score_rung_programs(programs, ladder.rungs),
        dims={
            "d": float(d),
            "e": float(e),
            "s": float(s),
            "du": float(du),
            "wbytes": 4.0,
        },
        donation_probes=[
            _donating_swap_probe((e, s), np.float32),
        ],
        notes=[
            f"score ladder {ladder.rungs} over the tier-2 serving "
            "fixture model; tables f32",
        ],
    )


def build_serve_kernel_memory() -> MemoryTrace:
    """The fused serve kernel's per-rung peaks (PHOTON_SERVE_KERNEL
    forced so the pallas path is what gets walked; env restored after).

    The kernel's memory story vs the jit chain is the ABSENCE of the
    gathered intermediates: the live set is the resident tables plus
    the padded request payloads and the [rung] output — no [rung, s]
    gathered coefficient rows, no [rung, k, s] one-hot operand. The
    budget formula in ops/serve_kernel.MEMORY_AUDIT prices exactly
    that, so a lowering regression that rematerializes a gather
    surfaces as memory-undeclared-growth here."""
    import os

    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 7, 3, 6
    model = _tiny_game_model(
        d, e, s, du, proj_seed=1234, rng_seed=20260803
    )
    ladder = ShapeLadder((1, 8, 64))
    prev = os.environ.get("PHOTON_SERVE_KERNEL")
    os.environ["PHOTON_SERVE_KERNEL"] = "force"
    try:
        tables = CoefficientTables.from_game_model(model)
        programs = ScorePrograms(
            tables, ladder=ladder, compile_now=False
        )
        if not programs.use_kernel:
            raise RuntimeError(
                "PHOTON_SERVE_KERNEL=force did not engage the fused "
                "kernel — the serve-kernel memory contract audits "
                "nothing"
            )
        traced = {
            f"serve_kernel_b{r}": ProgramMemory(
                name=f"serve_kernel_b{r}",
                jaxpr=(t := programs.trace(r)).jaxpr,
                lowered=t.lower(),
                dims={"rung": float(r)},
            )
            for r in ladder.rungs
        }
    finally:
        if prev is None:  # photon: ignore[spmd-host-divergence] -- env save/restore of the audit fixture's kernel flag; host-local tooling, not fleet code
            os.environ.pop("PHOTON_SERVE_KERNEL", None)
        else:
            os.environ["PHOTON_SERVE_KERNEL"] = prev
    return MemoryTrace(
        programs=traced,
        dims={
            "d": float(d),
            "e": float(e),
            "s": float(s),
            "du": float(du),
            "wbytes": 4.0,
        },
        notes=[
            f"fused kernel over ladder {ladder.rungs}, tier-2 serving "
            "fixture model, f32 tables, interpret-path lowering",
        ],
    )


def build_tables_memory() -> MemoryTrace:
    """Resident tables at BOTH precisions vs the admission oracle, and
    the rebuild_from double-residency transient."""
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 7, 3, 6
    model = _tiny_game_model(d, e, s, du, proj_seed=1234, rng_seed=20260803)
    residents: list[ResidentProbe] = []
    rebuild_peak = 0.0
    for precision, wbytes in (("float32", 4.0), ("bfloat16", 2.0)):
        tables = CoefficientTables.from_game_model(model, precision)
        predicted = predict_resident_bytes(model, precision=precision)
        residents.append(
            ResidentProbe(
                precision=precision,
                dims={"wbytes": wbytes},
                measured=_measured_table_bytes(tables),
                predicted=dict(predicted["tables"]),
            )
        )
        if precision == "float32":
            rebuild_peak = predicted["rebuild_peak_bytes"]
    return MemoryTrace(
        dims={
            "d": float(d),
            "e": float(e),
            "s": float(s),
            "du": float(du),
            "wbytes": 4.0,  # transient priced at the f32 build
        },
        donation_probes=[_donating_swap_probe((e, s), np.float32)],
        residents=residents,
        transient_values={"rebuild_from": rebuild_peak},
        notes=[
            "tables built at f32 AND bf16: the resident formulas price "
            "the precision width on both sides of the admission oracle",
        ],
    )


def build_pilot_serving_memory() -> MemoryTrace:
    """The pilot's serving bundle: its ladder rungs' peaks plus the
    promotion rebuild allowance."""
    from photon_tpu.serve.programs import ScorePrograms, ShapeLadder
    from photon_tpu.serve.tables import CoefficientTables

    d, e, s, du = 5, 6, 3, 5
    model = _tiny_game_model(d, e, s, du, proj_seed=99, rng_seed=20260804)
    ladder = ShapeLadder((1, 8))
    tables = CoefficientTables.from_game_model(model)
    programs = ScorePrograms(tables, ladder=ladder, compile_now=False)
    predicted = predict_resident_bytes(model, ladder=ladder)
    return MemoryTrace(
        programs=_score_rung_programs(programs, ladder.rungs),
        dims={
            "d": float(d),
            "e": float(e),
            "s": float(s),
            "du": float(du),
            "wbytes": 4.0,
        },
        transient_values={
            "promotion_rebuild": predicted["rebuild_peak_bytes"]
        },
        notes=[
            f"pilot ladder {ladder.rungs} over the tier-2 pilot fixture "
            "model (PilotServer defaults, f32 tables)",
        ],
    )


_BUILDERS: dict[str, Callable[[], MemoryTrace]] = {
    "build_fused_fit_memory": build_fused_fit_memory,
    "build_serve_kernel_memory": build_serve_kernel_memory,
    "build_serving_memory": build_serving_memory,
    "build_tables_memory": build_tables_memory,
    "build_pilot_serving_memory": build_pilot_serving_memory,
}


def contract_from_declaration(spec: dict) -> MemoryContract:
    builder = spec.get("builder")
    if builder not in _BUILDERS:
        raise ValueError(
            f"MEMORY_AUDIT declaration {spec.get('name')!r} names unknown "
            f"builder {builder!r}"
        )
    return MemoryContract(
        name=spec["name"],
        entry=spec["entry"],
        build=_BUILDERS[builder],
        covers=tuple(spec.get("covers", ())),
        budgets=dict(spec.get("budgets", {})),
        resident=dict(spec.get("resident", {})),
        transients=dict(spec.get("transients", {})),
        donations={
            k: tuple(v) for k, v in dict(spec.get("donations", {})).items()
        },
        tolerance=float(spec.get("tolerance", 1.5)),
        suppress=dict(spec.get("suppress", {})),
    )


def collect_contracts() -> list[MemoryContract]:
    """The repo's declared memory-contract registry."""
    specs: list[dict] = []
    for modname in MEMORY_DECLARING_MODULES:
        mod = importlib.import_module(modname)
        decl = getattr(mod, "MEMORY_AUDIT", None)
        if decl is None:
            raise ValueError(
                f"{modname} is a memory-declaring module but exports no "
                "MEMORY_AUDIT"
            )
        specs.extend(decl if isinstance(decl, (list, tuple)) else [decl])
    return [contract_from_declaration(s) for s in specs]


def check_coverage(
    contracts: Iterable[MemoryContract],
) -> list[Finding]:
    """Every tier-2 entry point carries a memory contract or a reasoned
    waiver — and no waiver outlives its reason."""
    from photon_tpu.analysis import program as tier2

    tier2_names = {c.name for c in tier2.collect_contracts()}
    covered: dict[str, str] = {}
    findings: list[Finding] = []
    anchor = MemoryContract(
        name="memory-coverage", entry="analysis.memory", build=MemoryTrace
    )
    for c in contracts:
        for name in c.covers:
            if name not in tier2_names:
                findings.append(
                    _finding(
                        anchor,
                        "memory-contract",
                        f"memory contract {c.name!r} covers unknown "
                        f"tier-2 contract {name!r}",
                    )
                )
            covered[name] = c.name
    for name, reason in TIER2_WAIVERS.items():
        if name not in tier2_names:
            findings.append(
                _finding(
                    anchor,
                    "memory-contract",
                    f"stale waiver: {name!r} is not a tier-2 contract",
                )
            )
        elif name in covered:
            findings.append(
                _finding(
                    anchor,
                    "memory-contract",
                    f"stale waiver: {name!r} is covered by memory "
                    f"contract {covered[name]!r} — drop the waiver",
                )
            )
        if not reason or not reason.strip():
            findings.append(
                _finding(
                    anchor,
                    "memory-contract",
                    f"waiver for {name!r} has no reason — a waiver "
                    "without a reason is a gap, not a decision",
                )
            )
    for name in sorted(tier2_names):
        if name not in covered and name not in TIER2_WAIVERS:
            findings.append(
                _finding(
                    anchor,
                    "memory-contract",
                    f"tier-2 contract {name!r} has no MEMORY_AUDIT "
                    "coverage and no waiver: declare its peak-HBM "
                    "budget or add a reasoned TIER2_WAIVERS entry",
                )
            )
    return findings


# --------------------------------------------------------------------------
# the audit driver
# --------------------------------------------------------------------------


def _xla_memory_analysis(prog: ProgramMemory) -> dict[str, float] | None:
    """XLA's own compiled memory accounting, where the backend exposes
    it — the cross-check column next to the static walk (works on CPU
    in current jax; absent backends degrade to walk-only)."""
    if prog.lowered is None:
        return None
    try:
        stats = prog.lowered.compile().memory_analysis()
    except Exception:  # noqa: BLE001 — optional cross-check only
        return None
    if stats is None:
        return None
    out: dict[str, float] = {}
    for field in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(stats, field, None)
        if v is not None:
            out[field] = float(v)
    return out or None


def audit(
    contracts: Iterable[MemoryContract] | None = None,
    *,
    with_xla: bool = True,
) -> tuple[list[Finding], dict]:
    """Run every memory contract; returns (findings, report).

    Builds run under ``disable_x64`` (the tier-2 discipline: audited
    traces match the production f32 configuration even when the host
    process enabled x64).
    """
    from jax.experimental import disable_x64

    findings: list[Finding] = []
    report: dict[str, Any] = {"contracts": {}, "waivers": dict(TIER2_WAIVERS)}
    with disable_x64():
        resolved = (
            collect_contracts() if contracts is None else list(contracts)
        )
        findings.extend(check_coverage(resolved))
        for contract in resolved:
            entry: dict[str, Any] = {
                "entry": contract.entry,
                "covers": list(contract.covers),
                "programs": {},
                "donations": {},
                "notes": [],
            }
            report["contracts"][contract.name] = entry
            try:
                trace = contract.build()
            except Exception as exc:  # noqa: BLE001 — any builder crash is a finding
                findings.append(
                    _finding(
                        contract,
                        "memory-contract",
                        f"contract builder failed: {exc!r}",
                    )
                )
                continue
            findings.extend(run_checks(contract, trace))
            for name, prog in trace.programs.items():
                dims = {**trace.dims, **prog.dims}
                formula = _budget_for(contract, name)
                pentry: dict[str, Any] = {
                    "static_peak_bytes": program_peak(prog),
                    "budget": formula,
                }
                if formula is not None:
                    try:
                        pentry["budget_bytes"] = _price(formula, dims)
                    except Exception:  # noqa: BLE001 — already a finding
                        pass
                if with_xla:
                    xla = _xla_memory_analysis(prog)
                    if xla is not None:
                        pentry["xla_memory_analysis"] = xla
                entry["programs"][name] = pentry
            for probe in trace.donation_probes:
                entry["donations"][probe.name] = donation_report(
                    probe.lowered
                )
            if trace.residents:
                entry["residents"] = [
                    {
                        "precision": p.precision,
                        "measured": dict(p.measured),
                        "predicted": dict(p.predicted),
                    }
                    for p in trace.residents
                ]
            if trace.transient_values:
                entry["transients"] = dict(trace.transient_values)
            entry["notes"] = list(trace.notes)
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings, report
