"""CLI: ``python -m photon_tpu.analysis [paths...]``.

Exit codes: 0 clean (or only suppressed findings), 1 unsuppressed
findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from photon_tpu.analysis.core import (
    analyze_paths,
    iter_python_files,
    registered_rules,
)
from photon_tpu.analysis.report import (
    render_json,
    render_rule_list,
    render_text,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m photon_tpu.analysis",
        description="JAX-aware static lint pass for photon_tpu "
        "(see ANALYSIS.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories to analyze (default: photon_tpu/)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format",
    )
    parser.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="include suppressed findings in text output",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rules and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rule_list())
        return 0

    paths = args.paths or ["photon_tpu"]
    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select
        else None
    )
    if select is not None:
        unknown = set(select) - set(registered_rules())
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(
            f"no such path(s): {', '.join(missing)}", file=sys.stderr
        )
        return 2
    try:
        findings = analyze_paths(paths, select=select)
    except OSError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if not any(iter_python_files(paths)):
        # A gate that analyzed zero files must not report "clean" — a
        # wrong CWD or glob would make CI pass vacuously.
        print(
            "no Python files found under: " + ", ".join(map(str, paths)),
            file=sys.stderr,
        )
        return 2

    if args.format == "json":
        print(render_json(findings))
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
