"""CLI: ``python -m photon_tpu.analysis [paths...]``.

Six tiers share this entry point:

- default: the tier-1 pure-``ast`` lint pass over source files;
- ``--semantic``: the tier-2 program auditor (analysis/program.py) —
  traces the package's jitted entry points under abstract shapes and
  audits jaxprs/HLO against the modules' declared contracts. Needs JAX
  (CPU is fine; no device execution) but no accelerator.
- ``--concurrency``: the tier-3 host-concurrency auditor
  (analysis/concurrency.py) — a pure-``ast`` lockset lint over source
  files, checked against the ``CONCURRENCY_AUDIT`` contracts the
  threaded modules declare. No JAX, no imports of the audited code.
- ``--memory``: the tier-4 memory auditor (analysis/memory.py) —
  static peak-HBM accounting over the tier-2-traced entry points,
  donation-safety verification against compiled HLO, and the declared
  ``MEMORY_AUDIT`` budget contracts. Needs JAX (CPU is fine; no device
  execution).
- ``--numerics``: the tier-5 numerics auditor (analysis/numerics.py) —
  dtype-provenance verification of the mixed-precision policy over the
  traced jaxprs (bf16 lineage must accumulate f32), the cast census,
  static worst-case error budgets, and the reduction-determinism
  census, against the declared ``NUMERICS_AUDIT`` contracts. Needs JAX
  (CPU is fine; no device execution).
- ``--spmd``: the tier-6 SPMD auditor (analysis/spmd.py) — cross-host
  trace-determinism proofs under simulated ``process_index`` 0..N-1,
  the host-divergence AST lint, the ordered collective-order deadlock
  census, and partition-rule coverage, against the declared
  ``SPMD_AUDIT`` contracts. ``--hosts N`` sets the simulated fleet
  size. Needs JAX (CPU is fine; no devices beyond the virtual
  platform, no distributed runtime).

Exit codes: 0 clean (or only suppressed findings), 1 unsuppressed
findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from photon_tpu.analysis.core import (
    analyze_paths,
    iter_python_files,
    registered_rules,
)
from photon_tpu.analysis.report import (
    render_json,
    render_rule_list,
    render_text,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m photon_tpu.analysis",
        description="JAX-aware static lint pass for photon_tpu "
        "(see ANALYSIS.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories to analyze (default: photon_tpu/)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format",
    )
    parser.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="include suppressed findings in text output",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rules and exit",
    )
    parser.add_argument(
        "--semantic",
        action="store_true",
        help="run the tier-2 program auditor (jaxpr/HLO contracts) "
        "instead of the source lint",
    )
    parser.add_argument(
        "--concurrency",
        action="store_true",
        help="run the tier-3 host-concurrency auditor (lockset lint "
        "against CONCURRENCY_AUDIT contracts) instead of the source "
        "lint",
    )
    parser.add_argument(
        "--memory",
        action="store_true",
        help="run the tier-4 memory auditor (static peak-HBM walks, "
        "donation aliasing, MEMORY_AUDIT budget contracts) instead of "
        "the source lint",
    )
    parser.add_argument(
        "--numerics",
        action="store_true",
        help="run the tier-5 numerics auditor (dtype-flow lattice, "
        "cast census, static error budgets, determinism census, "
        "NUMERICS_AUDIT contracts) instead of the source lint",
    )
    parser.add_argument(
        "--spmd",
        action="store_true",
        help="run the tier-6 SPMD auditor (cross-host trace proofs, "
        "host-divergence lint, collective-order census, partition-rule "
        "coverage, SPMD_AUDIT contracts) instead of the source lint",
    )
    parser.add_argument(
        "--hosts",
        type=int,
        metavar="N",
        help="with --spmd: simulate an N-process fleet (default: each "
        "contract's declared host count)",
    )
    parser.add_argument(
        "--cost-out",
        metavar="PATH",
        help="with --semantic: also write the per-program cost-model/"
        "roofline report to PATH as JSON",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        if args.concurrency:
            from photon_tpu.analysis import concurrency

            print(concurrency.render_rule_list())
        elif args.spmd:
            from photon_tpu.analysis import spmd

            print(spmd.render_rule_list())
        else:
            print(render_rule_list())
        return 0

    if sum(
        (
            args.semantic,
            args.concurrency,
            args.memory,
            args.numerics,
            args.spmd,
        )
    ) > 1:
        print(
            "--semantic, --concurrency, --memory, --numerics, and "
            "--spmd are separate tiers; run them as separate "
            "invocations",
            file=sys.stderr,
        )
        return 2
    if args.cost_out and not args.semantic:
        print("--cost-out requires --semantic", file=sys.stderr)
        return 2
    if args.hosts is not None and not args.spmd:
        print("--hosts requires --spmd", file=sys.stderr)
        return 2
    if args.spmd:
        if args.paths or args.select:
            print(
                "--spmd audits the package's declared SPMD contracts "
                "(the lint half always covers the whole package); "
                "paths/--select do not apply",
                file=sys.stderr,
            )
            return 2
        if args.hosts is not None and args.hosts < 2:
            print(
                "--hosts must be >= 2 (the cross-host proof needs a "
                "fleet)",
                file=sys.stderr,
            )
            return 2
        return _run_spmd(args)
    if args.numerics:
        if args.paths or args.select:
            print(
                "--numerics audits the package's declared numerics "
                "contracts; paths/--select do not apply",
                file=sys.stderr,
            )
            return 2
        return _run_numerics(args)
    if args.memory:
        if args.paths or args.select:
            print(
                "--memory audits the package's declared memory "
                "contracts; paths/--select do not apply",
                file=sys.stderr,
            )
            return 2
        return _run_memory(args)
    if args.concurrency:
        if args.select:
            print(
                "--select applies to the tier-1 rules; the concurrency "
                "tier always runs its full rule set",
                file=sys.stderr,
            )
            return 2
        return _run_concurrency(args)
    if args.semantic:
        if args.paths or args.select:
            print(
                "--semantic audits the package's declared program "
                "contracts; paths/--select do not apply",
                file=sys.stderr,
            )
            return 2
        return _run_semantic(args)

    paths = args.paths or ["photon_tpu"]
    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select
        else None
    )
    if select is not None:
        unknown = set(select) - set(registered_rules())
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
    if _paths_usage_error(paths):
        return 2
    try:
        findings = analyze_paths(paths, select=select)
    except OSError as exc:
        print(str(exc), file=sys.stderr)
        return 2

    if args.format == "json":
        print(render_json(findings))
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
    return 1 if any(not f.suppressed for f in findings) else 0


def _paths_usage_error(paths) -> bool:
    """Shared tier-1/tier-3 path validation: a gate that analyzed zero
    files must not report "clean" — a wrong CWD, typo, or empty glob
    exits 2, never 0."""
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(
            f"no such path(s): {', '.join(missing)}", file=sys.stderr
        )
        return True
    if not any(iter_python_files(paths)):
        print(
            "no Python files found under: " + ", ".join(map(str, paths)),
            file=sys.stderr,
        )
        return True
    return False


def _run_concurrency(args) -> int:
    from photon_tpu.analysis import concurrency

    paths = args.paths or ["photon_tpu"]
    if _paths_usage_error(paths):
        return 2
    findings = concurrency.audit_paths(paths)
    if args.format == "json":
        print(render_json(findings))
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
        contracts = concurrency.collect_contracts(paths)
        for name, c in sorted(contracts.items()):
            locks = ", ".join(
                f"{lk}->({', '.join(v)})" for lk, v in c.locks.items()
            )
            print(f"contract {name}: {locks or 'no locks declared'}")
    return 1 if any(not f.suppressed for f in findings) else 0


def _run_memory(args) -> int:
    from photon_tpu.analysis import memory

    findings, report = memory.audit()
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "report": report,
                },
                indent=2,
            )
        )
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
        for cname, entry in report["contracts"].items():
            progs = ", ".join(
                f"{n}@{p['static_peak_bytes']}B"
                for n, p in entry["programs"].items()
            )
            print(f"contract {cname}: {progs or 'no traced programs'}")
            for dname, d in entry["donations"].items():
                print(
                    f"  donation {dname}: declared={d['declared']} "
                    f"aliased={d['aliased']}"
                )
            for note in entry["notes"]:
                print(f"  note: {note}")
    return 1 if any(not f.suppressed for f in findings) else 0


def _run_numerics(args) -> int:
    from photon_tpu.analysis import numerics

    findings, report = numerics.audit()
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "report": report,
                },
                indent=2,
            )
        )
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
        for cname, entry in report["contracts"].items():
            progs = ", ".join(
                f"{n}(rounds={p['rounds']}, "
                f"len={int(p['reduce_len'])})"
                for n, p in entry["programs"].items()
            )
            print(f"contract {cname}: {progs or 'no traced programs'}")
            for note in entry["notes"]:
                print(f"  note: {note}")
    return 1 if any(not f.suppressed for f in findings) else 0


def _run_spmd(args) -> int:
    from photon_tpu.analysis import spmd

    findings, report = spmd.audit(hosts=args.hosts)
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "report": report,
                },
                indent=2,
            )
        )
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
        for cname, entry in report["contracts"].items():
            progs = ", ".join(
                f"{n}@{'ok' if p['identical'] else 'DIVERGENT'}"
                f"[{' -> '.join(p['collectives']) or 'no collectives'}]"
                for n, p in entry["programs"].items()
            )
            print(
                f"contract {cname} ({entry['hosts']} hosts): "
                f"{progs or 'no traced programs'}"
            )
            cov = entry.get("coverage")
            if cov:
                print(
                    f"  coverage: {cov['leaves']} leaves / "
                    f"{cov['rules']} rules"
                    + (
                        f"; UNCOVERED: {', '.join(cov['uncovered'])}"
                        if cov["uncovered"]
                        else ""
                    )
                )
            for note in entry["notes"]:
                print(f"  note: {note}")
    return 1 if any(not f.suppressed for f in findings) else 0


def _run_semantic(args) -> int:
    from photon_tpu.analysis import program

    # Cost analysis only where it is consumed: the plain text gate
    # prints signatures/notes, so pricing every program there is waste.
    findings, report = program.audit(
        with_cost=bool(args.cost_out or args.format == "json")
    )
    if args.cost_out:
        from photon_tpu.analysis import costmodel

        costmodel.write_report(args.cost_out, report)
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "report": report,
                },
                indent=2,
            )
        )
    else:
        out = render_text(findings, show_suppressed=args.show_suppressed)
        if out:
            print(out)
        for cname, entry in report["contracts"].items():
            progs = ", ".join(
                f"{n}@{p['signature'][:8]}"
                for n, p in entry["programs"].items()
            )
            print(f"contract {cname}: {progs or 'no traced programs'}")
            for note in entry["notes"]:
                print(f"  note: {note}")
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
