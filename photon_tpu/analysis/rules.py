"""The initial rule set — every rule is a hazard this repo actually hit.

See ANALYSIS.md at the repo root for each rule's rationale with the
in-repo example that motivated it, the suppression syntax, and the CLI.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_tpu.analysis.core import Finding, ModuleContext, rule
from photon_tpu.analysis.jitscope import (
    HOST_SYNC,
    NUMPY_ON_TRACER,
    find_jit_scopes,
    iter_calls,
    nearest_loop_before_function,
    walk_jit_scopes,
)

_JIT_PATHS = frozenset(
    {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
)


def _finding(
    ctx: ModuleContext, rule_id: str, node: ast.AST, message: str
) -> Finding:
    return Finding(
        rule=rule_id,
        path=ctx.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
    )


# --------------------------------------------------------------------------
# host-sync-in-jit / numpy-on-tracer (one shared taint walk)
# --------------------------------------------------------------------------


def _taint_events(ctx: ModuleContext) -> list[tuple]:
    """All (kind, node, detail, scope) taint events, walked ONCE per
    module and memoized on the context — both taint rules filter this."""
    cached = getattr(ctx, "_taint_events_cache", None)
    if cached is None:
        cached = []

        def on_event(kind, node, detail, scope):
            cached.append((kind, node, detail, scope))

        walk_jit_scopes(ctx, on_event)
        ctx._taint_events_cache = cached
    return cached


def _taint_findings(ctx: ModuleContext, want_kind: str, rule_id: str):
    out: list[Finding] = []
    for kind, node, detail, scope in _taint_events(ctx):
        if kind != want_kind:
            continue
        out.append(
            _finding(
                ctx,
                rule_id,
                node,
                f"{detail} (function `{_scope_name(scope.node)}` "
                f"{scope.why})",
            )
        )
    return out


def _scope_name(node: ast.AST) -> str:
    return getattr(node, "name", "<lambda>")


@rule(
    "host-sync-in-jit",
    "implicit bool()/int()/float()/if/.item()/np.asarray on a traced value "
    "inside a jit/scan/while_loop body",
)
def host_sync_in_jit(ctx: ModuleContext) -> Iterator[Finding]:
    yield from _taint_findings(ctx, HOST_SYNC, "host-sync-in-jit")


@rule(
    "numpy-on-tracer",
    "np.* called on a traced value where jnp is required",
)
def numpy_on_tracer(ctx: ModuleContext) -> Iterator[Finding]:
    yield from _taint_findings(ctx, NUMPY_ON_TRACER, "numpy-on-tracer")


# --------------------------------------------------------------------------
# recompile-hazard
# --------------------------------------------------------------------------


@rule(
    "recompile-hazard",
    "jit construction per call / unhashable static argument — every hit "
    "recompiles instead of reusing the cache",
)
def recompile_hazard(ctx: ModuleContext) -> Iterator[Finding]:
    # Map: name of a jit-wrapped function -> its static_argnames, so call
    # sites can be checked for unhashable static values.
    static_names_by_func: dict[str, frozenset[str]] = {}
    for scope in find_jit_scopes(ctx):
        name = getattr(scope.node, "name", None)
        if name and scope.static_argnames:
            static_names_by_func[name] = scope.static_argnames

    for call in iter_calls(ctx):
        path = ctx.resolve(call.func)
        if path in _JIT_PATHS:
            loop = nearest_loop_before_function(ctx, call)
            if loop is not None:
                yield _finding(
                    ctx,
                    "recompile-hazard",
                    call,
                    "jax.jit(...) constructed inside a loop: every "
                    "iteration builds a fresh wrapper and retraces; hoist "
                    "the jitted callable out of the loop",
                )
                continue
            parent = ctx.parents.get(call)
            if isinstance(parent, ast.Call) and parent.func is call:
                yield _finding(
                    ctx,
                    "recompile-hazard",
                    call,
                    "jax.jit(f)(...) constructs and immediately calls a "
                    "fresh wrapper: the compile cache is keyed on the "
                    "wrapper, so each call site pays a retrace; bind "
                    "jax.jit(f) once and reuse it",
                )
                continue
        # call sites of known-static functions: unhashable static values
        if isinstance(call.func, ast.Name):
            statics = static_names_by_func.get(call.func.id)
            if statics:
                for kw in call.keywords:
                    if kw.arg in statics and isinstance(
                        kw.value,
                        (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp),
                    ):
                        yield _finding(
                            ctx,
                            "recompile-hazard",
                            kw.value,
                            f"unhashable value for static argument "
                            f"`{kw.arg}`: jit static args key the compile "
                            "cache and must be hashable (tuple, frozen "
                            "dataclass); a list/dict/set raises or, worse, "
                            "defeats caching",
                        )


# --------------------------------------------------------------------------
# float64-literal
# --------------------------------------------------------------------------

_F64_PATHS = frozenset({"numpy.float64", "jax.numpy.float64"})


def _is_f64(ctx: ModuleContext, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    return ctx.resolve(node) in _F64_PATHS


@rule(
    "float64-literal",
    "float64 dtype inside traced code or as a signature default — silently "
    "becomes float32 under default x64-disabled JAX, or doubles slab "
    "memory when x64 is on",
)
def float64_literal(ctx: ModuleContext) -> Iterator[Finding]:
    # (a) anywhere inside a jit scope
    seen: set[ast.AST] = set()
    for scope in find_jit_scopes(ctx):
        for node in ast.walk(scope.node):
            if node in seen:
                continue
            if _is_f64(ctx, node):
                seen.add(node)
                yield _finding(
                    ctx,
                    "float64-literal",
                    node,
                    "float64 inside a traced function: under the default "
                    "x64-disabled config this silently produces float32; "
                    "spell the intended dtype explicitly",
                )
    # (b) as a parameter default anywhere (the classic dtype=np.float64)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = [*node.args.defaults, *node.args.kw_defaults]
        for d in defaults:
            if d is not None and d not in seen and _is_f64(ctx, d):
                seen.add(d)
                yield _finding(
                    ctx,
                    "float64-literal",
                    d,
                    f"float64 default in `{node.name}` signature: callers "
                    "inherit a dtype the float32 pipeline will down-cast "
                    "(or double memory under x64); default to the "
                    "pipeline dtype",
                )


# --------------------------------------------------------------------------
# int32-overflow
# --------------------------------------------------------------------------

_I32_PATHS = frozenset({"numpy.int32", "jax.numpy.int32"})
_GUARD_LIMIT = 2**31


def _is_i32_dtype(ctx: ModuleContext, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int32":
        return True
    return ctx.resolve(node) in _I32_PATHS


def _has_arith(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp) and isinstance(
            sub.op, (ast.Add, ast.Mult, ast.Sub)
        ):
            return True
    return False


def _int_guard_present(ctx: ModuleContext, node: ast.AST) -> bool:
    """2**31 / 1<<31 / iinfo(int32) mentioned in the enclosing function."""
    func = ctx.enclosing_function(node) or ctx.tree
    for sub in ast.walk(func):
        if isinstance(sub, ast.Constant) and sub.value in (
            _GUARD_LIMIT,
            _GUARD_LIMIT - 1,
        ):
            return True
        if isinstance(sub, ast.BinOp):
            if (
                isinstance(sub.op, (ast.Pow, ast.LShift))
                and isinstance(sub.left, ast.Constant)
                and sub.left.value == 2
                and isinstance(sub.right, ast.Constant)
                and sub.right.value == 31
            ):
                return True
        if isinstance(sub, ast.Call):
            path = ctx.resolve(sub.func)
            if path in ("numpy.iinfo", "jax.numpy.iinfo"):
                return True
    return False


@rule(
    "int32-overflow",
    "int32 cast of computed index arithmetic with no 2**31 guard in scope "
    "— flat indices silently wrap at scale",
)
def int32_overflow(ctx: ModuleContext) -> Iterator[Finding]:
    for call in iter_calls(ctx):
        operand: ast.AST | None = None
        # X.astype(np.int32 / "int32")
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype"
            and call.args
            and _is_i32_dtype(ctx, call.args[0])
        ):
            operand = call.func.value
        # np.int32(X)
        elif ctx.resolve(call.func) in _I32_PATHS and call.args:
            operand = call.args[0]
        # np.asarray(X, dtype=np.int32)
        elif ctx.resolve(call.func) in (
            "numpy.asarray",
            "numpy.array",
        ) and call.args:
            for kw in call.keywords:
                if kw.arg == "dtype" and _is_i32_dtype(ctx, kw.value):
                    operand = call.args[0]
        if operand is None or not _has_arith(operand):
            continue
        if _int_guard_present(ctx, call):
            continue
        yield _finding(
            ctx,
            "int32-overflow",
            call,
            "int32 cast of index arithmetic with no 2**31 guard in the "
            "enclosing function: past 2^31 elements the indices silently "
            "wrap (data/random_effect.py's inverse score map was the "
            "in-repo case); assert the bound or promote to int64",
        )


# --------------------------------------------------------------------------
# bf16-accumulation
# --------------------------------------------------------------------------

# Reductions whose accumulator silently inherits a bf16 operand dtype.
# Applies to EVERY analyzed module — the fused-fit modules where the
# policy began, `serve/` (bf16 coefficient tables score under the same
# f32-accumulator invariant), and `ops/segment_reduce.py`'s fallback
# path alike; tier 5 (`--numerics`, NUMERICS_AUDIT) is the semantic
# form of this rule and proves on jaxprs where the accumulator is
# already f32 — those sites carry reasoned suppressions instead of
# rewrites. ops/segment_reduce.sorted_segment_sum itself is
# deliberately absent from the call set: its kernel accumulates f32
# internally (verified per trace by the tier-5 contract).
_BF16_REDUCE_PATHS = frozenset(
    {
        "jax.numpy.sum",
        "jax.numpy.einsum",
        "jax.numpy.dot",
        "jax.numpy.matmul",
        "jax.numpy.tensordot",
        "jax.numpy.vdot",
        "jax.numpy.inner",
        "jax.ops.segment_sum",
    }
)
_BF16_PATHS = frozenset({"jax.numpy.bfloat16", "ml_dtypes.bfloat16"})
_F32_PATHS = frozenset({"jax.numpy.float32", "numpy.float32"})


def _mentions_bf16(ctx: ModuleContext, node: ast.AST) -> bool:
    """A bf16 STORAGE marker anywhere in the operand expression: the
    jnp.bfloat16 dtype object, the "bfloat16" string literal, an
    .astype(<bf16>) cast, or the ops.precision storage helpers
    (in_storage/like_storage/storage_dtype), whose results are bf16 by
    contract under the mixed policy."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and sub.value == "bfloat16":
            return True
        if ctx.resolve(sub) in _BF16_PATHS:
            return True
        if isinstance(sub, ast.Call) and isinstance(
            sub.func, ast.Attribute
        ) and sub.func.attr in (
            "in_storage", "like_storage", "storage_dtype"
        ):
            return True
    return False


def _f32_accumulator_kwarg(ctx: ModuleContext, call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg in ("dtype", "preferred_element_type"):
            if ctx.resolve(kw.value) in _F32_PATHS or (
                isinstance(kw.value, ast.Constant)
                and kw.value.value == "float32"
            ):
                return True
    return False


@rule(
    "bf16-accumulation",
    "jnp.sum/einsum/dot/segment_sum over a bf16-marked operand with no "
    "f32 accumulator (dtype=/preferred_element_type=float32) — the "
    "reduction accumulates in bf16 and loses ~3 decimal digits across "
    "a row axis; use ops.precision.acc_sum/acc_einsum",
)
def bf16_accumulation(ctx: ModuleContext) -> Iterator[Finding]:
    for call in iter_calls(ctx):
        if ctx.resolve(call.func) not in _BF16_REDUCE_PATHS:
            continue
        if _f32_accumulator_kwarg(ctx, call):
            continue
        if not any(_mentions_bf16(ctx, a) for a in call.args):
            continue
        yield _finding(
            ctx,
            "bf16-accumulation",
            call,
            "reduction over a bf16-marked operand accumulates in bf16 "
            "(f32-accumulator invariant of the mixed-precision policy, "
            "ops/precision.py): pass dtype=/preferred_element_type="
            "jnp.float32 or route through precision.acc_sum/acc_einsum",
        )


# --------------------------------------------------------------------------
# debug-debris
# --------------------------------------------------------------------------

_DEBRIS_CALLS = {
    "jax.debug.print": "jax.debug.print adds a host callback per trace — "
    "debugging leftovers serialize the device stream",
    "jax.debug.breakpoint": "jax.debug.breakpoint halts every execution",
    "pdb.set_trace": "pdb.set_trace() left in library code",
}


@rule(
    "debug-debris",
    "jax.debug.print / pdb / breakpoint() / block_until_ready in a hot "
    "loop — debugging leftovers that serialize or halt production runs",
)
def debug_debris(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            names = (
                [a.name for a in node.names]
                if isinstance(node, ast.Import)
                else [node.module or ""]
            )
            if any(n == "pdb" or n.startswith("pdb.") for n in names):
                yield _finding(
                    ctx, "debug-debris", node, "`import pdb` in library code"
                )
    for call in iter_calls(ctx):
        if isinstance(call.func, ast.Name) and call.func.id == "breakpoint":
            yield _finding(
                ctx, "debug-debris", call, "`breakpoint()` in library code"
            )
            continue
        path = ctx.resolve(call.func)
        if path in _DEBRIS_CALLS:
            yield _finding(ctx, "debug-debris", call, _DEBRIS_CALLS[path])
            continue
        is_bur = (
            path == "jax.block_until_ready"
            or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "block_until_ready"
            )
        )
        if is_bur and nearest_loop_before_function(ctx, call) is not None:
            yield _finding(
                ctx,
                "debug-debris",
                call,
                "block_until_ready inside a loop serializes the async "
                "dispatch pipeline per iteration; sync once after the "
                "loop (or not at all — the first consumer blocks)",
            )


# --------------------------------------------------------------------------
# use-after-donate
# --------------------------------------------------------------------------


def _literal_donate_positions(call: ast.Call, ctx: ModuleContext):
    """Donated positions from a LITERAL donate_argnums keyword on a
    ``jax.jit(...)`` / ``functools.partial(jax.jit, ...)`` call, or None
    when the call is not a jit wrapper or the positions are not literal
    (a computed donate tuple — e.g. the CPU-gated serve swap — cannot be
    checked flow-insensitively, so it is skipped, not guessed)."""
    path = ctx.resolve(call.func)
    if path == "functools.partial":
        if not (
            call.args and ctx.resolve(call.args[0]) in _JIT_PATHS
        ):
            return None
    elif path not in _JIT_PATHS:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in v.elts
        ):
            return tuple(e.value for e in v.elts)
        return None  # non-literal: skipped by design
    return None


def _donating_callables(ctx: ModuleContext) -> dict[str, tuple[int, ...]]:
    """Module-level names that donate operand positions when called.

    Three shapes, mirroring how this repo spells donation:

    - ``name = jax.jit(fn, donate_argnums=...)`` assignments;
    - ``@functools.partial(jax.jit, ..., donate_argnums=...)`` defs;
    - ONE hop of propagation: a plain module-level function that passes
      one of its OWN parameters to a known donating callable at a
      donated position is itself donating at that parameter's position
      (the ``_sub_add`` dispatcher pattern). Methods are not propagated
      (``self``-relative dataflow is out of a line lint's reach).
    """
    out: dict[str, tuple[int, ...]] = {}
    module_defs: list[ast.FunctionDef] = []
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _literal_donate_positions(node.value, ctx)
            if pos:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = pos
        elif isinstance(node, ast.FunctionDef):
            module_defs.append(node)
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _literal_donate_positions(dec, ctx)
                    if pos:
                        out[node.name] = pos
    for fn in module_defs:
        if fn.name in out:
            continue
        params = [a.arg for a in fn.args.args]
        forwarded: set[int] = set()
        for call in ast.walk(fn):
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
            ):
                continue
            donated = out.get(call.func.id)
            if not donated:
                continue
            for p in donated:
                if p < len(call.args) and isinstance(
                    call.args[p], ast.Name
                ):
                    arg = call.args[p].id
                    if arg in params:
                        forwarded.add(params.index(arg))
        if forwarded:
            out[fn.name] = tuple(sorted(forwarded))
    return out


@rule(
    "use-after-donate",
    "a binding passed at a donate_argnums position is read after the "
    "call site — the donated buffer may already be deleted or aliased",
)
def use_after_donate(ctx: ModuleContext) -> Iterator[Finding]:
    donating = _donating_callables(ctx)
    if not donating:
        return
    for call in iter_calls(ctx):
        if not isinstance(call.func, ast.Name):
            continue
        positions = donating.get(call.func.id)
        if not positions:
            continue
        scope = ctx.enclosing_function(call) or ctx.tree
        for p in positions:
            if p >= len(call.args) or not isinstance(
                call.args[p], ast.Name
            ):
                continue
            name = call.args[p].id
            # "After the call" is after its closing paren — a multi-line
            # call's own argument list must not read as a use-after.
            after = (
                call.end_lineno or call.lineno,
                call.end_col_offset or 0,
            )
            loads = sorted(
                (
                    n
                    for n in ast.walk(scope)
                    if isinstance(n, ast.Name)
                    and n.id == name
                    and isinstance(n.ctx, ast.Load)
                    and (n.lineno, n.col_offset) > after
                ),
                key=lambda n: (n.lineno, n.col_offset),
            )
            if not loads:
                continue
            first = loads[0]
            rebound = any(
                isinstance(n, ast.Name)
                and n.id == name
                and isinstance(n.ctx, ast.Store)
                and after < (n.lineno, n.col_offset)
                and n.lineno < first.lineno
                for n in ast.walk(scope)
            )
            if rebound:
                continue
            # Only the FIRST read is flagged (every later read is the
            # same taint; one finding per donation keeps the signal
            # reviewable and the suppression story one line).
            yield _finding(
                ctx,
                "use-after-donate",
                first,
                f"`{name}` was donated to `{call.func.id}` at line "
                f"{call.lineno} (donate_argnums position {p}) and is "
                "read again here: the donated buffer may be deleted or "
                "aliased by then — rebind the call's result before any "
                "further read, or route this case through a "
                "non-donating twin",
            )
