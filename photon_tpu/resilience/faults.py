"""Deterministic fault injection at the runtime's named boundaries.

Chaos testing is only useful if a failing run can be REPLAYED: every
injection here is driven by a seeded ``FaultPlan``, so the exact same
faults fire at the exact same call indices on the 2-core CI box as on a
dev machine. The production code carries one ``faults.check(point)``
call at each boundary — a single module-global read when nothing is
armed (zero overhead on clean runs; no locks, no allocation).

Injection points live at the existing architectural boundaries (the
places real failures enter):

==================  ======================================================
point               boundary
==================  ======================================================
``ingest.plan``     per-coordinate planner thunk (GameEstimator
                    ``_build_datasets.build_one`` on the plan pool)
``ingest.chunk``    chunked host pass (``pipeline.map_chunked`` workers)
``compile.aot``     AOT compile (``utils.compile_cache.aot_compile`` —
                    the warm-compile thread and the serve ladder)
``transfer.packed`` packed host->device transfer
                    (``pipeline.packed_device_put``)
``fit.dispatch``    fused whole-fit program dispatch (``FusedFit.run``)
``serve.dispatch``  serve queue batch dispatch
                    (``MicroBatchQueue._dispatch``)
``checkpoint.write``training checkpoint write, AFTER the tmp file but
                    BEFORE the atomic rename (the mid-write crash window)
``cd.iteration``    end of one outer CD iteration, AFTER its checkpoint
                    was written (the kill-and-resume window)
``io.shard_read``   streaming-ingest shard READ (bytes + size/checksum
                    verification against the ingest manifest,
                    ``data/stream.py``)
``io.shard_decode`` streaming-ingest shard DECODE (Avro container ->
                    window arrays, ``data/stream.py``)
``pilot.ingest``    pilot INGEST stage (the supervisor's streamed ingest
                    of a cycle's shard snapshot, ``pilot/loop.py``)
``pilot.train``     pilot TRAIN stage (warm-start retrain under the
                    training checkpointer)
``pilot.validate``  pilot VALIDATE stage (candidate-vs-serving
                    evaluation, BEFORE the promotion gate decides)
``pilot.promote``   pilot PROMOTE stage, AFTER the new generation's ring
                    commit but BEFORE the serving ``reload()`` commit —
                    the kill-during-promotion window
``pilot.rollback``  pilot ROLLBACK (SLO-burn-triggered revert to the
                    previous ring generation)
==================  ======================================================

Fault kinds (``FaultSpec.error``): ``"transient"`` raises
``TransientError`` (the retry layer's food), ``"poison"`` raises
``PoisonError`` (never retried), ``"crash"`` raises ``InjectedCrash``
(simulated process death), ``"delay"`` sleeps ``seconds`` (an injected
stall — e.g. to hold a subprocess mid-fit while a test sends SIGTERM),
``"sigterm"`` sends SIGTERM to the own process (drives the signal
handler deterministically from inside the run).

Triggers are ``nth`` (fire on the Nth call to the point, 1-based,
once) or ``probability`` (an independent seeded draw per call — the
per-point RNG substream is derived from ``(seed, crc32(point))``, so
adding calls at one point never perturbs another point's draws).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import threading
import time
import zlib

import numpy as np

from photon_tpu.resilience.errors import (
    InjectedCrash,
    PoisonError,
    TransientError,
)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`). The armed plan is read/advanced from every pool the
# runtime owns (plan/chunk/compile workers, the serve worker, the
# training thread); `_lock` guards the active-plan reference, the
# plan's call counters / fired log, and the crash-listener registry, so
# nth-call accounting is exact under concurrency. `check` reads the
# bare reference FIRST and returns without touching the lock when
# nothing is armed — the clean-run hot path takes no lock. Injected
# sleeps/raises — and crash-listener callbacks (the flight recorder's
# dump) — happen OUTSIDE the lock.
CONCURRENCY_AUDIT = dict(
    name="fault-injection",
    locks={
        "_lock": ("_active", "_counts", "_fired", "_crash_listeners"),
    },
    thread_entries=(),
    jax_dispatch_ok={},
)

INJECTION_POINTS = (
    "ingest.plan",
    "ingest.chunk",
    "compile.aot",
    "transfer.packed",
    "fit.dispatch",
    "serve.dispatch",
    "checkpoint.write",
    "cd.iteration",
    "io.shard_read",
    "io.shard_decode",
    "pilot.ingest",
    "pilot.train",
    "pilot.validate",
    "pilot.promote",
    "pilot.rollback",
)

_KINDS = ("transient", "poison", "crash", "delay", "sigterm")

ENV_VAR = "PHOTON_TPU_FAULT_PLAN"


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injected fault: where, when, and what."""

    point: str
    error: str = "transient"  # transient | poison | crash | delay | sigterm
    nth: int | None = None  # fire on the Nth call (1-based), once
    probability: float | None = None  # else: seeded per-call draw
    seconds: float = 0.0  # delay kind: how long to stall
    message: str = ""

    def __post_init__(self):
        if self.point not in INJECTION_POINTS:
            raise ValueError(
                f"unknown injection point {self.point!r} "
                f"(known: {', '.join(INJECTION_POINTS)})")
        if self.error not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.error!r} (known: "
                f"{', '.join(_KINDS)})")
        if (self.nth is None) == (self.probability is None):
            raise ValueError(
                "exactly one of nth / probability must be set "
                f"({self.point!r})")
        if self.nth is not None and self.nth < 1:
            raise ValueError(f"nth is 1-based, got {self.nth}")
        if self.probability is not None and not (
            0.0 < self.probability <= 1.0
        ):
            raise ValueError(
                f"probability must be in (0, 1], got {self.probability}")


class FaultPlan:
    """A seeded, replayable set of fault specs.

    Determinism contract: for a fixed (specs, seed) and a fixed
    per-point call sequence, the same calls trigger the same faults —
    per-point RNG substreams are keyed by ``(seed, crc32(point))`` so
    points never perturb each other, and nth-call counters are advanced
    under the module lock so concurrent callers count exactly.
    """

    def __init__(self, specs, *, seed: int = 0):
        self.specs = tuple(
            s if isinstance(s, FaultSpec) else FaultSpec(**s)
            for s in specs
        )
        self.seed = int(seed)
        self._by_point: dict[str, list[FaultSpec]] = {}
        for s in self.specs:
            self._by_point.setdefault(s.point, []).append(s)
        self._counts = {p: 0 for p in self._by_point}
        self._rngs = {
            p: np.random.default_rng(
                [self.seed, zlib.crc32(p.encode("utf-8"))]
            )
            for p in self._by_point
        }
        self._armed_nth: set[tuple[str, int]] = set()
        self._fired: list[dict] = []

    @staticmethod
    def from_json(blob: str | dict) -> "FaultPlan":
        """Build a plan from its JSON form:
        ``{"seed": 7, "faults": [{"point": ..., "nth": 1, ...}, ...]}``."""
        raw = json.loads(blob) if isinstance(blob, str) else dict(blob)
        return FaultPlan(raw.get("faults", ()), seed=raw.get("seed", 0))

    def _advance(self, point: str) -> FaultSpec | None:
        """Count one call to ``point`` and return the triggered spec, if
        any. Takes the module lock itself: counters and the fired log
        stay exact under concurrent callers from every pool."""
        with _lock:
            specs = self._by_point.get(point)
            if not specs:
                return None
            self._counts[point] += 1
            call = self._counts[point]
            rng = self._rngs[point]
            for idx, s in enumerate(specs):
                if s.nth is not None:
                    if (
                        call == s.nth
                        and (point, idx) not in self._armed_nth
                    ):
                        self._armed_nth.add((point, idx))
                        self._fired.append({
                            "point": point, "call": call,
                            "error": s.error,
                        })
                        return s
                elif rng.random() < s.probability:
                    self._fired.append({
                        "point": point, "call": call, "error": s.error,
                    })
                    return s
            return None


_lock = threading.Lock()
_active: FaultPlan | None = None
# Crash-fault listeners: called (point, message) at the raise point of a
# `crash`-kind fault, BEFORE InjectedCrash propagates — how the flight
# recorder (obs/flight.py) guarantees a post-mortem even when a caller
# catches the crash. Registration is lock-guarded; callbacks run outside
# the lock and must never raise into the fault path (logged instead).
_crash_listeners: list = []


def on_crash(fn) -> None:
    """Register ``fn(point, message)`` to run when a ``crash``-kind
    fault fires (at the raise point, before ``InjectedCrash``)."""
    with _lock:
        _crash_listeners.append(fn)


def remove_crash_listener(fn) -> None:
    """Unregister a crash listener. Idempotent."""
    with _lock:
        try:
            _crash_listeners.remove(fn)
        except ValueError:
            pass


def arm(plan: FaultPlan) -> None:
    """Make ``plan`` the process's active fault plan."""
    global _active
    with _lock:
        _active = plan


def disarm() -> None:
    global _active
    with _lock:
        _active = None


def active_plan() -> FaultPlan | None:
    return _active


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """Scope guard: arm ``plan`` for the block, disarm after."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


def arm_from_env(env_var: str = ENV_VAR) -> FaultPlan | None:
    """Arm a plan from ``PHOTON_TPU_FAULT_PLAN`` (JSON, or ``@path`` to
    a JSON file) — how the chaos CI reaches into CLI subprocesses.
    Returns the armed plan, or None when the variable is unset."""
    raw = os.environ.get(env_var)
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    plan = FaultPlan.from_json(raw)
    arm(plan)
    return plan


def _fault_instant(point: str, error: str) -> None:
    """Mark a fired fault on the trace timeline (no-op when telemetry is
    disabled or the obs layer is unimportable in a stripped embed)."""
    try:
        from photon_tpu.obs import trace as obs_trace

        obs_trace.instant(
            "fault.fired", cat="fault", point=point, error=error
        )
    except Exception:  # pragma: no cover — telemetry must never alter
        # the injected fault's semantics.
        pass


def fired() -> list[dict]:
    """Snapshot of the active plan's fired-fault log (empty when no
    plan is armed or nothing fired) — the chaos assertions' evidence."""
    with _lock:
        return list(_active._fired) if _active is not None else []


def check(point: str) -> None:
    """The injection hook production code calls at each boundary.

    Disarmed (the production default): ONE module-global read, no lock,
    no allocation. Armed: counts the call and executes any triggered
    spec — raising for transient/poison/crash kinds, stalling for
    delay, signalling for sigterm — with the stall/raise OUTSIDE the
    module lock.
    """
    if _active is None:
        return
    plan = _active
    spec = plan._advance(point) if plan is not None else None
    if spec is None:
        return
    msg = spec.message or f"injected {spec.error} fault at {point}"
    _fault_instant(point, spec.error)
    if spec.error == "transient":
        raise TransientError(msg)
    if spec.error == "poison":
        raise PoisonError(msg)
    if spec.error == "crash":
        with _lock:
            listeners = list(_crash_listeners)
        for fn in listeners:
            try:
                fn(point, msg)
            except Exception:  # noqa: BLE001 — a listener (the flight
                # recorder's dump) must never replace the injected crash
                # the chaos run is testing for.
                logging.getLogger(__name__).exception(
                    "crash-fault listener raised at %s", point)
        raise InjectedCrash(msg)
    if spec.error == "sigterm":
        import signal

        os.kill(os.getpid(), signal.SIGTERM)
        # Give the interpreter a beat to run the handler on the main
        # thread (delivery is asynchronous when called off-main-thread).
        time.sleep(0.05)
        return
    time.sleep(spec.seconds)
