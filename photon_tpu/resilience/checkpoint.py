"""Crash-safe training checkpoints: atomic writes, a validating manifest.

Spark restarts a failed photon-ml driver and lineage recomputes what was
lost; a preempted TPU host has no lineage — whatever block-coordinate-
descent state was in HBM is gone. The ``TrainingCheckpointer`` closes
that gap: after every outer CD iteration the estimator hands it the full
``GameModel`` and it persists one loadable recovery point.

Write protocol (crash-safe at every step):

1. the model npz is written to a TEMP name, fsynced, ``os.replace``d
   into a per-step filename (``checkpoint-c<config>-i<iter>.npz``), and
   the directory entry is fsynced — ``io.model_io.atomic_write_bytes``
   owns that dance for every durable artifact here (and the
   ``checkpoint.write`` fault-injection point sits exactly in the
   mid-write crash window);
2. ``manifest.json`` — schema version, the training configuration's
   STATIC KEY, config index / iteration, the npz filename and its
   sha256 — is then committed through the same dance. The manifest is
   the single commit point: a crash before its replace leaves the
   PREVIOUS manifest pointing at the PREVIOUS (still present) npz.
3. superseded npz files are garbage-collected only after the manifest
   commit.

Load protocol: read the manifest (``CheckpointError`` when absent or a
future schema), verify the npz hash (``CorruptModelError`` on
mismatch — a torn copy can never be half-loaded), decode the model.

The STATIC KEY pins what a checkpoint may resume: a sha1 over the task,
per-coordinate optimization configs, update sequence, iteration count,
locked set, and the opt-config grid. ``--resume`` with any of those
changed fails with ``ResumeMismatchError`` instead of silently
continuing a different optimization (day-over-day warm starts go
through ``warm_start_model_dir``, which deliberately has no such pin).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1
MANIFEST_FILE = "manifest.json"

# Completed-config final artifacts (``config-c<idx>-final.npz``) are
# RETAINED across later configs: a resumed multi-config run rebuilds
# the completed configs' results from them so the returned list lines
# up with the full grid (select_best / tuning / artifact indices).
# The in-progress config's best-by-validation model is retained the
# same way (``config-c<idx>-best.npz``, rewritten whenever the best
# improves): the per-iteration chain holds final-iteration state, so
# without it a resumed run would restart best selection from scratch
# and could silently return a worse model than the uninterrupted run.
import re as _re

_FINAL_RE = _re.compile(r"^config-c(\d+)-final\.npz$")
_BEST_RE = _re.compile(r"^config-c(\d+)-best\.npz$")


def _final_name(config_index: int) -> str:
    return f"config-c{config_index:03d}-final.npz"


def _best_name(config_index: int) -> str:
    return f"config-c{config_index:03d}-best.npz"


def training_static_key(estimator, opt_config_sequence=None) -> str:
    """Hashable identity of everything a resumed run must share with
    the run that wrote the checkpoint.

    Built from dataclass reprs (deterministic for the frozen config
    dataclasses involved) of: task, per-coordinate configurations,
    update sequence, iteration count, locked coordinates, incremental
    flag, normalization shard names, and the optimization-config grid.
    Data contents are deliberately NOT keyed: resuming on refreshed
    data is warm-start territory, not a config mismatch.
    """
    parts = [
        repr(estimator.task),
        repr(sorted(
            (cid, repr(cfg))
            for cid, cfg in estimator.coordinate_configs.items()
        )),
        repr(list(estimator.update_sequence)),
        repr(int(estimator.num_iterations)),
        repr(sorted(estimator.locked_coordinates)),
        repr(bool(estimator.incremental_training)),
        repr(sorted(estimator.normalization)),
    ]
    if opt_config_sequence is not None:
        parts.append(repr([
            sorted((cid, repr(c)) for cid, c in cfgs.items())
            for cfgs in opt_config_sequence
        ]))
    return hashlib.sha1("\n".join(parts).encode("utf-8")).hexdigest()


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _atomic_write_json(path: str, payload: dict) -> None:
    from photon_tpu.io.model_io import atomic_write_bytes

    atomic_write_bytes(
        path,
        json.dumps(payload, indent=2, sort_keys=True).encode("utf-8"),
    )


@dataclasses.dataclass(frozen=True)
class TrainingCheckpoint:
    """A loaded recovery point (what ``fit(resume=...)`` consumes)."""

    model: object  # GameModel
    config_index: int
    iteration: int  # last COMPLETED outer CD iteration of that config
    static_key: str
    interrupted: bool
    manifest: dict
    path: str  # the npz the model came from


class TrainingCheckpointer:
    """Writes one recovery point per completed outer CD iteration.

    Single-writer by design: only the training thread calls ``save``
    (the estimator invokes it from the CD loop's iteration callback),
    so it owns no locks. ``write_emergency`` re-commits the LAST saved
    state with ``interrupted=True`` — the CLI's signal handler calls it
    so an operator can tell a clean stop from a killed one.
    """

    def __init__(self, directory: str, static_key: str):
        self.directory = directory
        self.static_key = static_key
        self._last: tuple[object, int, int] | None = None
        self._committed_fname: str | None = None
        os.makedirs(directory, exist_ok=True)
        # Adopt what an interrupted run left behind so this instance's
        # GC keeps retaining it: the manifest-referenced npz (a fresh
        # checkpointer healing a config-final must not delete the
        # committed recovery point it is finalizing FROM) and the
        # best-model artifact (after a resume the best may never
        # improve again, so the hook may never rewrite the file —
        # losing it would strand the NEXT resume without the
        # pre-crash best).
        mpath = os.path.join(directory, MANIFEST_FILE)
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    self._committed_fname = json.load(f).get("file")
            except (OSError, json.JSONDecodeError):
                pass  # unreadable manifest: load will surface it
        self._best_fname: str | None = None
        for name in sorted(os.listdir(directory)):
            if _BEST_RE.match(name):
                self._best_fname = name
        # The final artifact THIS instance committed for the config in
        # progress. ``save``'s GC only retains finals at index <
        # config_index (an on-disk final at the CURRENT index is stale
        # debris from an earlier run reusing the directory), so the
        # emergency re-commit after ``save_config_final(ci)`` — cursor
        # still at ci — must pin its own final explicitly or destroy
        # the artifact the resume path depends on.
        self._final_fname: str | None = None
        # Run-scoped provenance riding every manifest commit (the
        # ``run`` block): the streaming-ingest cursor location/manifest
        # hash and the init-model digest, so crash recovery can resume
        # ingest-then-descent END TO END — the manifest records not
        # just where the descent was, but which ingest cursor and which
        # warm-start model the run was built from (DATA.md).
        self._run_meta: dict | None = None

    def set_run_meta(self, meta: dict | None) -> None:
        """Attach run provenance (ingest cursor, init-model digest) to
        every subsequent manifest commit. JSON-serializable values only;
        None clears."""
        self._run_meta = None if meta is None else dict(meta)

    def save(
        self,
        model,
        *,
        config_index: int,
        iteration: int,
        interrupted: bool = False,
    ) -> str:
        """Commit one recovery point; returns the npz path."""
        from photon_tpu.io.model_io import save_checkpoint

        # The emergency re-commit gets its OWN filename: writing over
        # the npz the current manifest references would open a window
        # (after the npz os.replace, before the manifest commit) where
        # a second kill leaves the manifest's sha256 pointing at
        # changed bytes — the crash-safety layer destroying its only
        # recovery point.
        suffix = "-interrupted" if interrupted else ""
        fname = (
            f"checkpoint-c{config_index:03d}-i{iteration:03d}"
            f"{suffix}.npz"
        )
        path = os.path.join(self.directory, fname)
        meta = {
            "schema_version": SCHEMA_VERSION,
            "static_key": self.static_key,
            "config_index": int(config_index),
            "iteration": int(iteration),
            "interrupted": bool(interrupted),
        }
        # Step 1: the npz (atomic internally; carries the loop state in
        # its own embedded manifest so the artifact is self-contained).
        digest = save_checkpoint(model, path, extra_meta=meta)
        # Step 2: the manifest commit point (the digest comes from the
        # write itself — the multi-GB npz is never re-read to hash it).
        manifest = dict(meta)
        manifest["file"] = fname
        manifest["sha256"] = digest
        manifest["written_at"] = time.time()
        if self._run_meta is not None:
            manifest["run"] = dict(self._run_meta)
        _atomic_write_json(
            os.path.join(self.directory, MANIFEST_FILE), manifest
        )
        self._last = (model, int(config_index), int(iteration))
        self._committed_fname = fname
        keep = {fname}
        if self._best_fname is not None:
            keep.add(self._best_fname)
        if self._final_fname is not None:
            keep.add(self._final_fname)
        self._gc(keep=keep, final_max=int(config_index) - 1)
        logger.info(
            "checkpoint: config %d iteration %d committed to %s",
            config_index, iteration, path)
        return path

    def save_best(self, model, *, config_index: int) -> str:
        """Retain the in-progress config's best-by-validation model
        (``config-c<idx>-best.npz``, rewritten atomically whenever the
        best improves — the estimator's iteration hook commits it
        BEFORE the iteration's manifest, so a crash at any point leaves
        a best no newer than one replayed iteration ahead of the
        cursor). A resumed run seeds CD's best tracking from it;
        ``save_config_final`` supersedes it when the config completes."""
        from photon_tpu.io.model_io import save_checkpoint

        fname = _best_name(config_index)
        path = os.path.join(self.directory, fname)
        save_checkpoint(model, path, extra_meta={
            "schema_version": SCHEMA_VERSION,
            "static_key": self.static_key,
            "config_index": int(config_index),
            "kind": "config_best",
        })
        self._best_fname = fname
        return path

    def save_config_final(self, model, *, config_index: int) -> str:
        """Persist a completed config's BEST model as a retained
        artifact (``config-c<idx>-final.npz``). The iteration manifest
        stays the recovery point; these files exist so a resumed run
        can rebuild the completed configs' ``GameFitResult`` entries
        (the per-iteration chain holds final-iteration models, not the
        best-by-validation model this config actually contributed)."""
        from photon_tpu.io.model_io import save_checkpoint

        fname = _final_name(config_index)
        path = os.path.join(self.directory, fname)
        save_checkpoint(model, path, extra_meta={
            "schema_version": SCHEMA_VERSION,
            "static_key": self.static_key,
            "config_index": int(config_index),
            "kind": "config_final",
        })
        keep = {fname}
        if self._committed_fname is not None:
            keep.add(self._committed_fname)
        # The config's best artifact is superseded: the final IS the
        # best model this config contributed — let the GC drop it.
        self._best_fname = None
        self._final_fname = fname
        self._gc(keep=keep, final_max=int(config_index))
        logger.info(
            "checkpoint: config %d final model retained at %s",
            config_index, path)
        return path

    def write_emergency(self) -> str | None:
        """Re-commit the last saved state flagged ``interrupted`` (the
        signal-handler path). None when nothing was ever saved — an
        interrupt during ingest has no loop state to persist."""
        if self._last is None:
            return None
        model, ci, it = self._last
        return self.save(
            model, config_index=ci, iteration=it, interrupted=True
        )

    def _gc(self, *, keep: set, final_max: int) -> None:
        """Drop superseded npz files + stale tmp debris (post-commit).

        Config-final artifacts with index <= ``final_max`` are
        retained for resume; finals at a HIGHER index are stale debris
        from an earlier, deeper run reusing this directory."""
        for name in os.listdir(self.directory):
            if name in keep or name == MANIFEST_FILE:
                continue
            m = _FINAL_RE.match(name)
            if m is not None and int(m.group(1)) <= final_max:
                continue
            if name.startswith(("checkpoint-", "config-")) \
                    or ".tmp." in name:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:  # pragma: no cover — concurrent cleanup
                    pass


def load_training_checkpoint(directory: str) -> TrainingCheckpoint:
    """Load the committed recovery point under ``directory``.

    Raises ``CheckpointError`` when there is none (or a future schema),
    ``CorruptModelError`` when the npz does not match its manifest hash
    or fails to decode.
    """
    from photon_tpu.io.model_io import load_checkpoint
    from photon_tpu.resilience.errors import (
        CheckpointError,
        CorruptModelError,
    )

    mpath = os.path.join(directory, MANIFEST_FILE)
    if not os.path.exists(mpath):
        raise CheckpointError(
            f"no training checkpoint manifest at {mpath}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        raise CheckpointError(
            f"checkpoint manifest {mpath} unreadable: {exc}") from exc
    version = manifest.get("schema_version")
    if version != SCHEMA_VERSION:
        raise CheckpointError(
            f"checkpoint manifest {mpath}: schema_version {version!r} "
            f"is not the supported {SCHEMA_VERSION}")
    path = os.path.join(directory, manifest["file"])
    if not os.path.exists(path):
        raise CheckpointError(
            f"checkpoint manifest {mpath} names {manifest['file']!r} "
            "but the file is missing")
    digest = _sha256(path)
    if digest != manifest.get("sha256"):
        raise CorruptModelError(
            f"checkpoint {path}: sha256 {digest} does not match the "
            f"manifest's {manifest.get('sha256')} — the file is torn "
            "or was modified after commit")
    model = load_checkpoint(path)
    return TrainingCheckpoint(
        model=model,
        config_index=int(manifest["config_index"]),
        iteration=int(manifest["iteration"]),
        static_key=str(manifest["static_key"]),
        interrupted=bool(manifest.get("interrupted", False)),
        manifest=manifest,
        path=path,
    )


def has_config_final(directory: str, config_index: int) -> bool:
    """Whether a completed config's retained final artifact exists —
    distinguishes 'training truly completed' from 'crashed in the
    window between the last-iteration checkpoint and the config-final
    retention'."""
    return os.path.exists(
        os.path.join(directory, _final_name(config_index))
    )


def load_config_best(
    directory: str, config_index: int, static_key: str | None = None
):
    """Load the in-progress config's retained best-by-validation model
    (the artifact ``save_best`` wrote), or None when there is none —
    missing is normal (no validation, or no full-model best committed
    yet). Raises ``ResumeMismatchError`` when it was written under a
    different training static key."""
    from photon_tpu.io.model_io import load_checkpoint_meta

    path = os.path.join(directory, _best_name(config_index))
    if not os.path.exists(path):
        return None
    model, meta = load_checkpoint_meta(path)
    _check_static_key(path, meta, static_key)
    return model


def load_config_final(
    directory: str, config_index: int, static_key: str | None = None
):
    """Load a completed config's retained final model (the artifact
    ``save_config_final`` wrote). Raises ``CheckpointError`` when the
    artifact is missing and ``ResumeMismatchError`` when it was written
    under a different training static key."""
    from photon_tpu.io.model_io import load_checkpoint_meta
    from photon_tpu.resilience.errors import CheckpointError

    path = os.path.join(directory, _final_name(config_index))
    if not os.path.exists(path):
        raise CheckpointError(
            f"resume needs {path} to rebuild completed config "
            f"{config_index}'s result, but it is missing — the "
            "checkpoint directory was pruned or predates config-final "
            "retention; retrain from scratch")
    model, meta = load_checkpoint_meta(path)
    _check_static_key(path, meta, static_key)
    return model


def _check_static_key(
    path: str, meta: dict | None, static_key: str | None
) -> None:
    """Raise ``ResumeMismatchError`` when an artifact's recorded
    training static key differs from this run's (either side None =
    nothing to compare)."""
    from photon_tpu.resilience.errors import ResumeMismatchError

    written_key = (meta or {}).get("static_key")
    if static_key is not None and written_key is not None \
            and written_key != static_key:
        raise ResumeMismatchError(
            f"{path} was written under training static key "
            f"{written_key[:12]}..., this run computes "
            f"{static_key[:12]}... — the configuration changed")
