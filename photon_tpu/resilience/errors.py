"""Typed failure vocabulary for the resilience layer.

Spark gave the reference fault tolerance for free — RDD lineage replays
lost partitions, drivers restart mid-job (PAPER.md §0) — so photon-ml
never needed an error taxonomy. This TPU-native build does: retry
policies, circuit breakers, and shedding all dispatch on the TYPE of a
failure, so every failure mode the runtime distinguishes gets its own
exception class here. This module is a dependency-free leaf (stdlib
only) so any layer — io, algorithm, serve, cli — can import it without
cycles.

The split that matters:

- ``TransientError``: expected to succeed on retry (preemption, a
  flaky compile RPC, a transfer hiccup). What ``resilience.retry``
  retries — together with REAL backend faults that ``is_transient``
  recognizes by their gRPC/absl status markers (jaxlib surfaces them
  as plain ``RuntimeError``, so type alone cannot classify them).
- ``PoisonError``: deterministic for its input (a malformed request, a
  bad batch). Retrying would fail forever; it must fail fast and fan
  out no further than its blast radius (one serve batch, one request).

Everything else (corrupt artifacts, deadline/overload/shutdown serving
errors, checkpoint mismatches) is neither: not retried, surfaced to
the caller with enough context to act on.
"""

from __future__ import annotations

import errno as _errno


class TransientError(RuntimeError):
    """A failure expected to clear on retry (preemption, flaky RPC)."""


class PoisonError(RuntimeError):
    """A deterministic failure: retrying the same input cannot help."""


class InjectedCrash(RuntimeError):
    """A fault-injection stand-in for a hard process death (the harness
    raises it where a real crash would kill the process mid-step; tests
    catch it to assert the on-disk state a real crash would leave)."""


class CorruptModelError(RuntimeError):
    """A model/checkpoint artifact failed to decode.

    Raised by ``io.model_io`` loaders instead of leaking codec
    tracebacks (``zipfile.BadZipFile``, Avro struct errors); the message
    names the FILE and what failed so an operator can tell a truncated
    upload from a wrong path.
    """


class CorruptShardError(RuntimeError):
    """A training DATA shard failed integrity or decode.

    The data-path sibling of ``CorruptModelError``: raised by the
    streaming ingest (``data/stream.py``) and the Avro data readers when
    a shard's size/checksum/record count disagrees with the ingest
    manifest or its container fails to decode. The message names the
    FILE so an operator can quarantine or re-fetch exactly one shard —
    never retried (bit rot is deterministic), but eligible for the
    bounded-loss quarantine policy instead of aborting the whole run.
    """


class CheckpointError(RuntimeError):
    """A training checkpoint could not be written or loaded."""


class ResumeMismatchError(CheckpointError):
    """``--resume`` against a checkpoint whose manifest static key does
    not match the current training configuration — resuming would
    silently continue a DIFFERENT optimization than the one that wrote
    the checkpoint."""


class NonFiniteUpdateError(RuntimeError):
    """A coordinate's very first update produced non-finite loss or
    weights: there is no previous iterate to roll back to, so the run
    must fail loudly instead of training on garbage."""


class TrainingInterrupted(BaseException):
    """Raised by the CLI's SIGINT/SIGTERM handler to unwind the fit.

    Subclasses ``BaseException`` (like ``KeyboardInterrupt``) so
    library-level ``except Exception`` recovery paths — retry loops,
    best-effort warm compiles — never swallow a shutdown request.
    """

    def __init__(self, signum: int):
        super().__init__(f"training interrupted by signal {signum}")
        self.signum = signum


class DeadlineExceededError(RuntimeError):
    """A serve request's deadline expired while it was still queued; it
    failed fast, before any device work was spent on it."""


class OverloadedError(RuntimeError):
    """The serve queue is past its shed watermark: the request was
    rejected immediately instead of blocking behind a backlog the
    server cannot clear in time."""


class CircuitOpenError(RuntimeError):
    """The serve dispatch circuit breaker is open (too many consecutive
    batch failures): requests fail fast until the breaker is reset."""


class ShutdownError(RuntimeError):
    """The serve queue was closed (or its drain timed out) with this
    request still pending; it will never be dispatched."""


# Real backend failures do not arrive as TransientError — a preempted
# TPU host, a flaky compile RPC, or a dropped transfer surfaces as a
# jaxlib RuntimeError (XlaRuntimeError subclasses it) or an OSError
# carrying a gRPC/absl status string. These markers are the
# retryable-status vocabulary (gRPC retry guidance: UNAVAILABLE and
# ABORTED are safe to retry; DEADLINE_EXCEEDED here is the RPC-level
# status, not a serve-queue request deadline). Deliberately absent:
# RESOURCE_EXHAUSTED (XLA uses it for HBM OOM, which is deterministic
# for the program being retried), INVALID_ARGUMENT / INTERNAL (compile
# bugs), and everything this module types as non-retryable.
TRANSIENT_ERROR_MARKERS: tuple[str, ...] = (
    "UNAVAILABLE",
    "ABORTED",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "Connection reset",
    "connection reset",
    "Broken pipe",
    "failed to connect",
    "Failed to connect",
    "preempted",
)

# Filesystem/IO errnos that are expected to clear on retry: the
# transient-media vocabulary of a network filesystem or a flaky disk
# path mid-streaming-ingest. An EIO on a shard READ is worth one more
# attempt before the shard is declared bad; deliberately absent are
# ENOENT/EACCES/ENOSPC-style errnos, which are deterministic for the
# retried call (a missing or unreadable shard does not reappear).
TRANSIENT_ERRNOS: tuple[int, ...] = (
    _errno.EIO,
    _errno.EAGAIN,
    _errno.EINTR,
    _errno.ETIMEDOUT,
    _errno.ECONNRESET,
    _errno.ENETRESET,
    _errno.ESTALE,
)


def is_transient(exc: BaseException) -> bool:
    """Classify a failure as expected-to-clear-on-retry.

    ``TransientError`` is transient by construction. Anything this
    module types as deterministic or terminal (poison, corrupt
    artifacts, checkpoint/serving errors, an injected crash, a signal)
    is not, whatever its message says. Real backend faults — jaxlib
    ``RuntimeError``/``OSError``/``ConnectionError`` — are transient
    when their status string carries a ``TRANSIENT_ERROR_MARKERS``
    entry; everything else (shape mismatches, real compile errors) is
    deterministic and must fail on the first attempt.
    """
    if isinstance(exc, TransientError):
        return True
    if isinstance(
        exc,
        (
            PoisonError,
            InjectedCrash,
            CorruptModelError,
            CorruptShardError,
            CheckpointError,
            NonFiniteUpdateError,
            DeadlineExceededError,
            OverloadedError,
            CircuitOpenError,
            ShutdownError,
        ),
    ):
        return False
    if isinstance(exc, ConnectionError):
        return True
    if isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS:
        # EIO-style media blips (network fs, flaky disk path): the
        # streaming ingest's shard read/decode sites retry these; a
        # checksum mismatch after a CLEAN read is CorruptShardError
        # (typed above) and never lands here.
        return True
    if isinstance(exc, (RuntimeError, OSError)):
        msg = str(exc)
        return any(marker in msg for marker in TRANSIENT_ERROR_MARKERS)
    return False
