"""Retry with exponential backoff + deterministic jitter.

The reference got retries from Spark's task scheduler (a lost executor's
work is resubmitted transparently); here the compile / transfer /
dispatch sites call ``call_with_retry`` around their one fallible step.
The policy is deliberately narrow:

- Only transient failures are retried: ``TransientError`` (and
  whatever a caller adds to ``retry_on``), plus REAL backend faults
  the policy's ``classify`` hook recognizes — by default
  ``errors.is_transient``, which matches the gRPC/absl status markers
  (UNAVAILABLE, ABORTED, connection resets, preemption) that jaxlib
  wraps in plain ``RuntimeError``. A ``PoisonError``, a shape
  mismatch, a real XLA compile error — anything deterministic —
  propagates on the FIRST attempt; retrying it would just triple the
  time to the same failure.
- Attempts are capped (``max_attempts``), backoff is exponential with
  a cap, and jitter is drawn from an RNG seeded by the call site name —
  the same run replays the same sleep schedule (chaos tests stay
  deterministic), while distinct sites still decorrelate.
- The happy path is free: no locks, no counters, no allocation unless
  an attempt actually fails. A clean run therefore records ZERO retry
  stats — which the bench/CI clean-run assertions rely on.

Accounting is two-layer: an always-on module counter dict
(``retry_stats()``, mirroring ``PIPELINE_STATS``' role for ingest) and,
when telemetry is enabled, ``retry_*`` obs metrics labeled by site
(``retry_attempts_total``, ``retry_recovered_total``,
``retry_exhausted_total``, ``retry_backoff_seconds_total``).

The retry wrapper is HOST-level machinery around already-built
programs: it never enters a trace, so it adds zero programs and zero
callbacks to any audited jaxpr — the tier-2 ``resilience-retry``
contract (declared in ``resilience/__init__.py``) proves that rather
than promising it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import zlib

import numpy as np

from photon_tpu.resilience import faults
from photon_tpu.resilience.errors import TransientError, is_transient

logger = logging.getLogger(__name__)

# Host-concurrency contract (audited by `python -m photon_tpu.analysis
# --concurrency`): `_lock` guards the module stats dict, written from
# whatever thread retries (compile pool, serve worker, training
# thread). The happy path never takes the lock — stats move only when
# an attempt fails.
CONCURRENCY_AUDIT = dict(
    name="resilience-retry",
    locks={
        "_lock": ("_stats",),
    },
    thread_entries=(),
    jax_dispatch_ok={},
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with bounded jitter."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # delay *= 1 + U(-jitter, +jitter)
    retry_on: tuple = (TransientError,)
    # Predicate for failures whose TYPE cannot identify them (jaxlib
    # wraps backend faults in plain RuntimeError): a failure retries
    # when it is an instance of ``retry_on`` OR ``classify(exc)`` is
    # True. None disables message-based classification entirely
    # (chaos tests that must see ONLY injected faults retried).
    classify: object = is_transient

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError("jitter must be in [0, 1)")

    def delay_for(self, attempt: int, rng) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        base = min(
            self.base_delay_s * self.multiplier ** (attempt - 1),
            self.max_delay_s,
        )
        if self.jitter:
            base *= 1.0 + self.jitter * float(rng.uniform(-1.0, 1.0))
        return max(base, 0.0)


DEFAULT_POLICY = RetryPolicy()

_lock = threading.Lock()
_stats = {
    "retries": 0,  # re-invocations performed
    "recovered": 0,  # calls that succeeded after >= 1 retry
    "exhausted": 0,  # calls that failed after the last attempt
    "backoff_seconds": 0.0,
}


def retry_stats() -> dict:
    """Snapshot of the module counters (all zero on a clean run)."""
    with _lock:
        return dict(_stats)


def reset_retry_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = type(_stats[k])()


def _record(key: str, value=1) -> None:
    with _lock:
        _stats[key] += value


def _metric(name: str, site: str, value: float = 1.0) -> None:
    try:
        from photon_tpu import obs

        if obs.enabled():
            obs.REGISTRY.counter(name, site=site).inc(value)
    except Exception:  # pragma: no cover — telemetry must never abort
        pass


def _instant(name: str, **args) -> None:
    """Mark a retry event on the trace timeline (obs/trace.py) — a
    no-op when telemetry is disabled."""
    try:
        from photon_tpu.obs import trace as obs_trace

        obs_trace.instant(name, cat="retry", **args)
    except Exception:  # pragma: no cover
        pass


def call_with_retry(
    fn,
    *,
    site: str,
    policy: RetryPolicy = DEFAULT_POLICY,
    seed: int | None = None,
    on_retry=None,
):
    """Invoke ``fn()``; retry transient failures per ``policy``.

    ``site`` names the call site for logs/metrics and seeds the jitter
    stream (override with ``seed``); distinct sites decorrelate, the
    same site replays the same schedule. Non-retryable exceptions
    propagate untouched on the first attempt. ``on_retry(attempt, exc)``
    fires before each backoff sleep — callers hook their own counters
    (the serve queue's ``dispatch_retries``) without re-implementing
    the loop.
    """
    # The jitter rng is built lazily on the FIRST failure: the happy
    # path must stay allocation-free (serve batches and fit dispatches
    # run through here per call). Determinism is unchanged — the stream
    # is keyed by site/seed alone, not by when it is constructed.
    rng = None
    retried = False
    for attempt in range(1, policy.max_attempts + 1):
        try:
            result = fn()
        except BaseException as exc:
            retryable = isinstance(exc, policy.retry_on) or (
                policy.classify is not None
                and isinstance(exc, Exception)
                and policy.classify(exc)
            )
            if not retryable:
                raise
            _record("retries" if attempt < policy.max_attempts
                    else "exhausted")
            _metric("retry_attempts_total", site)
            _instant(
                "retry.attempt", site=site, attempt=attempt,
                error=type(exc).__name__,
            )
            if attempt >= policy.max_attempts:
                _metric("retry_exhausted_total", site)
                _instant("retry.exhausted", site=site, attempt=attempt)
                logger.warning(
                    "%s: transient failure persisted through %d "
                    "attempt(s): %r", site, attempt, exc)
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            if rng is None:
                rng = np.random.default_rng(
                    zlib.crc32(site.encode("utf-8"))
                    if seed is None else seed
                )
            delay = policy.delay_for(attempt, rng)
            _record("backoff_seconds", delay)
            _metric("retry_backoff_seconds_total", site, delay)
            logger.info(
                "%s: transient failure (attempt %d/%d), retrying in "
                "%.3fs: %r", site, attempt, policy.max_attempts, delay,
                exc)
            time.sleep(delay)
            retried = True
            continue
        if retried:
            _record("recovered")
            _metric("retry_recovered_total", site)
        return result


def retrying_check(point: str, fn, *, site: str | None = None,
                   policy: RetryPolicy = DEFAULT_POLICY, on_retry=None):
    """``call_with_retry`` with the fault-injection hook for ``point``
    INSIDE the retried thunk — the standard wrapper shape for the
    compile/transfer/dispatch sites, so an injected transient fault is
    recovered by the same retry loop a real one would be."""

    def once():
        faults.check(point)
        return fn()

    return call_with_retry(
        once, site=site or point, policy=policy, on_retry=on_retry
    )
