"""photon_tpu.resilience — fault tolerance for the TPU-native runtime.

Photon-ML inherited fault tolerance from Spark: RDD lineage replays
lost partitions and a restarted driver resumes the job (PAPER.md §0).
This rebuild runs on hosts that get preempted, links that flake, and
traffic that overloads — so resilience is its own layer:

- **Typed errors** (``resilience/errors.py``): the taxonomy everything
  else dispatches on — ``TransientError`` (retryable) vs
  ``PoisonError`` (never retry), plus corrupt-artifact, deadline,
  overload, circuit-breaker, and shutdown errors.
- **Deterministic fault injection** (``resilience/faults.py``): named
  injection points at the existing boundaries (ingest plan/chunk
  thunks, AOT compile, device transfer, fused fit dispatch, serve
  queue dispatch, checkpoint write, CD iteration), armed by a seeded
  ``FaultPlan`` — every chaos test replays exactly, including on the
  2-core CI box. Disarmed, each hook is one global read.
- **Retry** (``resilience/retry.py``): capped exponential backoff +
  seeded jitter around the compile/transfer/dispatch sites; only
  transient errors retry; ``retry_*`` obs metrics + an always-on
  stats dict that stays ALL ZERO on a clean run.
- **Crash-safe checkpoints** (``resilience/checkpoint.py``): after
  each outer CD iteration the estimator commits an atomic
  (tmp + fsync + rename) model npz plus a manifest (schema version,
  config/iteration cursor, config static key, content hash);
  ``photon train --resume DIR`` restarts mid-descent and converges to
  the uninterrupted run's model, and rejects resumption under a
  changed configuration via the static key.

Serving degradation (deadlines, shedding, the dispatch circuit
breaker, ``health()``) lives with the queue it protects in
``serve/queue.py``; the typed errors it raises live here.

Format, injection-point table, retry policy, and degradation knobs:
RESILIENCE.md.
"""

from __future__ import annotations

from photon_tpu.resilience import faults
from photon_tpu.resilience.checkpoint import (
    TrainingCheckpoint,
    TrainingCheckpointer,
    has_config_final,
    load_config_best,
    load_config_final,
    load_training_checkpoint,
    training_static_key,
)
from photon_tpu.resilience.errors import (
    CheckpointError,
    CircuitOpenError,
    CorruptModelError,
    CorruptShardError,
    DeadlineExceededError,
    InjectedCrash,
    NonFiniteUpdateError,
    OverloadedError,
    PoisonError,
    ResumeMismatchError,
    ShutdownError,
    TrainingInterrupted,
    TransientError,
    is_transient,
)
from photon_tpu.resilience.faults import FaultPlan, FaultSpec
from photon_tpu.resilience.retry import (
    RetryPolicy,
    call_with_retry,
    reset_retry_stats,
    retry_stats,
    retrying_check,
)

# Program contract (audited by `python -m photon_tpu.analysis --semantic`;
# machinery in analysis/program.py build_resilience): the retry wrapper
# and the fault-injection hooks are HOST machinery only. Wrapping a
# dispatch site in `call_with_retry` — or arming a full-coverage
# FaultPlan — must leave every traced program byte-identical: zero added
# programs (census bound = the one probe program), identical recompile
# keys under retry_wrap / fault_plan_armed, and no callback primitive
# smuggled into a hot-loop jaxpr.
PROGRAM_AUDIT = dict(
    name="resilience-retry",
    entry="resilience.retry.call_with_retry / resilience.faults.check "
    "around an AOT score dispatch (host-level only)",
    builder="build_resilience",
    max_programs=1,
    stable_under=("retry_wrap", "fault_plan_armed"),
    hot_loop=True,
)

__all__ = [
    "CheckpointError",
    "CircuitOpenError",
    "CorruptModelError",
    "CorruptShardError",
    "DeadlineExceededError",
    "FaultPlan",
    "FaultSpec",
    "InjectedCrash",
    "NonFiniteUpdateError",
    "OverloadedError",
    "PROGRAM_AUDIT",
    "PoisonError",
    "ResumeMismatchError",
    "RetryPolicy",
    "ShutdownError",
    "TrainingCheckpoint",
    "TrainingCheckpointer",
    "TrainingInterrupted",
    "TransientError",
    "call_with_retry",
    "faults",
    "has_config_final",
    "is_transient",
    "load_config_best",
    "load_config_final",
    "load_training_checkpoint",
    "reset_retry_stats",
    "retry_stats",
    "retrying_check",
    "training_static_key",
]
