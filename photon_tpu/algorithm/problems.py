"""Optimization problems: config dataclasses + solve/variance orchestration.

TPU-native counterpart of:
- ``GLMOptimizationConfiguration`` + coordinate optimization configs
  (photon-api optimization/game/CoordinateOptimizationConfiguration.scala:113,
  GLMOptimizationConfiguration.scala),
- ``GeneralizedLinearOptimizationProblem`` / ``DistributedOptimizationProblem``
  (optimization/GeneralizedLinearOptimizationProblem.scala:146,
  optimization/DistributedOptimizationProblem.scala:46): zero-model init,
  warm-start lambda updates, SIMPLE (inverse Hessian diagonal) and FULL
  (inverse-Hessian diagonal via Cholesky) coefficient variances (:86-103),
  and the transformed-space-optimize / original-space-report normalization
  round trip (:124-132).

``VarianceComputationType`` mirrors optimization/VarianceComputationType.scala.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp

from photon_tpu import optim
from photon_tpu.data.dataset import GLMBatch
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.ops import glm as glm_ops
from photon_tpu.ops import losses as losses_mod
from photon_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_tpu.types import TaskType

Array = jax.Array


class VarianceComputationType(enum.Enum):
    NONE = "NONE"
    SIMPLE = "SIMPLE"
    FULL = "FULL"


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """Optimizer + regularization + lambda for one coordinate.

    Reference: GLMOptimizationConfiguration (optimizerConfig,
    regularizationContext, regularizationWeight); FixedEffect adds
    ``down_sampling_rate`` (FixedEffectOptimizationConfiguration).
    """

    optimizer: optim.OptimizerConfig = dataclasses.field(
        default_factory=optim.OptimizerConfig)
    regularization: optim.RegularizationContext = dataclasses.field(
        default_factory=optim.RegularizationContext)
    regularization_weight: float = 0.0
    down_sampling_rate: float = 1.0
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    # Hyperparameter-tuning search ranges (CoordinateOptimizationConfiguration
    # .scala:40-41 regularizationWeightRange / elasticNetParamRange); None
    # means the tuner's defaults apply.
    regularization_weight_range: tuple[float, float] | None = None
    elastic_net_param_range: tuple[float, float] | None = None
    # Incremental training: importance of the Gaussian prior built from the
    # previous model (GLMOptimizationConfiguration incrementalWeight,
    # DistributedGLMLossFunction.scala:190-192; default 1.0).
    incremental_weight: float = 1.0

    def with_regularization_weight(self, weight: float) -> "GLMOptimizationConfiguration":
        """Warm-start lambda update
        (DistributedOptimizationProblem.updateRegularizationWeight :64)."""
        return dataclasses.replace(self, regularization_weight=weight)

    @property
    def l1_weight(self) -> float:
        return self.regularization.l1_weight(self.regularization_weight)

    @property
    def l2_weight(self) -> float:
        return self.regularization.l2_weight(self.regularization_weight)


@dataclasses.dataclass(frozen=True)
class GLMSolution:
    """run() output: model in ORIGINAL feature space + solver diagnostics."""

    model: GeneralizedLinearModel
    result: optim.OptResult


def variances_in_transformed_space(
    batch: GLMBatch,
    loss: losses_mod.PointwiseLoss,
    coef_transformed: Array,
    norm: NormalizationContext,
    l2_diag: Array,
    variance_computation: VarianceComputationType,
) -> Array:
    """Transformed-space coefficient variances at the optimum.

    Shared core of the fixed-effect and (vmapped) random-effect variance
    paths. Reference semantics (DistributedOptimizationProblem.scala:86-103):
    - SIMPLE: element-wise inverse of the Hessian diagonal;
    - FULL:   diagonal of the inverse Hessian via Cholesky
              (util/Linalg.scala choleskyInverse).
    ``l2_diag`` is the per-coefficient L2 diagonal (0 at the intercept and at
    padded subspace slots). Slots with zero curvature — no data support and
    no L2 — get infinite variance instead of poisoning the Cholesky.
    """
    if variance_computation == VarianceComputationType.SIMPLE:
        diag = glm_ops.hessian_diagonal(batch, loss, coef_transformed, norm)
        diag = diag + l2_diag
        return 1.0 / jnp.where(diag == 0.0, jnp.inf, diag)

    h = glm_ops.hessian_matrix(batch, loss, coef_transformed, norm)
    h = h + jnp.diag(l2_diag)
    # Zero-curvature slots would make H singular; pin their diagonal to 1 and
    # report infinite variance for them.
    dead = jnp.diagonal(h) == 0.0
    h = h + jnp.diag(jnp.where(dead, 1.0, 0.0))
    d = coef_transformed.shape[-1]
    chol = jnp.linalg.cholesky(h)
    inv = jax.scipy.linalg.cho_solve((chol, True), jnp.eye(d, dtype=h.dtype))
    return jnp.where(dead, jnp.inf, jnp.diagonal(inv))


def compute_variances(
    batch: GLMBatch,
    loss: losses_mod.PointwiseLoss,
    coef_transformed: Array,
    norm: NormalizationContext,
    l2_weight: float,
    intercept_index: int | None,
    variance_computation: VarianceComputationType,
) -> Array | None:
    """Coefficient variances at the optimum, reported in original space.

    The L2 term contributes l2 to every non-intercept diagonal entry.
    Variances are computed in the optimization (transformed) space and mapped
    back with Var(w_j) = Var(w'_j) * factor_j^2 (the inverse of
    NormalizationContext.varToTransformedSpace).
    """
    if variance_computation == VarianceComputationType.NONE:
        return None
    d = coef_transformed.shape[-1]
    l2_diag = jnp.full((d,), l2_weight, dtype=coef_transformed.dtype)
    if intercept_index is not None:
        l2_diag = l2_diag.at[intercept_index].set(0.0)

    var_t = variances_in_transformed_space(
        batch, loss, coef_transformed, norm, l2_diag, variance_computation
    )
    if norm.factors is not None:
        var_t = var_t * norm.factors * norm.factors
    return var_t


@dataclasses.dataclass(frozen=True)
class GLMOptimizationProblem:
    """One GLM fit: objective assembly, transformed-space solve, round trip.

    Serves as both the reference's DistributedOptimizationProblem (fixed
    effect: ``batch`` sharded over the mesh) and, under vmap, its
    SingleNodeOptimizationProblem (per-entity: ``batch`` is one entity's padded
    block).
    """

    task: TaskType
    config: GLMOptimizationConfiguration
    normalization: NormalizationContext = dataclasses.field(
        default_factory=no_normalization)
    intercept_index: int | None = None
    # Incremental-training Gaussian prior (previous model's means/variances
    # in original space); replaces the plain L2 penalty when set
    # (DistributedGLMLossFunction.scala:184-193).
    prior: Coefficients | None = None

    @property
    def loss(self) -> losses_mod.PointwiseLoss:
        return losses_mod.get_loss(self.task)

    def initial_coefficients(self, dim: int, dtype=jnp.float32) -> Coefficients:
        """Zero model init (GeneralizedLinearOptimizationProblem
        initializeZeroModel)."""
        return Coefficients.zeros(dim, dtype=dtype)

    def run(
        self,
        batch: GLMBatch,
        initial: Coefficients | None = None,
    ) -> GLMSolution:
        """Fit on ``batch``; returns the model in original feature space.

        Matches Optimizer.optimize + DistributedOptimizationProblem.run: the
        initial (original-space) coefficients are mapped to transformed space,
        the solver runs there against the raw data via effective coefficients,
        and means/variances are mapped back.

        The whole solve runs under ONE cached ``jax.jit`` with the l1/l2
        weights as *traced* scalars, so coordinate-descent iterations, the
        warm-start lambda ladder, and hyperparameter tuning all reuse one
        compiled program per (shapes, optimizer config) — the reference pays
        a broadcast + treeAggregate per iteration instead
        (ValueAndGradientAggregator.scala:299-320).
        """
        d = batch.num_features
        dtype = batch.labels.dtype
        w0_orig = (initial.means if initial is not None
                   else jnp.zeros(d, dtype=dtype))

        cfg = self.config
        use_owlqn = cfg.l1_weight != 0.0
        prior = None
        if self.prior is not None:
            if self.prior.variances is None:
                raise ValueError(
                    "incremental training requires prior variances "
                    "(GameEstimator.scala:241-382 invariants)")
            # padded_to covers column-sharded solves: pad-slot variance 0 is
            # the "absent from prior" marker (inverse_prior_variances).
            p = self.prior.padded_to(d)
            prior = (
                jnp.asarray(p.means, dtype=dtype),
                jnp.asarray(p.variances, dtype=dtype),
            )
        # Box-constraint arrays make the optimizer config unhashable; that
        # rare path runs untraced (the constraints become trace constants).
        run = _run_jit if cfg.optimizer.box_constraints is None else _run_impl
        means, variances, result = run(
            batch,
            jnp.asarray(w0_orig, dtype=dtype),
            jnp.asarray(cfg.l1_weight, dtype=dtype),
            jnp.asarray(cfg.l2_weight, dtype=dtype),
            self.normalization,
            prior,
            jnp.asarray(cfg.incremental_weight, dtype=dtype),
            task=self.task,
            opt_config=cfg.optimizer,
            use_owlqn=use_owlqn,
            intercept_index=self.intercept_index,
            variance_computation=cfg.variance_computation,
        )
        model = GeneralizedLinearModel(
            Coefficients(means=means, variances=variances), self.task)
        return GLMSolution(model=model, result=result)


def _run_impl(
    batch: GLMBatch,
    w0_orig: Array,
    l1_weight: Array,
    l2_weight: Array,
    norm: NormalizationContext,
    prior: tuple[Array, Array] | None,
    incremental_weight: Array,
    *,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    use_owlqn: bool,
    intercept_index: int | None,
    variance_computation: VarianceComputationType,
):
    """One fused program: transform -> solve -> variances -> round trip.

    Regularization weights are traced operands: a new lambda re-runs the
    cached executable instead of recompiling (the warm-start ladder of
    DistributedOptimizationProblem.updateRegularizationWeight :64 and the
    tuner's retrains hit the same trace). Solver routing is static: OWL-QN
    whenever the config carries an L1 part (OptimizerFactory semantics).
    """
    loss = losses_mod.get_loss(task)
    w0 = norm.coef_to_transformed_space(w0_orig)
    fun = glm_ops.make_value_and_grad(batch, loss, norm)

    if prior is not None:
        # Gaussian prior REPLACES the plain L2 term; the L2 weight survives
        # as the inverse-variance fallback for features absent from the
        # prior model (PriorDistribution.scala:31-60, normalizePrior :49).
        prior_means_t = norm.coef_to_transformed_space(prior[0])
        inv_prior_var_t = optim.inverse_prior_variances(
            norm.var_to_transformed_space(prior[1]), l2_weight
        )
        obj = optim.with_gaussian_prior(
            fun, incremental_weight, prior_means_t, inv_prior_var_t
        )
    else:
        obj = optim.with_l2(fun, l2_weight, intercept_index)

    if use_owlqn:
        result = optim.owlqn_solve(obj, w0, l1_weight, opt_config)
    elif opt_config.optimizer_type == optim.OptimizerType.TRON:
        raw_hvp = glm_ops.make_hvp(batch, loss, norm)
        if prior is not None:
            hvp = optim.with_gaussian_prior_hvp(
                raw_hvp, incremental_weight, inv_prior_var_t
            )
        else:
            hvp = optim.with_l2_hvp(raw_hvp, l2_weight, intercept_index)
        result = optim.tron_solve(obj, hvp, w0, opt_config)
    else:
        result = optim.lbfgs_solve(obj, w0, opt_config)

    if variance_computation == VarianceComputationType.NONE:
        variances = None
    else:
        d = w0_orig.shape[-1]
        if prior is not None:
            # The prior contributes iw/var to every diagonal entry
            # (PriorDistributionTwiceDiff.l2RegHessianDiagonal).
            l2_diag = incremental_weight * inv_prior_var_t
        else:
            l2_diag = jnp.full((d,), l2_weight, dtype=w0_orig.dtype)
            if intercept_index is not None:
                l2_diag = l2_diag.at[intercept_index].set(0.0)
        variances = variances_in_transformed_space(
            batch, loss, result.coefficients, norm, l2_diag,
            variance_computation,
        )
        if norm.factors is not None:
            variances = variances * norm.factors * norm.factors
    means = norm.coef_to_original_space(result.coefficients)
    return means, variances, result



_run_jit = functools.partial(
    jax.jit,
    static_argnames=(
        "task", "opt_config", "use_owlqn", "intercept_index",
        "variance_computation",
    ),
)(_run_impl)
