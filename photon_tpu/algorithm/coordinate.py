"""Coordinates: the trainable/scorable units of a GAME model.

TPU-native counterpart of photon-lib algorithm/Coordinate.scala:28 (train
with optional warm start / residual offsets, score) and photon-api
algorithm/FixedEffectCoordinate.scala:33. The random-effect coordinate lives
in ``random_effect.py``; score-only (locked) coordinates are
``ModelCoordinate`` equivalents.

A coordinate's ``score`` returns the pure model contribution per row — the
CoordinateDataScores used as residual offsets by coordinate descent
(FixedEffectCoordinate.score :144-154 computes coefficient dot features with
no offset added).
"""

from __future__ import annotations

import dataclasses
from typing import Protocol

import jax
import jax.numpy as jnp

from photon_tpu.algorithm.problems import (
    GLMOptimizationConfiguration,
    GLMOptimizationProblem,
)
from photon_tpu.data.dataset import GLMBatch
from photon_tpu.data.sampling import downsample
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.types import TaskType

Array = jax.Array


class Coordinate(Protocol):
    """Reference: algorithm/Coordinate.scala:28."""

    def train(
        self,
        residuals: Array | None = None,
        initial_model=None,
        *,
        seed: int = 0,
    ):
        """Fit against base offsets + residual scores; returns
        (model, diagnostics)."""

    def score(self, model) -> Array:
        """Model contribution per row of the canonical table."""


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinate:
    """Global GLM coordinate over one feature shard.

    ``batch.offsets`` are the dataset's base offsets; residual scores from
    other coordinates are added per train call (Coordinate.scala:52-53).
    Optional negative down-sampling applies per train call with a fresh
    seeded key (FixedEffectCoordinate.trainModel →
    DistributedOptimizationProblem.runWithSampling :141-167).
    """

    batch: GLMBatch
    problem: GLMOptimizationProblem
    # Canonical row count when ``batch`` carries weight-0 padding rows for
    # even device sharding (parallel/mesh.py shard_batch): residual vectors
    # arrive at the canonical length and scores must return at it, so the
    # coordinate-descent bookkeeping never sees the padding.
    logical_rows: int | None = None

    @property
    def config(self) -> GLMOptimizationConfiguration:
        return self.problem.config

    def train(
        self,
        residuals: Array | None = None,
        initial_model: GeneralizedLinearModel | None = None,
        *,
        seed: int = 0,
    ):
        batch = self.batch
        if residuals is not None:
            pad = batch.num_samples - residuals.shape[0]
            if pad:
                residuals = jnp.pad(residuals, (0, pad))
            batch = batch.with_offsets(batch.offsets + residuals)
        rate = self.config.down_sampling_rate
        if 0.0 < rate < 1.0:
            binary = self.problem.task in (
                TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            )
            batch = downsample(
                batch, rate, jax.random.key(seed), binary=binary)
        initial = initial_model.coefficients if initial_model is not None else None
        if initial is not None:
            # Column-sharded features solve in a device-count-padded
            # coefficient space; externally visible models stay at the
            # logical feature count (see the trim below).
            initial = initial.padded_to(batch.num_features)
        solution = self.problem.run(batch, initial)
        model = solution.model
        logical_d = getattr(batch.features, "logical_d", None)
        if logical_d is not None and logical_d != batch.num_features:
            coefs = model.coefficients
            model = dataclasses.replace(
                model,
                coefficients=dataclasses.replace(
                    coefs,
                    means=coefs.means[:logical_d],
                    variances=(
                        None if coefs.variances is None
                        else coefs.variances[:logical_d]
                    ),
                ),
            )
        return model, solution.result

    def score(self, model: GeneralizedLinearModel) -> Array:
        s = model.coefficients.compute_score(self.batch.features)
        if self.logical_rows is not None and s.shape[0] != self.logical_rows:
            s = s[: self.logical_rows]
        return s


@dataclasses.dataclass(frozen=True)
class ModelCoordinate:
    """Score-only coordinate for locked (partial-retrain) models.

    Reference: algorithm/ModelCoordinate.scala:64,
    FixedEffectModelCoordinate.scala:44.
    """

    inner: Coordinate
    model: GeneralizedLinearModel

    def train(self, residuals=None, initial_model=None, *, seed: int = 0):
        raise RuntimeError(
            "locked coordinate cannot be retrained "
            "(partialRetrainLockedCoordinates)")

    def score(self, model=None) -> Array:
        return self.inner.score(self.model if model is None else model)
