"""CoordinateDescent: the GAME outer loop with residual-score bookkeeping.

TPU-native counterpart of photon-lib algorithm/CoordinateDescent.scala:43.
The reference's loop (run :132, descend :373, descendWithValidation :493,
descendSingleCoordinate :653) alternates coordinate updates, each training
against the *residual* scores of all other coordinates, with RDD
persist/unpersist choreography around score updates
(``summedScores - oldScores + previousScores``, :442,583). Here every
coordinate's scores are one ``[n]`` device array aligned with the canonical
row order, so the bookkeeping is three vector adds and the choreography
disappears.

Locked coordinates (partial retraining, partialRetrainLockedCoordinates
:47,55) contribute scores but are never retrained. Validation evaluation runs
after every coordinate update (:312-333) and the best full GAME model by the
primary evaluator is tracked across all updates.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.evaluation.suite import EvaluationResults, EvaluationSuite
from photon_tpu.models.game import GameModel

Array = jax.Array
logger = logging.getLogger(__name__)


def _sub_add_impl(total, old, new):
    """summedScores - oldScores + previousScores as one fused program."""
    return total - old + new


# The residual-total CARRY is donated: after `total = _sub_add(total,
# old, new)` the previous total buffer is dead, so XLA reuses its HBM
# for the result instead of round-tripping a fresh [n] allocation per
# coordinate update (the unfused CD sweep's working-set donation;
# PERFORMANCE.md donation map). The plain twin serves the one aliased
# case — a single-coordinate descent where the carry IS the stored
# score (donating a buffer that is also another operand is an XLA
# runtime error).
_sub_add_donating = jax.jit(_sub_add_impl, donate_argnums=(0,))
_sub_add_plain = jax.jit(_sub_add_impl)


def _sub_add(total, old, new):
    if total is old or total is new:
        return _sub_add_plain(total, old, new)
    return _sub_add_donating(total, old, new)


@jax.jit
def _all_finite(x):
    """One tiny reduce per operand shape (jit caches per aval)."""
    return jnp.all(jnp.isfinite(x))


def _model_weight_arrays(model) -> list:
    """The weight arrays a coordinate model carries (guard operands).

    Knows the three shapes that flow through the CD loop: shard-tagged
    FixedEffectModels (``.model`` is the GLM), RandomEffectModels
    (``.coefficients`` is the padded table), and bare GLMs (direct CD
    use in tests). Unknown types contribute nothing — the score check
    still covers them.
    """
    glm = getattr(model, "model", model)
    coefs = getattr(glm, "coefficients", None)
    if coefs is None:
        return []
    means = getattr(coefs, "means", None)
    if means is not None:
        return [means]
    return [coefs] if hasattr(coefs, "shape") else []


def _update_is_finite(model, scores) -> bool:
    """Host-side non-finite guard for one coordinate update.

    This is a DELIBERATE host sync per update — the guard exists to
    stop a poisoned iterate before it corrupts the residual total, and
    only runs when ``non_finite_guard`` is enabled (the default loop
    stays fully asynchronous).
    """
    for arr in [scores, *_model_weight_arrays(model)]:
        if not bool(_all_finite(arr)):
            return False
    return True


def _serialize_on_cpu_mesh(x) -> None:
    """Block on ``x`` when it lives on a multi-device CPU mesh.

    XLA's CPU in-process communicator can deadlock when two
    collective-bearing executions are in flight at once (their all-reduce
    rendezvous interleave across the shared device threads). TPU streams
    execute programs in dispatch order per device, so the async pipeline is
    safe on hardware — but the forced-host-device mesh (tests, the driver's
    multichip dryrun) must serialize, and one host sync per coordinate
    update is noise next to the solve it waits on.
    """
    devices = getattr(x, "devices", None)
    if devices is None:
        return
    ds = x.devices()
    if len(ds) > 1 and next(iter(ds)).platform == "cpu":
        jax.block_until_ready(x)


@dataclasses.dataclass(frozen=True)
class ValidationContext:
    """Validation data + per-coordinate scorers.

    ``scorers[k](model)`` returns coordinate k's score contribution for every
    validation row (the GameEstimator builds these from the validation
    dataset's per-coordinate feature/entity views).
    """

    suite: EvaluationSuite
    scorers: dict[str, Callable[[Any], Array]]


@dataclasses.dataclass(frozen=True)
class CoordinateUpdateRecord:
    """One coordinate update's diagnostics (OptimizationStatesTracker /
    RandomEffectOptimizationTracker equivalents plus timing).

    ``seconds`` is host DISPATCH time: training is fully asynchronous (no
    host sync per update), so device execution overlaps later updates and
    is not attributable per coordinate. End-to-end wall time lives at the
    fit / driver level, where the caller's first blocking read (evaluation,
    model save) absorbs the queued work.

    On the FUSED whole-fit path (algorithm/fused_fit.py) the entire
    descent is one device program, so not even dispatch time exists per
    coordinate. The contract there is two-valued:

    - telemetry OFF (``photon_tpu.obs`` disabled, the default):
      ``seconds`` is ``None`` — never a synthetic split consumers would
      read as measured;
    - telemetry ON: the fused fit's root span measures the fit
      program's real dispatch->completion window (one
      ``block_until_ready`` at the span root; slab materialization and
      the AOT compile wait are excluded), and ``seconds`` is that
      measurement's analytic ATTRIBUTION to this record — weighted by
      the coordinate's measured solver iteration counts x static shape
      work (``FusedFit._attribute_seconds``). Attributed shares sum to
      the measured fit window; treat them as a breakdown of one real
      measurement, not as independent per-coordinate timings. A fit
      whose window was NOT pure execution — the cold jit-fallback entry
      that traces/compiles inside the dispatch call — keeps ``None``
      (the span's ``fit_window_pure`` attr says why); only AOT-served
      and warm re-entries attribute.

    Consumers must treat ``None`` as "unattributable", not zero.
    """

    iteration: int
    coordinate_id: str
    seconds: float | None  # host dispatch time; None on the fused path
    diagnostics: Any
    evaluation: EvaluationResults | None
    # Non-finite guard outcome: True when this update produced NaN/inf
    # loss or weights and the loop kept the PREVIOUS iterate instead
    # (the diagnostics are the poisoned update's, for debugging).
    rolled_back: bool = False


@dataclasses.dataclass(frozen=True)
class CoordinateDescentResult:
    model: GameModel  # final models after the last iteration
    best_model: GameModel  # best by validation primary metric (== model if no validation)
    best_evaluation: EvaluationResults | None
    history: tuple[CoordinateUpdateRecord, ...]


class CoordinateDescent:
    """Reference: algorithm/CoordinateDescent.scala:43.

    ``update_sequence`` lists coordinate ids in update order; ids in
    ``locked_coordinates`` must come with a model in ``initial_models`` and
    are score-only.
    """

    def __init__(
        self,
        update_sequence: list[str],
        num_iterations: int,
        *,
        locked_coordinates: set[str] | None = None,
        emitter=None,
        non_finite_guard: bool = False,
    ):
        # Optional event fan-out (photon_tpu.events.EventEmitter): a
        # CoordinateUpdateEvent after every coordinate update
        # (EventEmitter.scala:24 semantics, wired to the GAME path).
        self.emitter = emitter
        # Resilience: when enabled, every coordinate update is checked
        # for non-finite loss/weights/scores (one host sync per update)
        # and a poisoned update ROLLS BACK to the previous iterate
        # instead of corrupting the model (resilience layer;
        # RESILIENCE.md). Off by default: the asynchronous dispatch
        # pipeline is the performance contract of this loop.
        self.non_finite_guard = bool(non_finite_guard)
        if num_iterations < 1:
            raise ValueError(f"num_iterations must be >= 1: {num_iterations}")
        seen = set()
        for cid in update_sequence:
            if cid in seen:
                raise ValueError(f"duplicate coordinate id {cid!r}")
            seen.add(cid)
        self.update_sequence = list(update_sequence)
        self.num_iterations = num_iterations
        self.locked_coordinates = set(locked_coordinates or ())
        unlocked = [c for c in update_sequence if c not in self.locked_coordinates]
        if not unlocked:
            raise ValueError(
                "update sequence contains no trainable coordinates "
                "(CoordinateDescent.scala:71 checkInvariants)"
            )

    def run(
        self,
        coordinates: dict[str, Coordinate],
        initial_models: dict[str, Any] | None = None,
        validation: ValidationContext | None = None,
        *,
        seed: int = 0,
        start_iteration: int = 0,
        on_iteration=None,
        initial_best=None,
    ) -> CoordinateDescentResult:
        """Train all coordinates by block coordinate descent.

        Mirrors CoordinateDescent.descend/descendWithValidation: coordinate k
        trains against offsets + (sum of all other coordinates' scores); its
        new scores replace its old ones in the running total.

        ``start_iteration`` resumes mid-descent from a checkpoint:
        iterations [0, start_iteration) are assumed done and baked into
        ``initial_models`` — the loop runs [start_iteration,
        num_iterations) with the SAME per-iteration seeds the
        uninterrupted run would have used. ``initial_best`` — a
        ``(model, evaluation)`` pair — seeds the best-by-validation
        tracking on resume: without it a resumed run restarts best
        selection from scratch and can silently return a worse model
        than the uninterrupted run when the pre-crash best never
        recurs. ``on_iteration(it, model, best_model)`` fires after
        each completed outer iteration with the full GameModel and the
        best-so-far (None until a full model has been evaluated) — the
        training checkpointer's hook.
        """
        if not 0 <= start_iteration <= self.num_iterations:
            raise ValueError(
                f"start_iteration {start_iteration} outside "
                f"[0, {self.num_iterations}]")
        for cid in self.update_sequence:
            if cid not in coordinates:
                raise KeyError(f"no coordinate for id {cid!r}")
        initial_models = dict(initial_models or {})
        for cid in self.locked_coordinates:
            if cid not in initial_models:
                raise ValueError(
                    f"locked coordinate {cid!r} needs an initial model "
                    "(partialRetrainLockedCoordinates invariant)"
                )

        models: dict[str, Any] = {}
        scores: dict[str, Array] = {}
        total: Array | None = None

        def add(total_, s):
            return s if total_ is None else total_ + s

        # Initial scores from warm-start / locked models
        # (CoordinateDescent.run computes initial model scores up front).
        for cid in self.update_sequence:
            if cid in initial_models:
                models[cid] = initial_models[cid]
                s = coordinates[cid].score(models[cid])
                _serialize_on_cpu_mesh(s)
                scores[cid] = s
                total = add(total, s)

        history: list[CoordinateUpdateRecord] = []
        best_model: GameModel | None = None
        best_eval: EvaluationResults | None = None
        if initial_best is not None:
            best_model, best_eval = initial_best
        all_ids = set(self.update_sequence)
        val_scores: dict[str, Array] = {}
        val_total: Array | None = None

        from photon_tpu import obs

        for it in range(start_iteration, self.num_iterations):
            for cid in self.update_sequence:
                if cid in self.locked_coordinates:
                    continue
                coord = coordinates[cid]
                t0 = time.perf_counter()
                rolled_back = False
                # Telemetry span mirrors the measured dispatch window
                # below (host-side only; the obs tree's unfused analog of
                # the fused fit's single whole-fit span — no sync here:
                # per-update syncs are exactly what this loop avoids).
                with obs.span(f"coord:{cid}", attrs={"iteration": it}):
                    residuals = None
                    if total is not None:
                        residuals = total
                        if cid in scores:
                            residuals = residuals - scores[cid]
                    model, diag = coord.train(
                        residuals=residuals,
                        initial_model=models.get(cid),
                        seed=seed + it,
                    )
                    new_scores = coord.score(model)
                    _serialize_on_cpu_mesh(new_scores)
                    # Non-finite guard (resilience): catch a poisoned
                    # update BEFORE it enters the residual total. The
                    # rollback keeps the previous iterate for this
                    # coordinate; total/scores stay untouched, so every
                    # later update trains against the last good state.
                    if self.non_finite_guard and not _update_is_finite(
                        model, new_scores
                    ):
                        if cid not in models:
                            from photon_tpu.resilience.errors import (
                                NonFiniteUpdateError,
                            )

                            raise NonFiniteUpdateError(
                                f"coordinate {cid!r} produced non-finite "
                                f"loss/weights on its first update (CD "
                                f"iteration {it}): no previous iterate "
                                "to roll back to")
                        rolled_back = True
                    elif total is None:
                        # summedScores - oldScores + previousScores
                        # (:442,583). One jitted program: each eager
                        # arithmetic op costs a ~0.5s one-off compile on
                        # the tunneled TPU backend.
                        total = new_scores
                    elif cid in scores:
                        total = _sub_add(total, scores[cid], new_scores)
                    else:
                        total = total + new_scores  # photon: ignore[use-after-donate] -- line 354 re-binds `total` to the donating call's result in the same statement, so this branch (a later coordinate's first appearance) reads the NEW buffer; the carry-aliased case routes through the plain twin via _sub_add's identity guard
                if rolled_back:
                    logger.warning(
                        "CD iter %d coordinate %s: non-finite update "
                        "ROLLED BACK to the previous iterate", it, cid)
                    if obs.enabled():
                        obs.REGISTRY.counter(
                            "coordinate_rollbacks_total", coordinate=cid
                        ).inc()
                        from photon_tpu.obs import trace as obs_trace

                        obs_trace.instant(
                            "cd.rollback", cat="resilience",
                            coordinate=cid, iteration=it,
                        )
                    record = CoordinateUpdateRecord(
                        iteration=it,
                        coordinate_id=cid,
                        seconds=time.perf_counter() - t0,
                        diagnostics=diag,
                        evaluation=None,
                        rolled_back=True,
                    )
                    history.append(record)
                    if self.emitter is not None:
                        from photon_tpu.events import (
                            CoordinateRollbackEvent,
                        )

                        self.emitter.send_event(
                            CoordinateRollbackEvent(record)
                        )
                    continue
                models[cid] = model
                scores[cid] = new_scores
                seconds = time.perf_counter() - t0

                evaluation = None
                if validation is not None:
                    # Incremental validation total: only the updated
                    # coordinate is rescored (same - old + new pattern as
                    # the training-side residual bookkeeping). Locked /
                    # warm-start models enter on their first appearance.
                    for vid, m in models.items():
                        if vid == cid or vid not in val_scores:
                            vs = validation.scorers[vid](m)
                            if val_total is None:
                                val_total = vs
                            else:
                                old = val_scores.get(vid)
                                val_total = (
                                    val_total + vs if old is None
                                    else _sub_add(val_total, old, vs)
                                )
                            val_scores[vid] = vs
                    evaluation = validation.suite.evaluate(val_total)  # photon: ignore[use-after-donate] -- the ternary above re-binds `val_total` to the donating call's result before this read, and a carry aliased with an operand dispatches through _sub_add's non-donating plain twin
                    primary = validation.suite.primary
                    # Only a FULL model (every coordinate trained or seeded)
                    # is eligible for best-model selection; partial models
                    # from the first sweep would silently drop coordinates.
                    if set(models) == all_ids and (
                        best_eval is None
                        or primary.better_than(
                            evaluation.primary_evaluation,
                            best_eval.primary_evaluation,
                        )
                    ):
                        best_eval = evaluation
                        best_model = GameModel(dict(models))
                    logger.info(
                        "CD iter %d coordinate %s: %s (%.2fs)",
                        it, cid, evaluation.evaluations, seconds,
                    )
                else:
                    logger.info(
                        "CD iter %d coordinate %s dispatched (%.2fs)",
                        it, cid, seconds,
                    )
                record = CoordinateUpdateRecord(
                    iteration=it,
                    coordinate_id=cid,
                    seconds=seconds,
                    diagnostics=diag,
                    evaluation=evaluation,
                )
                history.append(record)
                if self.emitter is not None:
                    from photon_tpu.events import CoordinateUpdateEvent

                    self.emitter.send_event(CoordinateUpdateEvent(record))
            # End of one OUTER iteration: the crash-safe recovery point.
            # The checkpointer hook runs first (state committed), then
            # the `cd.iteration` injection point — so an injected crash
            # here simulates dying with iteration `it`'s checkpoint
            # already durable, the kill-and-resume chaos window.
            if on_iteration is not None:
                on_iteration(it, GameModel(dict(models)), best_model)
            from photon_tpu.resilience import faults

            faults.check("cd.iteration")

        final = GameModel(dict(models))
        if best_model is None:
            best_model = final
        return CoordinateDescentResult(
            model=final,
            best_model=best_model,
            best_evaluation=best_eval,
            history=tuple(history),
        )
