"""RandomEffectCoordinate: batched vmapped per-entity GLM solves.

TPU-native counterpart of photon-api algorithm/RandomEffectCoordinate.scala:38
and optimization/game/RandomEffectOptimizationProblem.scala:45. The
reference's design — join activeData with per-entity
SingleNodeOptimizationProblems and run a *local* Breeze optimizer per entity
inside ``mapValues`` (:243-292) — becomes: for each size bucket of entities,
ONE jitted ``vmap`` of the full L-BFGS/OWL-QN/TRON while_loop over the entity
axis. JAX's while_loop batching rule gives masked per-entity convergence for
free (converged entities stop changing), the analog of heterogeneous
convergence across executor-local solves (SURVEY §7.3).

Per-entity projected normalization contexts
(RandomEffectOptimizationProblem.scala:137-198) are gathers of the global
factor/shift vectors through the entity's projector; the per-entity intercept
slot is a traced index, so coefficient space round-trips use one-hot masks
instead of static-index updates.

Scoring covers active AND passive rows uniformly via the dataset's remapped
scoring table (scoreActiveData :314-332 / scorePassiveData :346-366 collapse
into one gather-multiply-reduce).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu import optim
from photon_tpu.algorithm.problems import (
    GLMOptimizationConfiguration,
    VarianceComputationType,
    variances_in_transformed_space,
)
from photon_tpu.data.dataset import (
    DenseFeatures,
    GLMBatch,
    SparseFeatures,
)
from photon_tpu.data.random_effect import (
    DENSE_SUB_DIM_MAX,
    ONE_HOT_ELEMENT_BUDGET,
    BlockPlan,
    EntityBlocks,
    RandomEffectDataset,
)
from photon_tpu.models.game import RandomEffectModel
from photon_tpu.ops import glm as glm_ops
from photon_tpu.ops import losses as losses_mod
from photon_tpu.ops.normalization import NormalizationContext
from photon_tpu.types import TaskType

Array = jax.Array


class RandomEffectTrainingStats:
    """Aggregate per-entity solver diagnostics, fetched LAZILY.

    Reference: RandomEffectOptimizationTracker (optimization/
    RandomEffectOptimizationTracker.scala:89) — counts of convergence reasons
    plus iteration stats over entities.

    The diagnostic arrays live on the device until an attribute is read:
    fetching them eagerly would insert a device->host sync into every
    coordinate update of the CD loop (on a remote-attached chip that sync
    costs more than the solve itself). Training code threads this object
    into the history without touching it; summaries/tests that read it pay
    the one coalesced transfer then. Unread stats pin two [num_entities]
    int32 device buffers per update — bounded well below the [E, S]
    coefficient matrices the same history records already retain, so no
    explicit release hook is needed.
    """

    def __init__(self, reasons=None, iterations=None, *, device=None):
        # device: (reason device arrays, iteration device arrays,
        #          host keep-masks) — one pull on first attribute access.
        self._device = device
        self._host = None
        if device is None:
            self._host = (
                np.asarray(reasons) if reasons is not None
                else np.empty(0, np.int32),
                np.asarray(iterations) if iterations is not None
                else np.empty(0, np.int32),
            )

    @staticmethod
    def from_arrays(reasons: np.ndarray, iterations: np.ndarray):
        return RandomEffectTrainingStats(reasons, iterations)

    @staticmethod
    def from_device(reason_arrays, iteration_arrays, keep_masks):
        return RandomEffectTrainingStats(
            device=(reason_arrays, iteration_arrays, keep_masks)
        )

    def _materialize(self):
        if self._host is None:
            reasons_d, iters_d, keeps = self._device
            keep = np.concatenate(keeps) if keeps else np.empty(0, bool)
            # One coalesced fetch of all blocks' diagnostics.
            reasons = (
                np.asarray(jnp.concatenate(reasons_d)) if reasons_d
                else np.empty(0, np.int32)
            )
            iters = (
                np.asarray(jnp.concatenate(iters_d)) if iters_d
                else np.empty(0, np.int32)
            )
            self._host = (reasons[keep], iters[keep])
            self._device = None
        return self._host

    @property
    def convergence_reason_counts(self) -> dict[str, int]:
        reasons, _ = self._materialize()
        counts: dict[str, int] = {}
        for code, cnt in zip(*np.unique(reasons, return_counts=True)):
            counts[optim.ConvergenceReason(int(code)).name] = int(cnt)
        return counts

    @property
    def iterations_mean(self) -> float:
        _, iters = self._materialize()
        return float(iters.mean()) if iters.size else 0.0

    @property
    def iterations_max(self) -> int:
        _, iters = self._materialize()
        return int(iters.max()) if iters.size else 0

    @property
    def num_entities(self) -> int:
        _, iters = self._materialize()
        return int(iters.size)


def _onehot(slot: Array, dim: int, dtype) -> Array:
    """One-hot of a traced (possibly -1) slot index; all-zero when slot < 0."""
    iota = jnp.arange(dim)
    return jnp.where(iota == slot, 1.0, 0.0).astype(dtype)


def _coef_to_transformed(w, factors, shifts, int_onehot):
    if shifts is not None:
        w = w + jnp.dot(w, shifts) * int_onehot
    if factors is not None:
        w = w / factors
    return w


def _coef_to_original(w_t, factors, shifts, int_onehot):
    w = w_t if factors is None else w_t * factors
    if shifts is not None:
        w = w - jnp.dot(w, shifts) * int_onehot
    return w


def _features_of(
    x_indices: Array | None, x_values: Array, sub_dim: int
):
    """Per-entity feature view: dense [R, S] matrix or ELL slabs."""
    if x_indices is None:
        return DenseFeatures(x_values)
    return SparseFeatures(x_indices, x_values, sub_dim)


def _densify_ell_slots(
    x_indices: Array, x_values: Array, sub_dim: int
) -> Array:
    """[..., k] slot-ELL -> [..., S] dense via one-hot contraction (NOT
    scatter: batched scatter/gather lowers to a pathologically
    slow-compiling program on TPU; the one-hot einsum compiles in <1s and
    runs on the MXU). Duplicate slots sum, matching scatter-add."""
    onehot = (
        x_indices[..., None]
        == jnp.arange(sub_dim, dtype=x_indices.dtype)
    ).astype(x_values.dtype)
    return jnp.einsum("...k,...ks->...s", x_values, onehot)


def _solve_one_entity_direct(
    x_indices: Array | None,  # [R, k] ELL slots, or None (dense layout)
    x_values: Array,  # [R, k] or [R, S]
    labels: Array,  # [R]
    offsets: Array,  # [R]
    weights: Array,  # [R]
    penalty_mask: Array,  # [S]
    valid_mask: Array,  # [S]
    factors: Array | None,  # [S]
    shifts: Array | None,  # [S]
    intercept_slot: Array,
    prior: tuple[Array, Array] | None,
    *,
    sub_dim: int,
    variance_computation: VarianceComputationType,
    l2_weight: Array,
    incremental_weight: Array,
    task: TaskType,
):
    """Exact per-entity solve for the squared-loss case: one batched
    Cholesky instead of ~100 sequential L-BFGS device steps.

    The per-entity GLMix subproblem for squared loss is a small convex
    quadratic; its minimizer is the normal-equations solution
      (X'^T diag(wt) X' + diag(pen)) w = X'^T diag(wt) (y - offset) (+ prior)
    — identical (to machine precision) to what the reference's LBFGS/TRON
    iterates toward (SingleNodeOptimizationProblem.run), but as a single
    MXU-friendly [S, S] factorization per entity, vmapped over the bucket.
    The subspace design matrix is densified per entity (S = sub_dim is small
    by construction — LinearSubspaceProjector compression).
    """
    dtype = x_values.dtype
    if x_indices is None:
        x = x_values
    else:
        # This branch only runs for wide subspaces (_solve_block densifies
        # small ones up front): scatter-add keeps peak memory at the dense
        # [R, S] result instead of a [R, k, S] one-hot operand.
        r = x_values.shape[0]
        rows = jnp.broadcast_to(jnp.arange(r)[:, None], x_indices.shape)
        x = jnp.zeros((r, sub_dim), dtype).at[rows, x_indices].add(x_values)
    if shifts is not None:
        x = x - shifts[None, :]
    if factors is not None:
        x = x * factors[None, :]
    y_eff = (labels - offsets) * weights
    h = x.T @ (x * weights[:, None])
    b = x.T @ y_eff
    if prior is not None:
        int_onehot = (
            None if shifts is None
            else _onehot(intercept_slot, sub_dim, dtype)
        )
        m_t = _coef_to_transformed(prior[0], factors, shifts, int_onehot)
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        l2_diag = incremental_weight * inv_prior_var
        b = b + l2_diag * m_t
    else:
        l2_diag = l2_weight * penalty_mask
    h = h + jnp.diag(l2_diag + (1.0 - valid_mask))
    chol = jnp.linalg.cholesky(h)
    w_t = jax.scipy.linalg.cho_solve((chol, True), b) * valid_mask

    norm = NormalizationContext(
        factors=factors, shifts=shifts,
        intercept_index=None if shifts is None else 0,
    )
    if variance_computation != VarianceComputationType.NONE:
        loss = losses_mod.get_loss(task)
        batch = GLMBatch(
            _features_of(x_indices, x_values, sub_dim),
            labels, offsets, weights,
        )
        var_t = variances_in_transformed_space(
            batch, loss, w_t, norm, l2_diag, variance_computation,
        )
        f_sq = 1.0 if factors is None else factors * factors
        variances = jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    else:
        variances = jnp.zeros_like(w_t)

    int_onehot = (
        None if shifts is None else _onehot(intercept_slot, sub_dim, dtype)
    )
    w_orig = _coef_to_original(w_t, factors, shifts, int_onehot) * valid_mask
    return (
        w_orig,
        variances,
        jnp.asarray(1, jnp.int32),
        jnp.asarray(int(optim.ConvergenceReason.GRADIENT_CONVERGED),
                    jnp.int32),
    )


def _solve_one_entity(
    x_indices: Array | None,  # [R, k] ELL slots, or None (dense layout)
    x_values: Array,  # [R, k] or [R, S]
    labels: Array,  # [R]
    offsets: Array,  # [R]
    weights: Array,  # [R]
    penalty_mask: Array,  # [S]
    valid_mask: Array,  # [S]
    factors: Array,  # [S] (ones where no normalization)
    shifts: Array,  # [S] (zeros where none)
    intercept_slot: Array,  # scalar int32, -1 if absent
    w0_orig: Array,  # [S] original-space warm start
    prior: tuple[Array, Array] | None,  # ([S] means, [S] vars) original space
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    use_owlqn: bool,
    variance_computation: VarianceComputationType,
    l1_weight: Array,  # traced scalars, closed over (broadcast under vmap)
    l2_weight: Array,
    incremental_weight: Array,
):
    """One entity's full solve; vmapped over the bucket's entity axis.

    Mirrors SingleNodeOptimizationProblem.run (:90-98): transformed-space
    solve with the effective-coefficient rewrite, reported in original space.
    Regularization weights are traced, so a new lambda (warm-start ladder,
    tuner retrain) reuses the compiled block solve.
    """
    loss = losses_mod.get_loss(task)
    feats = _features_of(x_indices, x_values, sub_dim)
    batch = GLMBatch(feats, labels, offsets, weights)
    # Per-entity projected normalization; factors/shifts are None (static)
    # when the coordinate has no normalization, so the objective specializes
    # to the raw fast path at trace time. intercept_index is only consulted
    # by the static-index round-trip helpers, which we bypass.
    norm = NormalizationContext(
        factors=factors,
        shifts=shifts,
        intercept_index=None if shifts is None else 0,
    )
    int_onehot = (
        None if shifts is None
        else _onehot(intercept_slot, sub_dim, w0_orig.dtype)
    )

    w0 = _coef_to_transformed(w0_orig, factors, shifts, int_onehot)
    fun = glm_ops.make_value_and_grad(batch, loss, norm)
    if prior is not None:
        # Per-entity Gaussian prior (incremental training): replaces the
        # plain L2 term; the L2 weight is the fallback precision for slots
        # absent from the prior model (PriorDistribution.scala:31-60).
        # Padded slots are masked out of the penalty entirely.
        prior_means_t = _coef_to_transformed(
            prior[0], factors, shifts, int_onehot)
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        obj = optim.with_gaussian_prior(
            fun, incremental_weight, prior_means_t, inv_prior_var)
        l2_diag = incremental_weight * inv_prior_var
    else:
        obj = optim.with_l2_masked(fun, l2_weight, penalty_mask)
        l2_diag = l2_weight * penalty_mask

    if use_owlqn:
        result = optim.owlqn_solve(obj, w0, l1_weight, opt_config)
    elif opt_config.optimizer_type == optim.OptimizerType.TRON:
        hvp = glm_ops.make_hvp(batch, loss, norm)
        if prior is not None:
            obj_hvp = optim.with_gaussian_prior_hvp(
                hvp, incremental_weight, inv_prior_var)
        else:
            obj_hvp = optim.with_l2_hvp_masked(hvp, l2_weight, penalty_mask)
        result = optim.tron_solve(obj, obj_hvp, w0, opt_config)
    else:
        result = optim.lbfgs_solve(obj, w0, opt_config)

    w_t = result.coefficients * valid_mask

    if variance_computation != VarianceComputationType.NONE:
        var_t = variances_in_transformed_space(
            batch, loss, w_t, norm, l2_diag, variance_computation,
        )
        f_sq = 1.0 if factors is None else factors * factors
        # Padded slots (and zero-support slots) carry var inf; report 0 for
        # padding, inf for genuinely unsupported-but-valid slots.
        variances = jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    else:
        variances = jnp.zeros_like(w_t)

    w_orig = _coef_to_original(w_t, factors, shifts, int_onehot) * valid_mask
    return w_orig, variances, result.iterations, result.convergence_reason


@functools.partial(
    jax.jit,
    static_argnames=(
        "sub_dim", "task", "opt_config", "use_owlqn", "variance_computation",
        "direct",
    ),
)
def _solve_block(
    block,  # EntityBlocks | BlockPlan (pytree structure selects the path)
    residuals: Array | None,  # [n] canonical residual scores, or None
    factors_full: Array | None,  # [d] global normalization factors
    shifts_full: Array | None,  # [d] global normalization shifts
    w0_full: Array | None,  # [E, Smax] original-space warm starts
    l1_weight: Array,
    l2_weight: Array,
    incremental_weight: Array,
    prior_full: tuple[Array, Array] | None,  # ([E, Smax], [E, Smax]) or None
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    use_owlqn: bool,
    variance_computation: VarianceComputationType,
    direct: bool = False,
):
    """One bucket's batched per-entity solve (everything traced/fused).

    Lazy ``BlockPlan`` buckets materialize their [B, R, k] slabs here, INSIDE
    the compiled program, by gathering the HBM-resident raw arrays — the
    slabs never exist on the host (data/random_effect.py module docstring).
    Warm-start / prior / normalization gathers are also traced, so one fit
    dispatches a single device program per bucket.
    """
    if isinstance(block, BlockPlan):
        block = block.materialize(residuals)
        offsets = block.offsets
    else:
        offsets = block.offsets
        if residuals is not None:
            # Padding rows alias canonical row 0; mask their gather.
            offsets = offsets + jnp.where(
                block.weights > 0,
                jnp.take(residuals, block.row_ids, mode="clip"),
                0.0,
            )
    dtype = block.x_values.dtype
    if (
        block.x_indices is not None
        and sub_dim <= DENSE_SUB_DIM_MAX
        and int(np.prod(block.x_indices.shape)) * sub_dim
        <= ONE_HOT_ELEMENT_BUDGET
    ):
        # Densify small-subspace ELL blocks so every downstream op is a
        # matmul; batched gather/scatter both execute worse and compile
        # ~40x slower on TPU. The element budget keeps the transient
        # one-hot operand bounded; over-budget blocks stay ELL.
        block = dataclasses.replace(
            block,
            x_indices=None,
            x_values=_densify_ell_slots(
                block.x_indices, block.x_values, sub_dim
            ),
        )
    s = sub_dim
    codes = block.entity_codes
    proj = block.proj  # [B, S]; -1 pad
    safe = jnp.maximum(proj, 0)
    factors_sub = shifts_sub = None
    if factors_full is not None:
        f = jnp.take(factors_full.astype(dtype), safe, mode="clip")
        factors_sub = jnp.where(proj >= 0, f, 1.0)
    if shifts_full is not None:
        sh = jnp.take(shifts_full.astype(dtype), safe, mode="clip")
        shifts_sub = jnp.where(proj >= 0, sh, 0.0)
    if w0_full is not None:
        # Sentinel codes (mesh entity padding) clip to the last row; their
        # results are dropped by the out-of-bounds scatter on the way back.
        w0 = jnp.take(w0_full.astype(dtype), codes, axis=0, mode="clip")
        w0 = w0[:, :s]
    else:
        w0 = jnp.zeros((block.num_entities, s), dtype)
    prior = None
    if prior_full is not None:
        prior = (
            jnp.take(
                prior_full[0].astype(dtype), codes, axis=0, mode="clip"
            )[:, :s],
            jnp.take(
                prior_full[1].astype(dtype), codes, axis=0, mode="clip"
            )[:, :s],
        )
    if direct:
        def direct_solver(xi, xv, lb, off, wt, pm, vm, f, sh, islot, prior_e):
            return _solve_one_entity_direct(
                xi, xv, lb, off, wt, pm, vm, f, sh, islot, prior_e,
                sub_dim=sub_dim,
                variance_computation=variance_computation,
                l2_weight=l2_weight,
                incremental_weight=incremental_weight,
                task=task,
            )

        return jax.vmap(direct_solver)(
            block.x_indices,
            block.x_values,
            block.labels,
            offsets,
            block.weights,
            block.penalty_mask,
            block.valid_mask,
            factors_sub,
            shifts_sub,
            block.intercept_slots,
            prior,
        )

    def solver(xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e, prior_e):
        return _solve_one_entity(
            xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e, prior_e,
            sub_dim=sub_dim,
            task=task,
            opt_config=opt_config,
            use_owlqn=use_owlqn,
            variance_computation=variance_computation,
            l1_weight=l1_weight,
            l2_weight=l2_weight,
            incremental_weight=incremental_weight,
        )

    return jax.vmap(solver)(
        block.x_indices,
        block.x_values,
        block.labels,
        offsets,
        block.weights,
        block.penalty_mask,
        block.valid_mask,
        factors_sub,
        shifts_sub,
        block.intercept_slots,
        w0,
        prior,
    )


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinate:
    """Per-entity coordinate over one random-effect type.

    Reference: algorithm/RandomEffectCoordinate.scala:38 (trainModel
    :234-300, scoring :314-366).
    """

    dataset: RandomEffectDataset
    task: TaskType
    config: GLMOptimizationConfiguration
    normalization: NormalizationContext = dataclasses.field(
        default_factory=NormalizationContext
    )
    # Incremental-training prior: a RandomEffectModel (with variances)
    # already remapped onto this dataset's entity/slot layout. Entities or
    # slots absent from it carry variance 0 and fall back to plain L2
    # (RandomEffectOptimizationProblem.scala:137-198 projected priors).
    prior: RandomEffectModel | None = None

    def train(
        self,
        residuals: Array | None = None,
        initial_model: RandomEffectModel | None = None,
        *,
        seed: int = 0,
    ) -> tuple[RandomEffectModel, RandomEffectTrainingStats]:
        ds = self.dataset
        dtype = jnp.dtype(ds.dtype)
        w_all = jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
        v_all = (
            jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
            if self.config.variance_computation != VarianceComputationType.NONE
            else None
        )
        # (device reason array, host real-entity mask) per block; fetched in
        # two coalesced transfers after all blocks are dispatched.
        reasons: list[tuple[Array, np.ndarray]] = []
        iters: list[Array] = []
        real_masks = [
            ds.real_entity_mask(i) for i in range(len(ds.blocks))
        ]

        if self.normalization.shifts is not None:
            # Shift normalization folds the shift mass into the intercept on
            # the coefficient round trip; every trained entity must have one
            # (the per-entity analog of NormalizationContext.__post_init__).
            for ints, real in zip(ds.block_intercepts_np, real_masks):
                if bool((np.asarray(ints)[real] < 0).any()):
                    raise ValueError(
                        "normalization with shifts requires every entity's "
                        "subspace to contain the intercept; build the "
                        "dataset with intercept_index set"
                    )

        if self.prior is not None and self.prior.variances is None:
            raise ValueError(
                "incremental training requires prior variances for "
                "every entity model (GameEstimator.scala:241-382)")

        for block, real in zip(ds.blocks, real_masks):
            s = block.sub_dim
            # Squared-loss subproblems are convex quadratics: solve them
            # exactly with one batched Cholesky instead of iterating
            # (identical optimum, ~100x fewer sequential device steps).
            # l2 > 0 guarantees X^T W X + diag(pen) is positive definite even
            # for entities with fewer rows than active features — without it
            # the normal equations can be singular and the iterative solver's
            # implicit regularization is the correct behavior.
            direct = (
                self.task == TaskType.LINEAR_REGRESSION
                and self.config.l1_weight == 0.0
                and self.config.l2_weight > 0.0
                and self.config.optimizer.box_constraints is None
                # With a prior, absent-feature slots are penalized by
                # incremental_weight * inv_prior_var instead of l2; at
                # incremental_weight == 0 the normal equations can be
                # singular for entities with fewer rows than features.
                and (self.prior is None
                     or self.config.incremental_weight > 0.0)
            )
            w, v, it, reason = _solve_block(
                block,
                residuals,
                self.normalization.factors,
                self.normalization.shifts,
                None if initial_model is None
                else initial_model.coefficients,
                jnp.asarray(self.config.l1_weight, dtype=dtype),
                jnp.asarray(self.config.l2_weight, dtype=dtype),
                jnp.asarray(self.config.incremental_weight, dtype=dtype),
                None if self.prior is None
                else (self.prior.coefficients, self.prior.variances),
                sub_dim=s,
                task=self.task,
                opt_config=self.config.optimizer,
                use_owlqn=self.config.l1_weight != 0.0,
                variance_computation=self.config.variance_computation,
                direct=direct,
            )
            pad = ds.max_sub_dim - s
            if pad:
                w = jnp.pad(w, ((0, 0), (0, pad)))
                v = jnp.pad(v, ((0, 0), (0, pad)))
            w_all = w_all.at[block.entity_codes].set(w)
            if v_all is not None:
                v_all = v_all.at[block.entity_codes].set(v)
            # Keep diagnostics on device; fetch once after the loop
            # (a per-block np.asarray would sync per block).
            reasons.append((reason, real))
            iters.append(it)

        model = RandomEffectModel(
            coefficients=w_all,
            random_effect_type=ds.config.random_effect_type,
            feature_shard_id=ds.config.feature_shard_id,
            task=self.task,
            proj_all=ds.proj_all,
            variances=v_all,
            entity_keys=ds.entity_keys,
        )
        # Diagnostics stay on device: the CD loop never reads them, and an
        # eager fetch here would sync the host to every block solve.
        stats = RandomEffectTrainingStats.from_device(
            [r for r, _ in reasons], iters, [real for _, real in reasons]
        )
        return model, stats

    def score(self, model: RandomEffectModel) -> Array:
        """Model contribution per canonical row (active + passive)."""
        return model.score_dataset(self.dataset)
