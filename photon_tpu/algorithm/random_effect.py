"""RandomEffectCoordinate: batched vmapped per-entity GLM solves.

TPU-native counterpart of photon-api algorithm/RandomEffectCoordinate.scala:38
and optimization/game/RandomEffectOptimizationProblem.scala:45. The
reference's design — join activeData with per-entity
SingleNodeOptimizationProblems and run a *local* Breeze optimizer per entity
inside ``mapValues`` (:243-292) — becomes: for each size bucket of entities,
ONE jitted ``vmap`` of the full L-BFGS/OWL-QN/TRON while_loop over the entity
axis. JAX's while_loop batching rule gives masked per-entity convergence for
free (converged entities stop changing), the analog of heterogeneous
convergence across executor-local solves (SURVEY §7.3).

Per-entity projected normalization contexts
(RandomEffectOptimizationProblem.scala:137-198) are gathers of the global
factor/shift vectors through the entity's projector; the per-entity intercept
slot is a traced index, so coefficient space round-trips use one-hot masks
instead of static-index updates.

Scoring covers active AND passive rows uniformly via the dataset's remapped
scoring table (scoreActiveData :314-332 / scorePassiveData :346-366 collapse
into one gather-multiply-reduce).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_tpu import optim
from photon_tpu.algorithm.problems import (
    GLMOptimizationConfiguration,
    VarianceComputationType,
    variances_in_transformed_space,
)
from photon_tpu.data.dataset import (
    DenseFeatures,
    GLMBatch,
    SparseFeatures,
)
from photon_tpu.data.random_effect import (
    DENSE_SUB_DIM_MAX,
    ONE_HOT_ELEMENT_BUDGET,
    BlockPlan,
    EntityBlocks,
    RandomEffectDataset,
)
from photon_tpu.models.game import RandomEffectModel
from photon_tpu.ops import glm as glm_ops
from photon_tpu.ops import losses as losses_mod
from photon_tpu.ops import precision as precision_mod
from photon_tpu.ops import segment_reduce
from photon_tpu.ops.normalization import NormalizationContext
from photon_tpu.types import TaskType

Array = jax.Array


class RandomEffectTrainingStats:
    """Aggregate per-entity solver diagnostics, fetched LAZILY.

    Reference: RandomEffectOptimizationTracker (optimization/
    RandomEffectOptimizationTracker.scala:89) — counts of convergence reasons
    plus iteration stats over entities.

    The diagnostic arrays live on the device until an attribute is read:
    fetching them eagerly would insert a device->host sync into every
    coordinate update of the CD loop (on a remote-attached chip that sync
    costs more than the solve itself). Training code threads this object
    into the history without touching it; summaries/tests that read it pay
    the one coalesced transfer then. Unread stats pin two [num_entities]
    int32 device buffers per update — bounded well below the [E, S]
    coefficient matrices the same history records already retain, so no
    explicit release hook is needed.
    """

    def __init__(self, reasons=None, iterations=None, *, device=None,
                 thunk=None):
        # device: (reason device arrays, iteration device arrays,
        #          host keep-masks) — one pull on first attribute access.
        # thunk: zero-arg callable -> (reasons np, iterations np); the
        #        fused fit's packed-diagnostics buffer resolves through it.
        self._device = device
        self._thunk = thunk
        self._host = None
        if device is None and thunk is None:
            self._host = (
                np.asarray(reasons) if reasons is not None
                else np.empty(0, np.int32),
                np.asarray(iterations) if iterations is not None
                else np.empty(0, np.int32),
            )

    @staticmethod
    def from_arrays(reasons: np.ndarray, iterations: np.ndarray):
        return RandomEffectTrainingStats(reasons, iterations)

    @staticmethod
    def from_device(reason_arrays, iteration_arrays, keep_masks):
        return RandomEffectTrainingStats(
            device=(reason_arrays, iteration_arrays, keep_masks)
        )

    @staticmethod
    def from_thunk(thunk):
        return RandomEffectTrainingStats(thunk=thunk)

    def _materialize(self):
        if self._host is None and self._thunk is not None:
            reasons, iters = self._thunk()
            self._host = (np.asarray(reasons), np.asarray(iters))
            self._thunk = None
        if self._host is None:
            reasons_d, iters_d, keeps = self._device
            keep = np.concatenate(keeps) if keeps else np.empty(0, bool)
            # One coalesced fetch of all blocks' diagnostics.
            reasons = (
                np.asarray(jnp.concatenate(reasons_d)) if reasons_d
                else np.empty(0, np.int32)
            )
            iters = (
                np.asarray(jnp.concatenate(iters_d)) if iters_d
                else np.empty(0, np.int32)
            )
            self._host = (reasons[keep], iters[keep])
            self._device = None
        return self._host

    @property
    def convergence_reason_counts(self) -> dict[str, int]:
        reasons, _ = self._materialize()
        counts: dict[str, int] = {}
        for code, cnt in zip(*np.unique(reasons, return_counts=True)):
            counts[optim.ConvergenceReason(int(code)).name] = int(cnt)
        return counts

    @property
    def iterations_mean(self) -> float:
        _, iters = self._materialize()
        return float(iters.mean()) if iters.size else 0.0

    @property
    def iterations_max(self) -> int:
        _, iters = self._materialize()
        return int(iters.max()) if iters.size else 0

    @property
    def num_entities(self) -> int:
        _, iters = self._materialize()
        return int(iters.size)


def _onehot(slot: Array, dim: int, dtype) -> Array:
    """One-hot of a traced (possibly -1) slot index; all-zero when slot < 0."""
    iota = jnp.arange(dim)
    return jnp.where(iota == slot, 1.0, 0.0).astype(dtype)


def _coef_to_transformed(w, factors, shifts, int_onehot):
    if shifts is not None:
        w = w + jnp.dot(w, shifts) * int_onehot
    if factors is not None:
        w = w / factors
    return w


def _coef_to_original(w_t, factors, shifts, int_onehot):
    w = w_t if factors is None else w_t * factors
    if shifts is not None:
        w = w - jnp.dot(w, shifts) * int_onehot
    return w


def _features_of(
    x_indices: Array | None, x_values: Array, sub_dim: int
):
    """Per-entity feature view: dense [R, S] matrix or ELL slabs."""
    if x_indices is None:
        return DenseFeatures(x_values)
    return SparseFeatures(x_indices, x_values, sub_dim)


def _densify_ell_slots(
    x_indices: Array, x_values: Array, sub_dim: int
) -> Array:
    """[..., k] slot-ELL -> [..., S] dense via one-hot contraction (NOT
    scatter: batched scatter/gather lowers to a pathologically
    slow-compiling program on TPU; the one-hot einsum compiles in <1s and
    runs on the MXU). Duplicate slots sum, matching scatter-add (with an
    f32 accumulator when the values are stored bf16; the densified slab
    returns to the storage dtype)."""
    onehot = (
        x_indices[..., None]
        == jnp.arange(sub_dim, dtype=x_indices.dtype)
    ).astype(x_values.dtype)
    return precision_mod.acc_einsum(
        "...k,...ks->...s", x_values, onehot
    ).astype(x_values.dtype)


def _spd_solve_cg(h: Array, b: Array, sub_dim: int,
                  refine: bool = True) -> Array:
    """Solve the SPD system ``h x = b`` by FIXED-count conjugate gradients.

    Batched tiny Cholesky/triangular solves lower to sequential scalar
    loops on TPU — slow to run at B~1e5 under vmap and pathologically slow
    to compile — while CG is ``sub_dim`` iterations of [S, S] matvecs that
    batch cleanly into GEMMs. For SPD H (strict convexity + the unit
    padding diagonal) CG is exact after S steps up to roundoff; sub_dim is
    small by construction (LinearSubspaceProjector compression).

    In float32 S-step CG is NOT backward-stable on ill-conditioned H
    (relative error ~0.5 at cond(H)=1e4 measured), so with ``refine`` one
    round of iterative refinement follows: ``x += cg(H, b - H x)``. Both
    passes are the same batched GEMM shapes; the refined solve tracks a
    direct fp32 Cholesky down to cond(H)~1e6. Newton DIRECTION solves pass
    ``refine=False`` — directions only need descent (enforced by the
    g.d < 0 steepest-descent fallback at the call site), and refinement
    would double the sequential depth of the latency-bound hot loop.
    """

    def run_cg(rhs):
        def cg_step(_, state):
            x, r, p, rs = state
            hp = h @ p
            alpha = rs / jnp.maximum(jnp.dot(p, hp), 1e-30)
            x = x + alpha * p
            r = r - alpha * hp
            rs_new = jnp.dot(r, r)
            p = r + (rs_new / jnp.maximum(rs, 1e-30)) * p
            return x, r, p, rs_new

        init = (jnp.zeros_like(rhs), rhs, rhs, jnp.dot(rhs, rhs))
        x, _, _, _ = lax.fori_loop(0, sub_dim, cg_step, init)
        return x

    x = run_cg(b)
    if not refine:
        return x
    return x + run_cg(b - h @ x)


def _solve_one_entity_direct(
    x_indices: Array | None,  # [R, k] ELL slots, or None (dense layout)
    x_values: Array,  # [R, k] or [R, S]
    labels: Array,  # [R]
    offsets: Array,  # [R]
    weights: Array,  # [R]
    penalty_mask: Array,  # [S]
    valid_mask: Array,  # [S]
    factors: Array | None,  # [S]
    shifts: Array | None,  # [S]
    intercept_slot: Array,
    prior: tuple[Array, Array] | None,
    *,
    sub_dim: int,
    variance_computation: VarianceComputationType,
    l2_weight: Array,
    incremental_weight: Array,
    task: TaskType,
):
    """Exact per-entity solve for the squared-loss case: one batched
    Cholesky instead of ~100 sequential L-BFGS device steps.

    The per-entity GLMix subproblem for squared loss is a small convex
    quadratic; its minimizer is the normal-equations solution
      (X'^T diag(wt) X' + diag(pen)) w = X'^T diag(wt) (y - offset) (+ prior)
    — identical (to machine precision) to what the reference's LBFGS/TRON
    iterates toward (SingleNodeOptimizationProblem.run), but as a single
    MXU-friendly [S, S] factorization per entity, vmapped over the bucket.
    The subspace design matrix is densified per entity (S = sub_dim is small
    by construction — LinearSubspaceProjector compression).
    """
    # Solver STATE (w, H, b, variances) lives in the label dtype (f32);
    # only the design matrix x may be stored bf16 under mixed precision,
    # with every row-axis contraction accumulating f32 (acc_einsum).
    dtype = labels.dtype
    if x_indices is None:
        x = x_values
    else:
        # This branch only runs for wide subspaces (_solve_block densifies
        # small ones up front): scatter-add keeps peak memory at the dense
        # [R, S] result instead of a [R, k, S] one-hot operand.
        r = x_values.shape[0]
        rows = jnp.broadcast_to(jnp.arange(r)[:, None], x_indices.shape)
        x = jnp.zeros((r, sub_dim), x_values.dtype).at[
            rows, x_indices].add(x_values)
    if shifts is not None:
        x = x - precision_mod.like_storage(shifts, x)[None, :]
    if factors is not None:
        x = x * precision_mod.like_storage(factors, x)[None, :]
    y_eff = (labels - offsets) * weights
    h = precision_mod.acc_einsum(
        "rs,rt->st", x * precision_mod.like_storage(weights, x)[:, None], x
    )
    b = precision_mod.acc_einsum(
        "rs,r->s", x, precision_mod.like_storage(y_eff, x)
    )
    if prior is not None:
        int_onehot = (
            None if shifts is None
            else _onehot(intercept_slot, sub_dim, dtype)
        )
        m_t = _coef_to_transformed(prior[0], factors, shifts, int_onehot)
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        l2_diag = incremental_weight * inv_prior_var
        b = b + l2_diag * m_t
    else:
        l2_diag = l2_weight * penalty_mask
    h = h + jnp.diag(l2_diag + (1.0 - valid_mask))
    w_t = _spd_solve_cg(h, b, sub_dim) * valid_mask

    norm = NormalizationContext(
        factors=factors, shifts=shifts,
        intercept_index=None if shifts is None else 0,
    )
    if variance_computation != VarianceComputationType.NONE:
        loss = losses_mod.get_loss(task)
        # Variances run the deep f32 machinery: upcast a bf16-stored
        # design (identity on the default path) — variances are a few
        # tiny solves, not the hot loop.
        batch = GLMBatch(
            _features_of(x_indices, x_values.astype(dtype), sub_dim),
            labels, offsets, weights,
        )
        var_t = variances_in_transformed_space(
            batch, loss, w_t, norm, l2_diag, variance_computation,
        )
        f_sq = 1.0 if factors is None else factors * factors
        variances = jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    else:
        variances = jnp.zeros_like(w_t)

    int_onehot = (
        None if shifts is None else _onehot(intercept_slot, sub_dim, dtype)
    )
    w_orig = _coef_to_original(w_t, factors, shifts, int_onehot) * valid_mask
    return (
        w_orig,
        variances,
        jnp.asarray(1, jnp.int32),
        jnp.asarray(int(optim.ConvergenceReason.GRADIENT_CONVERGED),
                    jnp.int32),
    )


def _materialize_transformed_design(
    x_indices: Array | None,
    x_values: Array,
    factors: Array | None,
    shifts: Array | None,
    sub_dim: int,
) -> Array:
    """Dense [R, S] transformed design matrix for one entity."""
    dtype = x_values.dtype
    if x_indices is None:
        x = x_values
    else:
        r = x_values.shape[0]
        rows = jnp.broadcast_to(jnp.arange(r)[:, None], x_indices.shape)
        x = jnp.zeros((r, sub_dim), dtype).at[rows, x_indices].add(x_values)
    if shifts is not None:
        x = x - shifts[None, :]
    if factors is not None:
        x = x * factors[None, :]
    return x


_NEWTON_LINE_SEARCH_HALVINGS = 15


def _spd_solve_cg_sb(h_sb: Array, b_sb: Array, sub_dim: int,
                     active: Array) -> Array:
    """Batched SPD solve in BATCH-MINOR layout: ``h_sb`` is [S, S, B] and
    ``b_sb``/result are [S, B].

    Why the layout matters: a vmapped per-entity CG carries H as [B, S, S]
    and state as [B, S]; with S ~ 17 the TPU's (8, 128) tiling pads the
    minor axis 17 -> 128, physically inflating every CG-step re-read of H
    ~7-10x (the dominant HBM traffic of the whole per-entity solve,
    measured by the round-4 Pallas probe, experiments/README.md). With B
    minor, lanes are dense: H is stored compact and each of the S CG steps
    is elementwise-over-B multiply-reduce work at full lane utilization.

    ``active`` [B] masks converged entities: their iterates are frozen so
    a diverging stale system cannot produce NaNs that poison the batch.
    """

    def cg_step(_, state):
        x, r, p, rs = state
        # Broadcast-multiply-reduce, NOT einsum/dot_general: the batched
        # contraction with minor batch dim lowers to per-row slice chains
        # (~3 x 0.7ms per CG step measured), while this form fuses into
        # one elementwise+reduce kernel over the compact [S, S, B] block.
        hp = jnp.sum(h_sb * p[None, :, :], axis=1)
        denom = jnp.sum(p * hp, axis=0)
        alpha = jnp.where(active, rs / jnp.maximum(denom, 1e-30), 0.0)
        x = x + alpha[None, :] * p
        r = r - alpha[None, :] * hp
        rs_new = jnp.sum(r * r, axis=0)
        beta = rs_new / jnp.maximum(rs, 1e-30)
        p = r + jnp.where(active, beta, 0.0)[None, :] * p
        return x, r, p, rs_new

    init = (jnp.zeros_like(b_sb), b_sb, b_sb,
            jnp.sum(b_sb * b_sb, axis=0))
    x, _, _, _ = lax.fori_loop(0, sub_dim, cg_step, init)
    return x


def _solve_direct_gram(
    block,  # EntityBlocks, ELL layout (x_indices is not None)
    offsets: Array,  # [B, R] effective offsets (residuals folded in)
    factors_sub: Array | None,  # [B, S]
    prior: tuple[Array, Array] | None,  # ([B, S], [B, S])
    *,
    sub_dim: int,
    l2_weight: Array,
    incremental_weight: Array,
    gram_mults: tuple,
):
    """Whole-bucket exact squared-loss solve straight from the ELL layout.

    The wide-subspace direct path previously materialized a dense
    [B, R, S] slab (per entity, or bucket-wide via densify_ell_blocks)
    just to form X^T W X — at wide S that slab is the dominant HBM
    object of the whole solve. But the normal equations only need the
    [B, S, S] gram blocks and the [B, S] moment vector, and BOTH are
    segment sums over the ELL entries: pair products w * v_j * v_l land
    in gram segment (entity, slot_j, slot_l), weighted targets in
    (entity, slot). The tiled segment-reduce (ops/segment_reduce)
    aggregates them with the host-computed window bounds sizing
    coverage (``gram_mults`` = data/random_effect.block_gram_mults) —
    the dense slab never exists.

    Engagement is gated by ``_solve_block`` (direct + ELL + no shifts +
    no variances + kernel-served shape). Normalization factors fold in
    AFTER the reduce: X' = X F gives H' = F H F and b' = F b (diagonal
    congruence) — the same algebra the per-entity solver applies
    row-wise before aggregating.
    """
    dtype = block.labels.dtype
    s = sub_dim
    grad_mult, hess_mult = gram_mults
    gram = segment_reduce.ell_gram_blocks(
        block.x_indices, block.x_values, block.weights, s,
        multiplicity=hess_mult,
    )
    y_eff = (block.labels - offsets) * block.weights
    bvec = segment_reduce.ell_segment_slots(
        block.x_indices, block.x_values, y_eff, s,
        multiplicity=grad_mult,
    )
    assert gram is not None and bvec is not None  # ell_gram_supported gate
    h = gram.astype(dtype)
    b_vec = bvec.astype(dtype)
    if factors_sub is not None:
        h = h * factors_sub[:, :, None] * factors_sub[:, None, :]
        b_vec = b_vec * factors_sub
    valid_mask = block.valid_mask
    if prior is not None:
        # Shifts are None on this route, so the transformed prior means
        # are just the factor-rescaled originals (no intercept fold).
        m_t = _coef_to_transformed(prior[0], factors_sub, None, None)
        f_sq = 1.0 if factors_sub is None else factors_sub * factors_sub
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        l2_diag = incremental_weight * inv_prior_var
        b_vec = b_vec + l2_diag * m_t
    else:
        l2_diag = l2_weight * block.penalty_mask
    # Padding slots get a unit diagonal so the system stays PD; their
    # gradient is masked (identical to the per-entity solver).
    h = h + jnp.eye(s, dtype=dtype) * (
        l2_diag + (1.0 - valid_mask))[:, None, :]
    # Batch-minor CG (compact lanes, see _spd_solve_cg_sb) plus one
    # refinement pass — matching the refined default the per-entity
    # direct solver gets from _spd_solve_cg.
    h_sb = jnp.transpose(h, (1, 2, 0))
    b_sb = jnp.transpose(b_vec)
    active = jnp.ones(b_vec.shape[0], bool)
    sol = _spd_solve_cg_sb(h_sb, b_sb, s, active)
    res = b_sb - jnp.sum(h_sb * sol[None, :, :], axis=1)
    sol = sol + _spd_solve_cg_sb(h_sb, res, s, active)
    w_t = jnp.transpose(sol).astype(dtype) * valid_mask
    w = _coef_to_original(w_t, factors_sub, None, None) * valid_mask
    bsz = w.shape[0]
    return (
        w,
        jnp.zeros_like(w),
        jnp.ones(bsz, jnp.int32),
        jnp.full(
            bsz,
            int(optim.ConvergenceReason.GRADIENT_CONVERGED),
            jnp.int32,
        ),
    )


def _solve_newton_batched(
    x: Array,  # [B, R, S] dense slab (raw, untransformed)
    labels: Array,  # [B, R]
    offsets: Array,  # [B, R]
    weights: Array,  # [B, R]
    penalty_mask: Array,  # [B, S]
    valid_mask: Array,  # [B, S]
    factors: Array | None,  # [B, S]
    shifts: Array | None,  # [B, S]
    intercept_slots: Array,  # [B]
    w0_orig: Array,  # [B, S]
    prior: tuple[Array, Array] | None,  # ([B, S], [B, S])
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    variance_computation: VarianceComputationType,
    l2_weight: Array,
    incremental_weight: Array,
):
    """Batch-level damped-Newton/IRLS for a whole dense bucket.

    Numerically the batched transcription of ``_solve_one_entity_newton``
    (same objective, same one-pass Armijo trials, same convergence
    cascade), written WITHOUT vmap so the Hessians and CG state can live
    in batch-minor layout (see ``_spd_solve_cg_sb``): the [B, S, S] MXU
    Hessian batch is transposed ONCE to compact [S, S, B] instead of being
    re-read S times through a 7-10x tiling-padded layout. The Newton
    direction uses a single S-step CG (no refinement pass — directions
    only need descent, which the g.d < 0 guard enforces; the refined
    solver stays on the exact direct path where the solution itself is
    the answer).
    """
    # Solver state (w, f, g, H, CG iterates) is f32; only the slab x may
    # be stored bf16 under mixed precision — every contraction against
    # it reads bf16 and accumulates f32 (ops/precision.py invariant).
    dtype = labels.dtype
    b = x.shape[0]
    if shifts is not None:
        x = x - precision_mod.like_storage(shifts, x)[:, None, :]
    if factors is not None:
        x = x * precision_mod.like_storage(factors, x)[:, None, :]
    loss = losses_mod.get_loss(task)
    iota = jnp.arange(sub_dim)[None, :]
    int_onehot = (
        None if shifts is None
        else (iota == intercept_slots[:, None]).astype(dtype)
    )

    def to_transformed(w):
        if shifts is not None:
            w = w + jnp.sum(w * shifts, axis=-1, keepdims=True) * int_onehot
        if factors is not None:
            w = w / factors
        return w

    def to_original(w_t):
        w = w_t if factors is None else w_t * factors
        if shifts is not None:
            w = w - jnp.sum(w * shifts, axis=-1, keepdims=True) * int_onehot
        return w

    if prior is not None:
        m_t = to_transformed(prior[0])
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        l2_diag = incremental_weight * inv_prior_var
    else:
        m_t = jnp.zeros((b, sub_dim), dtype)
        l2_diag = l2_weight * penalty_mask

    def objective(w):  # w [B, S] -> f [B], g [B, S]
        z = precision_mod.acc_einsum(
            "brs,bs->br", x, precision_mod.like_storage(w, x)
        ) + offsets
        f = jnp.sum(weights * loss.loss(z, labels), axis=-1) + 0.5 * jnp.sum(
            l2_diag * (w - m_t) ** 2, axis=-1
        )
        g = precision_mod.acc_einsum(
            "brs,br->bs", x,
            precision_mod.like_storage(weights * loss.dz(z, labels), x),
        )
        g = g + l2_diag * (w - m_t)
        return f, g * valid_mask

    # Per-entity absolute tolerances from the zero state
    # (Optimizer.scala:167-170 semantics, batched).
    f0z, g0z = objective(jnp.zeros((b, sub_dim), dtype))
    tol = optim.Tolerances(
        loss_abs=jnp.abs(f0z) * opt_config.tolerance,
        gradient_abs=jnp.sqrt(jnp.sum(g0z * g0z, axis=-1))
        * opt_config.tolerance,
    )
    w0 = to_transformed(w0_orig) * valid_mask
    f0, g0 = objective(w0)
    max_iters = opt_config.max_iterations

    from photon_tpu.ops import newton_kernel as nk

    r = x.shape[1]
    # The fused Newton kernel is f32-only: a bf16-stored slab takes the
    # batch-minor XLA path below (which reads the slab at half width —
    # the storage win survives the fallback).
    if nk.kernel_supported(task, x.dtype, r, sub_dim):
        # Fused Pallas step: the [S, S] Hessians never leave VMEM (the
        # XLA path's padded [B, S, S] HBM round trip was the dominant
        # per-iteration traffic; ops/newton_kernel.py, 3.1x measured).
        bp = nk.pad_lanes(b)

        def pad_b(a):
            return jnp.pad(a, [(0, bp - b)] + [(0, 0)] * (a.ndim - 1))

        x_l = jnp.transpose(pad_b(x), (2, 1, 0))
        y_l = nk.to_lanes(labels, bp)
        wt_l = nk.to_lanes(weights, bp)
        off_l = nk.to_lanes(offsets, bp)
        l2_l = nk.to_lanes(jnp.broadcast_to(l2_diag, (b, sub_dim)), bp)
        mt_l = nk.to_lanes(jnp.broadcast_to(m_t, (b, sub_dim)), bp)
        vm_l = nk.to_lanes(valid_mask, bp)
        w_l = nk.to_lanes(w0, bp)
        g_l = nk.to_lanes(g0, bp)
        f_l = jnp.pad(f0, (0, bp - b))[None, :]
        tol_p = optim.Tolerances(
            loss_abs=jnp.pad(tol.loss_abs, (0, bp - b)),
            gradient_abs=jnp.pad(tol.gradient_abs, (0, bp - b)),
        )

        def cond_k(st):
            return jnp.any(st[4] == 0)

        def body_k(st):
            w_c, f_c, g_c, it_c, code_c = st
            active = code_c == 0
            w_n, f_n, g_n, imp = nk.newton_step_lanes(
                x_l, w_c, y_l, wt_l, off_l, l2_l, mt_l, vm_l, f_c,
                r=r, s=sub_dim, task=task,
                trials=_NEWTON_LINE_SEARCH_HALVINGS + 1,
                interpret=nk.interpret_required(),
            )
            w_n = jnp.where(active[None, :], w_n, w_c)
            f_n = jnp.where(active[None, :], f_n, f_c)
            g_n = jnp.where(active[None, :], g_n, g_c)
            it_n = jnp.where(active, it_c + 1, it_c)
            code_n = optim.convergence_code(
                iteration=it_n,
                max_iterations=max_iters,
                loss_delta=f_c[0] - f_n[0],
                gradient_norm=jnp.sqrt(jnp.sum(g_n * g_n, axis=0)),
                tol=tol_p,
                not_improving=~(imp[0] > 0),
            )
            code_n = jnp.where(active, code_n, code_c)
            return w_n, f_n, g_n, it_n, code_n

        w_lk, _, _, iters_k, reason_k = lax.while_loop(
            cond_k, body_k,
            (w_l, f_l, g_l, jnp.zeros(bp, jnp.int32),
             jnp.zeros(bp, jnp.int32)),
        )
        w_t = jnp.transpose(w_lk)[:b] * valid_mask
        iters = iters_k[:b]
        reason = reason_k[:b]
        if variance_computation != VarianceComputationType.NONE:
            variances = _batched_variances(
                x, labels, offsets, weights, w_t, l2_diag, valid_mask,
                factors, shifts, loss, variance_computation,
            )
        else:
            variances = jnp.zeros_like(w_t)
        w_orig = to_original(w_t) * valid_mask
        return w_orig, variances, iters, reason

    trial_ts = 0.5 ** jnp.arange(
        _NEWTON_LINE_SEARCH_HALVINGS + 1, dtype=dtype
    )  # [T]

    def cond(s):
        _, _, _, _, code = s
        return jnp.any(code == 0)

    def body(s):
        w, f, g, it, code = s
        active = code == 0
        z = precision_mod.acc_einsum(
            "brs,bs->br", x, precision_mod.like_storage(w, x)
        ) + offsets
        curvature = weights * loss.dzz(z, labels)
        h = precision_mod.acc_einsum(
            "brs,brt->bst",
            x * precision_mod.like_storage(curvature, x)[:, :, None], x,
        )
        h = h + (
            l2_diag[:, :, None] * jnp.eye(sub_dim, dtype=dtype)[None]
            + (1.0 - valid_mask)[:, :, None]
            * jnp.eye(sub_dim, dtype=dtype)[None]
        )
        # ONE compact transpose; CG then re-reads the dense [S, S, B]
        # copy instead of the tiling-padded MXU output.
        h_sb = jnp.transpose(h, (1, 2, 0))
        d = jnp.transpose(
            _spd_solve_cg_sb(h_sb, -jnp.transpose(g), sub_dim, active)
        ) * valid_mask
        gd = jnp.sum(g * d, axis=-1)
        # Descent guard (same as the vmapped path): fp32 CG on a
        # near-singular Hessian can return a non-descent direction.
        bad = gd >= 0.0
        d = jnp.where(bad[:, None], -g, d)
        gd = jnp.where(bad, -jnp.sum(g * g, axis=-1), gd)

        zd = precision_mod.acc_einsum(
            "brs,bs->br", x, precision_mod.like_storage(d, x)
        )
        z_t = z[None] + trial_ts[:, None, None] * zd[None]  # [T, B, R]
        w_t_trials = w[None] + trial_ts[:, None, None] * d[None]  # [T,B,S]
        f_t = jnp.sum(
            weights[None] * loss.loss(z_t, labels[None]), axis=-1
        ) + 0.5 * jnp.sum(
            l2_diag[None] * (w_t_trials - m_t[None]) ** 2, axis=-1
        )  # [T, B]
        armijo = f_t <= f[None] + 1e-4 * trial_ts[:, None] * gd[None]
        first = jnp.argmax(armijo, axis=0)  # [B]
        any_ok = jnp.any(armijo, axis=0)
        t = trial_ts[first]
        f_t_sel = jnp.take_along_axis(f_t, first[None], axis=0)[0]
        improved = any_ok & (f_t_sel < f)
        step_ok = active & improved
        w_new = jnp.where(step_ok[:, None], w + t[:, None] * d, w)
        f_new, g_new = objective(w_new)
        f_new = jnp.where(active, f_new, f)
        g_new = jnp.where(active[:, None], g_new, g)
        it_new = jnp.where(active, it + 1, it)
        code_new = optim.convergence_code(
            iteration=it_new,
            max_iterations=max_iters,
            loss_delta=f - f_new,
            gradient_norm=jnp.sqrt(jnp.sum(g_new * g_new, axis=-1)),
            tol=tol,
            not_improving=~improved,
        )
        code_new = jnp.where(active, code_new, code)
        return w_new, f_new, g_new, it_new, code_new

    w_t, f_fin, g_fin, iters, reason = lax.while_loop(
        cond, body,
        (w0, f0, g0, jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.int32)),
    )
    w_t = w_t * valid_mask

    if variance_computation != VarianceComputationType.NONE:
        variances = _batched_variances(
            x, labels, offsets, weights, w_t, l2_diag, valid_mask,
            factors, shifts, loss, variance_computation,
        )
    else:
        variances = jnp.zeros_like(w_t)

    w_orig = to_original(w_t) * valid_mask
    return w_orig, variances, iters, reason


def _batched_variances(x_t, labels, offsets, weights, w_t, l2_diag,
                       valid_mask, factors, shifts, loss,
                       variance_computation):
    """Coefficient variances for a dense bucket, batched.

    ``x_t`` is ALREADY the transformed design, so the Hessian diagonal /
    full Hessian come from plain batched contractions (the vmapped
    ``variances_in_transformed_space`` would re-apply normalization).
    SIMPLE inverts the Hessian diagonal; FULL recovers the inverse
    Hessian's diagonal with one refined batch-minor CG per basis vector.
    """
    z = precision_mod.acc_einsum(
        "brs,bs->br", x_t, precision_mod.like_storage(w_t, x_t)
    ) + offsets
    curv = weights * loss.dzz(z, labels)
    f_sq = 1.0 if factors is None else factors * factors
    h_diag = precision_mod.acc_einsum(
        "brs,br->bs", x_t * x_t, precision_mod.like_storage(curv, x_t)
    ) + l2_diag
    dead = h_diag == 0.0  # zero-support, zero-penalty slots: var = inf
    if variance_computation == VarianceComputationType.SIMPLE:
        var_t = 1.0 / jnp.where(dead, jnp.inf, h_diag)
        return jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    # FULL: diagonal of the inverse Hessian — one refined batch-minor CG
    # per basis vector (refinement keeps fp32 accuracy at the direct
    # path's level; variance columns are s tiny solves, not the hot loop).
    s = w_t.shape[-1]
    h = precision_mod.acc_einsum(
        "brs,brt->bst",
        x_t * precision_mod.like_storage(curv, x_t)[:, :, None], x_t,
    )
    h = h + l2_diag[:, :, None] * jnp.eye(s, dtype=w_t.dtype)[None]
    h = h + dead[:, :, None] * jnp.eye(s, dtype=w_t.dtype)[None]
    h_sb = jnp.transpose(h, (1, 2, 0))
    active = jnp.ones(w_t.shape[0], bool)

    def col(i, acc):
        e = jnp.zeros((s, w_t.shape[0]), w_t.dtype).at[i].set(1.0)
        sol = _spd_solve_cg_sb(h_sb, e, s, active)
        res = e - jnp.sum(h_sb * sol[None, :, :], axis=1)
        sol = sol + _spd_solve_cg_sb(h_sb, res, s, active)
        return acc.at[:, i].set(sol[i])

    var_t = lax.fori_loop(0, s, col, jnp.zeros_like(w_t))
    var_t = jnp.where(dead, jnp.inf, var_t)
    return jnp.where(valid_mask > 0, var_t * f_sq, 0.0)


def _solve_one_entity_newton(
    x_indices: Array | None,  # [R, k] ELL slots, or None (dense layout)
    x_values: Array,  # [R, k] or [R, S]
    labels: Array,  # [R]
    offsets: Array,  # [R]
    weights: Array,  # [R]
    penalty_mask: Array,  # [S]
    valid_mask: Array,  # [S]
    factors: Array | None,  # [S]
    shifts: Array | None,  # [S]
    intercept_slot: Array,
    w0_orig: Array,  # [S] original-space warm start
    prior: tuple[Array, Array] | None,
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    variance_computation: VarianceComputationType,
    l2_weight: Array,
    incremental_weight: Array,
):
    """Damped-Newton (IRLS) per-entity solve for smooth convex losses.

    The iterative L-BFGS path runs ~100+ sequential tiny device steps per
    bucket (two-loop recursions and line-search probes on S~17 vectors) —
    latency-bound work that leaves the MXU idle. For logistic/Poisson with
    an L2 term the subproblem is smooth and strictly convex, so exact
    Newton with Armijo backtracking converges in a handful of iterations
    of batched [R,S] GEMMs + one [S,S] Cholesky — the same optimum the
    reference's per-entity LBFGS iterates toward
    (RandomEffectCoordinate.scala:243-292) at a fraction of the sequential
    depth. Convergence reporting matches the Optimizer cascade
    (Optimizer.scala:126-139) via the shared ``convergence_code``.
    """
    dtype = x_values.dtype
    x = _materialize_transformed_design(
        x_indices, x_values, factors, shifts, sub_dim
    )
    loss = losses_mod.get_loss(task)
    int_onehot = (
        None if shifts is None else _onehot(intercept_slot, sub_dim, dtype)
    )
    if prior is not None:
        m_t = _coef_to_transformed(prior[0], factors, shifts, int_onehot)
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        l2_diag = incremental_weight * inv_prior_var
    else:
        m_t = jnp.zeros(sub_dim, dtype)
        l2_diag = l2_weight * penalty_mask

    def objective(w):
        z = x @ w + offsets
        f = jnp.sum(weights * loss.loss(z, labels)) + 0.5 * jnp.sum(
            l2_diag * (w - m_t) ** 2
        )
        g = x.T @ (weights * loss.dz(z, labels)) + l2_diag * (w - m_t)
        return f, g * valid_mask

    tol = optim.absolute_tolerances(
        objective, w0_orig, opt_config.tolerance
    )
    w0 = _coef_to_transformed(w0_orig, factors, shifts, int_onehot)
    w0 = w0 * valid_mask
    f0, g0 = objective(w0)
    max_iters = opt_config.max_iterations

    def cond(s):
        w, f, g, it, code = s
        return code == 0

    # All Armijo trial steps evaluate in ONE pass: the margin is affine in
    # the step size (z_t = z + t * (x @ d)), so a single extra matvec gives
    # every candidate, replacing up to _NEWTON_LINE_SEARCH_HALVINGS
    # sequential probe loops with elementwise work — sequential depth is
    # what the batched solve is bound by.
    trial_ts = 0.5 ** jnp.arange(
        _NEWTON_LINE_SEARCH_HALVINGS + 1, dtype=dtype
    )  # [T]: 1, 1/2, 1/4, ...

    def body(s):
        w, f, g, it, code = s
        z = x @ w + offsets
        curvature = weights * loss.dzz(z, labels)
        h = x.T @ (curvature[:, None] * x)
        # Padding slots get a unit diagonal so the system stays PD;
        # their gradient is masked, so their step is 0.
        h = h + jnp.diag(l2_diag + (1.0 - valid_mask))
        d = _spd_solve_cg(h, -g, sub_dim, refine=False) * valid_mask
        gd = jnp.dot(g, d)
        # Unrefined fp32 CG can return a non-descent direction on a
        # near-singular Hessian; Armijo would then reject every trial and
        # the loop would exit at a non-optimum. Fall back to steepest
        # descent for such iterations — guaranteed descent, and the next
        # iteration's Hessian is evaluated at the new point.
        bad = gd >= 0.0
        d = jnp.where(bad, -g, d)
        gd = jnp.where(bad, -jnp.sum(g * g), gd)

        zd = x @ d  # [R]; z_t = z + t * zd for every trial t
        z_t = z[None, :] + trial_ts[:, None] * zd[None, :]  # [T, R]
        w_t_trials = w[None, :] + trial_ts[:, None] * d[None, :]  # [T, S]
        f_t = jnp.sum(
            weights[None, :] * loss.loss(z_t, labels[None, :]), axis=1
        ) + 0.5 * jnp.sum(
            l2_diag[None, :] * (w_t_trials - m_t[None, :]) ** 2, axis=1
        )  # [T]
        armijo = f_t <= f + 1e-4 * trial_ts * gd
        # First (largest) t satisfying Armijo — the same step sequential
        # halving would accept.
        first = jnp.argmax(armijo)
        any_ok = jnp.any(armijo)
        t = trial_ts[first]
        f_t_sel = f_t[first]
        improved = any_ok & (f_t_sel < f)
        w_new = jnp.where(improved, w + t * d, w)
        f_new, g_new = objective(w_new)
        code_new = optim.convergence_code(
            iteration=it + 1,
            max_iterations=max_iters,
            loss_delta=f - f_new,
            gradient_norm=jnp.sqrt(jnp.sum(g_new * g_new)),
            tol=tol,
            not_improving=~improved,
        )
        return w_new, f_new, g_new, it + 1, code_new

    w_t, f_fin, g_fin, iters, reason = lax.while_loop(
        cond, body,
        (w0, f0, g0, jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)),
    )
    w_t = w_t * valid_mask

    if variance_computation != VarianceComputationType.NONE:
        batch = GLMBatch(
            _features_of(x_indices, x_values, sub_dim),
            labels, offsets, weights,
        )
        norm = NormalizationContext(
            factors=factors, shifts=shifts,
            intercept_index=None if shifts is None else 0,
        )
        var_t = variances_in_transformed_space(
            batch, loss, w_t, norm, l2_diag, variance_computation,
        )
        f_sq = 1.0 if factors is None else factors * factors
        variances = jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    else:
        variances = jnp.zeros_like(w_t)

    w_orig = _coef_to_original(w_t, factors, shifts, int_onehot) * valid_mask
    return w_orig, variances, iters, reason


def _solve_one_entity(
    x_indices: Array | None,  # [R, k] ELL slots, or None (dense layout)
    x_values: Array,  # [R, k] or [R, S]
    labels: Array,  # [R]
    offsets: Array,  # [R]
    weights: Array,  # [R]
    penalty_mask: Array,  # [S]
    valid_mask: Array,  # [S]
    factors: Array,  # [S] (ones where no normalization)
    shifts: Array,  # [S] (zeros where none)
    intercept_slot: Array,  # scalar int32, -1 if absent
    w0_orig: Array,  # [S] original-space warm start
    prior: tuple[Array, Array] | None,  # ([S] means, [S] vars) original space
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    use_owlqn: bool,
    variance_computation: VarianceComputationType,
    l1_weight: Array,  # traced scalars, closed over (broadcast under vmap)
    l2_weight: Array,
    incremental_weight: Array,
):
    """One entity's full solve; vmapped over the bucket's entity axis.

    Mirrors SingleNodeOptimizationProblem.run (:90-98): transformed-space
    solve with the effective-coefficient rewrite, reported in original space.
    Regularization weights are traced, so a new lambda (warm-start ladder,
    tuner retrain) reuses the compiled block solve.
    """
    loss = losses_mod.get_loss(task)
    feats = _features_of(x_indices, x_values, sub_dim)
    batch = GLMBatch(feats, labels, offsets, weights)
    # Per-entity projected normalization; factors/shifts are None (static)
    # when the coordinate has no normalization, so the objective specializes
    # to the raw fast path at trace time. intercept_index is only consulted
    # by the static-index round-trip helpers, which we bypass.
    norm = NormalizationContext(
        factors=factors,
        shifts=shifts,
        intercept_index=None if shifts is None else 0,
    )
    int_onehot = (
        None if shifts is None
        else _onehot(intercept_slot, sub_dim, w0_orig.dtype)
    )

    w0 = _coef_to_transformed(w0_orig, factors, shifts, int_onehot)
    fun = glm_ops.make_value_and_grad(batch, loss, norm)
    if prior is not None:
        # Per-entity Gaussian prior (incremental training): replaces the
        # plain L2 term; the L2 weight is the fallback precision for slots
        # absent from the prior model (PriorDistribution.scala:31-60).
        # Padded slots are masked out of the penalty entirely.
        prior_means_t = _coef_to_transformed(
            prior[0], factors, shifts, int_onehot)
        f_sq = 1.0 if factors is None else factors * factors
        inv_prior_var = optim.inverse_prior_variances(
            prior[1] / f_sq, l2_weight) * valid_mask
        obj = optim.with_gaussian_prior(
            fun, incremental_weight, prior_means_t, inv_prior_var)
        l2_diag = incremental_weight * inv_prior_var
    else:
        obj = optim.with_l2_masked(fun, l2_weight, penalty_mask)
        l2_diag = l2_weight * penalty_mask

    if use_owlqn:
        result = optim.owlqn_solve(obj, w0, l1_weight, opt_config)
    elif opt_config.optimizer_type == optim.OptimizerType.TRON:
        hvp = glm_ops.make_hvp(batch, loss, norm)
        if prior is not None:
            obj_hvp = optim.with_gaussian_prior_hvp(
                hvp, incremental_weight, inv_prior_var)
        else:
            obj_hvp = optim.with_l2_hvp_masked(hvp, l2_weight, penalty_mask)
        result = optim.tron_solve(obj, obj_hvp, w0, opt_config)
    else:
        result = optim.lbfgs_solve(obj, w0, opt_config)

    w_t = result.coefficients * valid_mask

    if variance_computation != VarianceComputationType.NONE:
        var_t = variances_in_transformed_space(
            batch, loss, w_t, norm, l2_diag, variance_computation,
        )
        f_sq = 1.0 if factors is None else factors * factors
        # Padded slots (and zero-support slots) carry var inf; report 0 for
        # padding, inf for genuinely unsupported-but-valid slots.
        variances = jnp.where(valid_mask > 0, var_t * f_sq, 0.0)
    else:
        variances = jnp.zeros_like(w_t)

    w_orig = _coef_to_original(w_t, factors, shifts, int_onehot) * valid_mask
    return w_orig, variances, result.iterations, result.convergence_reason


@functools.partial(
    jax.jit,
    static_argnames=(
        "sub_dim", "task", "opt_config", "use_owlqn", "variance_computation",
        "direct", "newton", "precision", "gram_mults",
    ),
    # Buffer donation through _scatter_results: the [E, Smax] coefficient
    # and variance tables are CARRIES — each bucket's scatter returns the
    # updated table and the caller rebinds, so the input buffers are dead
    # on return. Donating them lets XLA update the tables in place
    # instead of round-tripping a fresh [E, Smax] allocation per bucket
    # (inline fused calls ignore donation; the fori_loop carries alias
    # there instead). Callers must never alias w_all/v_all with another
    # operand (see warmup_thunks).
    donate_argnums=(9, 10),
)
def _solve_block(
    block,  # EntityBlocks | BlockPlan (pytree structure selects the path)
    residuals: Array | None,  # [n] canonical residual scores, or None
    factors_full: Array | None,  # [d] global normalization factors
    shifts_full: Array | None,  # [d] global normalization shifts
    w0_full: Array | None,  # [E, Smax] original-space warm starts
    l1_weight: Array,
    l2_weight: Array,
    incremental_weight: Array,
    prior_full: tuple[Array, Array] | None,  # ([E, Smax], [E, Smax]) or None
    w_all: Array,  # [E, Smax] coefficient table to scatter results into
    v_all: Array | None,  # [E, Smax] variance table, or None
    *,
    sub_dim: int,
    task: TaskType,
    opt_config: optim.OptimizerConfig,
    use_owlqn: bool,
    variance_computation: VarianceComputationType,
    direct: bool = False,
    newton: bool = False,
    precision: str = "float32",
    gram_mults: tuple | None = None,
):
    """One bucket's batched per-entity solve (everything traced/fused).

    Lazy ``BlockPlan`` buckets materialize their [B, R, k] slabs here, INSIDE
    the compiled program, by gathering the HBM-resident raw arrays — the
    slabs never exist on the host (data/random_effect.py module docstring).
    Warm-start / prior / normalization gathers are also traced, so one fit
    dispatches a single device program per bucket. The result scatter into
    the [E, Smax] tables happens in here too — eager per-block pads and
    scatters each cost a ~0.7s one-time compile on the TPU backend, so the
    whole update rides the bucket's one program. Mesh-padding sentinel codes
    (== num_entities) drop out of bounds in the scatter.
    """
    if isinstance(block, BlockPlan):
        block = block.materialize(residuals)
        offsets = block.offsets
    else:
        offsets = block.offsets
        if residuals is not None:
            # Padding rows alias canonical row 0; mask their gather.
            offsets = offsets + jnp.where(
                block.weights > 0,
                jnp.take(residuals, block.row_ids, mode="clip"),
                0.0,
            )
    if precision_mod.is_mixed(precision):
        # bf16 SLAB STORAGE (the mixed-precision policy): the design
        # slab — the dominant per-iteration HBM read — is held and read
        # at half width; solver state stays f32 (dtype below) and every
        # row-axis contraction accumulates f32 (ops/precision.py).
        block = dataclasses.replace(
            block,
            x_values=precision_mod.in_storage(block.x_values, precision),
        )
    # Solver state (tables, gradients, Hessians, masks) anchors on the
    # LABEL dtype, not the slab's: a bf16-stored slab must not narrow
    # the iterates.
    dtype = block.labels.dtype
    if (
        block.x_indices is not None
        and sub_dim <= DENSE_SUB_DIM_MAX
        and int(np.prod(block.x_indices.shape)) * sub_dim
        <= ONE_HOT_ELEMENT_BUDGET
    ):
        # Densify small-subspace ELL blocks so every downstream op is a
        # matmul; batched gather/scatter both execute worse and compile
        # ~40x slower on TPU. The element budget keeps the transient
        # one-hot operand bounded; over-budget blocks stay ELL.
        block = dataclasses.replace(
            block,
            x_indices=None,
            x_values=_densify_ell_slots(
                block.x_indices, block.x_values, sub_dim
            ),
        )
    # Wide-ELL direct solves can skip densification ENTIRELY: the normal
    # equations only need X^T W X and X^T W y, which _solve_direct_gram
    # aggregates straight from the ELL entries through the tiled
    # segment-reduce. Engagement needs the planner's host-computed
    # window bounds (gram_mults), no shift normalization (shifts break
    # ELL sparsity), no variance computation (variances read the dense
    # design), and a kernel-served shape — everything static.
    gram_route = (
        direct
        and gram_mults is not None
        and shifts_full is None
        and variance_computation == VarianceComputationType.NONE
        and block.x_indices is not None
        and segment_reduce.ell_gram_supported(
            *block.x_indices.shape, sub_dim,
            grad_mult=gram_mults[0], hess_mult=gram_mults[1],
        )
    )
    if (
        block.x_indices is not None
        and (newton or direct)
        and not gram_route
    ):
        # Wide-subspace ELL: one flat tiled segment-reduce densifies the
        # WHOLE bucket (ops/segment_reduce) where the kernel serves this
        # backend — routing it onto the batched dense solvers instead of
        # the per-entity vmapped scatter path. None = keep ELL.
        dense = segment_reduce.densify_ell_blocks(
            block.x_indices, block.x_values, sub_dim
        )
        if dense is not None:
            block = dataclasses.replace(
                block, x_indices=None, x_values=dense
            )
    if (
        block.x_values.dtype == jnp.bfloat16
        and not direct
        and not (newton and block.x_indices is None)
    ):
        # The vmapped quasi-Newton/OWL-QN/ELL-Newton paths run f32 end
        # to end: upcast the stored slab once inside the program (the
        # HBM read of the slab is still half-width).
        block = dataclasses.replace(
            block, x_values=block.x_values.astype(dtype)
        )
    s = sub_dim
    codes = block.entity_codes
    proj = block.proj  # [B, S]; -1 pad
    safe = jnp.maximum(proj, 0)
    factors_sub = shifts_sub = None
    if factors_full is not None:
        f = jnp.take(factors_full.astype(dtype), safe, mode="clip")
        factors_sub = jnp.where(proj >= 0, f, 1.0)
    if shifts_full is not None:
        sh = jnp.take(shifts_full.astype(dtype), safe, mode="clip")
        shifts_sub = jnp.where(proj >= 0, sh, 0.0)
    if w0_full is not None:
        # Sentinel codes (mesh entity padding) clip to the last row; their
        # results are dropped by the out-of-bounds scatter on the way back.
        w0 = jnp.take(w0_full.astype(dtype), codes, axis=0, mode="clip")
        w0 = w0[:, :s]
    else:
        w0 = jnp.zeros((block.num_entities, s), dtype)
    prior = None
    if prior_full is not None:
        prior = (
            jnp.take(
                prior_full[0].astype(dtype), codes, axis=0, mode="clip"
            )[:, :s],
            jnp.take(
                prior_full[1].astype(dtype), codes, axis=0, mode="clip"
            )[:, :s],
        )
    if direct:
        if gram_route:
            w, v, it, reason = _solve_direct_gram(
                block,
                offsets,
                factors_sub,
                prior,
                sub_dim=sub_dim,
                l2_weight=l2_weight,
                incremental_weight=incremental_weight,
                gram_mults=gram_mults,
            )
            return _scatter_results(w_all, v_all, codes, w, v, it, reason)

        def direct_solver(xi, xv, lb, off, wt, pm, vm, f, sh, islot, prior_e):
            return _solve_one_entity_direct(
                xi, xv, lb, off, wt, pm, vm, f, sh, islot, prior_e,
                sub_dim=sub_dim,
                variance_computation=variance_computation,
                l2_weight=l2_weight,
                incremental_weight=incremental_weight,
                task=task,
            )

        w, v, it, reason = jax.vmap(direct_solver)(
            block.x_indices,
            block.x_values,
            block.labels,
            offsets,
            block.weights,
            block.penalty_mask,
            block.valid_mask,
            factors_sub,
            shifts_sub,
            block.intercept_slots,
            prior,
        )
        return _scatter_results(w_all, v_all, codes, w, v, it, reason)

    if newton:
        if block.x_indices is None:
            # Dense buckets take the batch-minor rewrite: compact [S,S,B]
            # Hessians + dense-lane CG instead of the vmapped layout whose
            # tiling-padded H re-reads dominated the solve's HBM traffic.
            w, v, it, reason = _solve_newton_batched(
                block.x_values,
                block.labels,
                offsets,
                block.weights,
                block.penalty_mask,
                block.valid_mask,
                factors_sub,
                shifts_sub,
                block.intercept_slots,
                w0,
                prior,
                sub_dim=sub_dim,
                task=task,
                opt_config=opt_config,
                variance_computation=variance_computation,
                l2_weight=l2_weight,
                incremental_weight=incremental_weight,
            )
            return _scatter_results(w_all, v_all, codes, w, v, it, reason)

        def newton_solver(xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e,
                          prior_e):
            return _solve_one_entity_newton(
                xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e, prior_e,
                sub_dim=sub_dim,
                task=task,
                opt_config=opt_config,
                variance_computation=variance_computation,
                l2_weight=l2_weight,
                incremental_weight=incremental_weight,
            )

        w, v, it, reason = jax.vmap(newton_solver)(
            block.x_indices,
            block.x_values,
            block.labels,
            offsets,
            block.weights,
            block.penalty_mask,
            block.valid_mask,
            factors_sub,
            shifts_sub,
            block.intercept_slots,
            w0,
            prior,
        )
        return _scatter_results(w_all, v_all, codes, w, v, it, reason)

    def solver(xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e, prior_e):
        return _solve_one_entity(
            xi, xv, lb, off, wt, pm, vm, f, sh, islot, w0_e, prior_e,
            sub_dim=sub_dim,
            task=task,
            opt_config=opt_config,
            use_owlqn=use_owlqn,
            variance_computation=variance_computation,
            l1_weight=l1_weight,
            l2_weight=l2_weight,
            incremental_weight=incremental_weight,
        )

    w, v, it, reason = jax.vmap(solver)(
        block.x_indices,
        block.x_values,
        block.labels,
        offsets,
        block.weights,
        block.penalty_mask,
        block.valid_mask,
        factors_sub,
        shifts_sub,
        block.intercept_slots,
        w0,
        prior,
    )
    return _scatter_results(w_all, v_all, codes, w, v, it, reason)


def _scatter_results(w_all, v_all, codes, w, v, it, reason):
    """Pad to the table width and scatter one bucket's solutions in."""
    pad = w_all.shape[1] - w.shape[1]
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
        v = jnp.pad(v, ((0, 0), (0, pad)))
    w_all = w_all.at[codes].set(w)
    if v_all is not None:
        v_all = v_all.at[codes].set(v)
    return w_all, v_all, it, reason


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinate:
    """Per-entity coordinate over one random-effect type.

    Reference: algorithm/RandomEffectCoordinate.scala:38 (trainModel
    :234-300, scoring :314-366).
    """

    dataset: RandomEffectDataset
    task: TaskType
    config: GLMOptimizationConfiguration
    normalization: NormalizationContext = dataclasses.field(
        default_factory=NormalizationContext
    )
    # Incremental-training prior: a RandomEffectModel (with variances)
    # already remapped onto this dataset's entity/slot layout. Entities or
    # slots absent from it carry variance 0 and fall back to plain L2
    # (RandomEffectOptimizationProblem.scala:137-198 projected priors).
    prior: RandomEffectModel | None = None
    # Mixed-precision policy (ops/precision.py): "bfloat16" stores the
    # design slabs bf16 with f32 accumulators/state; "float32" (default)
    # is the historical path. A declared recompile key (PERFORMANCE.md).
    precision: str = "float32"

    def _dispatch_block(self, block, residuals, w0_full, w_all, v_all,
                        block_index=None):
        """Assemble and dispatch one bucket's ``_solve_block`` call.

        Shared by ``train`` (sequential scatter into the tables) and
        ``warmup_thunks`` (concurrent compile priming), so the jit call
        structure cannot drift between them. ``block_index`` keys the
        planner's host-side per-bucket tables (gram window bounds); both
        callers enumerate ``device_blocks()`` so the statics agree.
        """
        dtype = jnp.dtype(self.dataset.dtype)
        # Squared-loss subproblems are convex quadratics: solve them
        # exactly with one batched Cholesky instead of iterating
        # (identical optimum, ~100x fewer sequential device steps).
        # l2 > 0 guarantees X^T W X + diag(pen) is positive definite even
        # for entities with fewer rows than active features — without it
        # the normal equations can be singular and the iterative solver's
        # implicit regularization is the correct behavior.
        well_posed = (
            self.config.l1_weight == 0.0
            and self.config.l2_weight > 0.0
            and self.config.optimizer.box_constraints is None
            # With a prior, absent-feature slots are penalized by
            # incremental_weight * inv_prior_var instead of l2; at
            # incremental_weight == 0 the normal equations can be
            # singular for entities with fewer rows than features.
            and (self.prior is None
                 or self.config.incremental_weight > 0.0)
        )
        direct = well_posed and self.task == TaskType.LINEAR_REGRESSION
        # Smooth strictly-convex losses take the damped-Newton/IRLS
        # path: same optimum as the configured quasi-Newton solver, at
        # ~10x less sequential device depth (MXU-batched GEMM + [S,S]
        # Cholesky per iteration). Smoothed hinge is excluded — its
        # curvature approximation vanishes on flat segments.
        newton = well_posed and self.task in (
            TaskType.LOGISTIC_REGRESSION, TaskType.POISSON_REGRESSION
        )
        # Host-computed gram window bounds for this bucket (None when
        # the planner skipped them — small subspaces densify, lazy
        # datasets have no host slab view): the static coverage key of
        # the direct ELL gram route (_solve_direct_gram).
        gram_mults = None
        if block_index is not None:
            gm = getattr(self.dataset, "block_gram_mults", ())
            if block_index < len(gm):
                gram_mults = gm[block_index]
        # Scalars ride as host float32 jit operands (an eager
        # jnp.asarray would compile its own convert program per call
        # site on the TPU backend).
        return _solve_block(
            block,
            residuals,
            self.normalization.factors,
            self.normalization.shifts,
            w0_full,
            np.asarray(self.config.l1_weight, dtype=dtype),
            np.asarray(self.config.l2_weight, dtype=dtype),
            np.asarray(self.config.incremental_weight, dtype=dtype),
            None if self.prior is None
            else (self.prior.coefficients, self.prior.variances),
            w_all,
            v_all,
            sub_dim=block.sub_dim,
            task=self.task,
            opt_config=self.config.optimizer,
            use_owlqn=self.config.l1_weight != 0.0,
            variance_computation=self.config.variance_computation,
            direct=direct,
            newton=newton,
            precision=precision_mod.resolve(self.precision),
            gram_mults=gram_mults,
        )

    def warmup_thunks(self):
        """Zero-argument thunks that compile this coordinate's programs.

        One thunk per bucket solver plus one for the scorer; the estimator
        runs thunks from ALL coordinates on a thread pool so the XLA
        compiles overlap (~2.5x measured) instead of serializing through
        the first CD sweep. Results are discarded — only the jit cache
        entries matter.
        """
        ds = self.dataset
        dtype = jnp.dtype(ds.dtype)
        residuals = jnp.zeros(ds.num_rows, dtype)
        w0_full = jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
        v_all = (
            jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
            if self.config.variance_computation != VarianceComputationType.NONE
            else None
        )

        def block_thunk(block, idx):
            # w_all/v_all are DONATED by _solve_block: each thunk gets
            # its own fresh tables — reusing w0_full as w_all would
            # alias a donated buffer with a live operand, and a shared
            # v_all would be consumed by the first thunk to run.
            def thunk():
                w_tab = jnp.zeros_like(w0_full)
                v_tab = None if v_all is None else jnp.zeros_like(v_all)
                jax.block_until_ready(self._dispatch_block(
                    block, residuals, w0_full, w_tab, v_tab,
                    block_index=idx,
                )[0])

            return thunk

        def score_thunk():
            model = RandomEffectModel(
                coefficients=w0_full,
                random_effect_type=ds.config.random_effect_type,
                feature_shard_id=ds.config.feature_shard_id,
                task=self.task,
                proj_all=ds.proj_all,
                variances=None,
                entity_keys=ds.entity_keys,
            )
            jax.block_until_ready(self.score(model))

        return [
            block_thunk(b, i) for i, b in enumerate(ds.device_blocks())
        ] + [score_thunk]

    def train(
        self,
        residuals: Array | None = None,
        initial_model: RandomEffectModel | None = None,
        *,
        seed: int = 0,
    ) -> tuple[RandomEffectModel, RandomEffectTrainingStats]:
        ds = self.dataset
        dtype = jnp.dtype(ds.dtype)
        # Normalize the optional inputs to arrays: None vs array changes the
        # jit pytree structure, and CD's first iteration (no residuals, no
        # warm start) would otherwise compile a SECOND program per bucket
        # that is used exactly once. A zeros gather costs nothing; a
        # duplicate XLA compile costs seconds.
        if residuals is None:
            residuals = jnp.zeros(ds.num_rows, dtype)
        w0_full = (
            initial_model.coefficients if initial_model is not None
            else jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
        )
        w_all = jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
        v_all = (
            jnp.zeros((ds.num_entities, ds.max_sub_dim), dtype)
            if self.config.variance_computation != VarianceComputationType.NONE
            else None
        )
        # (device reason array, host real-entity mask) per block; fetched in
        # two coalesced transfers after all blocks are dispatched.
        reasons: list[tuple[Array, np.ndarray]] = []
        iters: list[Array] = []
        real_masks = [
            ds.real_entity_mask(i) for i in range(len(ds.blocks))
        ]

        if self.normalization.shifts is not None:
            # Shift normalization folds the shift mass into the intercept on
            # the coefficient round trip; every trained entity must have one
            # (the per-entity analog of NormalizationContext.__post_init__).
            for ints, real in zip(ds.block_intercepts_np, real_masks):
                if bool((np.asarray(ints)[real] < 0).any()):
                    raise ValueError(
                        "normalization with shifts requires every entity's "
                        "subspace to contain the intercept; build the "
                        "dataset with intercept_index set"
                    )

        if self.prior is not None and self.prior.variances is None:
            raise ValueError(
                "incremental training requires prior variances for "
                "every entity model (GameEstimator.scala:241-382)")

        # Feature slabs materialize on device once per dataset; per-solve
        # gathers shrink to the [B, R] residual rows (data/random_effect.py
        # device_blocks).
        for i, (block, real) in enumerate(
            zip(ds.device_blocks(), real_masks)
        ):
            w_all, v_all, it, reason = self._dispatch_block(
                block, residuals, w0_full, w_all, v_all, block_index=i
            )
            # Keep diagnostics on device; fetch once after the loop
            # (a per-block np.asarray would sync per block).
            reasons.append((reason, real))
            iters.append(it)

        model = RandomEffectModel(
            coefficients=w_all,
            random_effect_type=ds.config.random_effect_type,
            feature_shard_id=ds.config.feature_shard_id,
            task=self.task,
            proj_all=ds.proj_all,
            variances=v_all,
            entity_keys=ds.entity_keys,
        )
        # Diagnostics stay on device: the CD loop never reads them, and an
        # eager fetch here would sync the host to every block solve.
        stats = RandomEffectTrainingStats.from_device(
            [r for r, _ in reasons], iters, [real for _, real in reasons]
        )
        return model, stats

    def score(self, model: RandomEffectModel) -> Array:
        """Model contribution per canonical row (active + passive)."""
        return model.score_dataset(self.dataset)
